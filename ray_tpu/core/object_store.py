"""Object storage: per-process memory store + per-node shared-memory store.

Ref analogs:
 * MemoryStore — src/ray/core_worker/store_provider/memory_store/
   memory_store.h:42 (small objects live in the owner process; waiters are
   async futures).
 * ShmObjectStore — the plasma store
   (src/ray/object_manager/plasma/store.h:55) redesigned host-side: every
   sealed object is one named POSIX shm segment (mmap'd by any process on
   the node, zero-copy reads via pickle-5 buffer views). The directory +
   pinning + eviction live in the node manager; this class is the
   per-process mapping cache. A C++ arena allocator can replace the
   per-object segments without changing this interface.

Device arrays (jax.Array) do NOT pass through here — they stay in HBM and
move over ICI via the mesh/collective layer. This store is for host objects.
"""

from __future__ import annotations

import asyncio
import os
import threading
from multiprocessing import shared_memory, resource_tracker
from typing import Any

from ray_tpu._internal.ids import ObjectID
from ray_tpu._internal.serialization import deserialize, serialize, serialized_size

_logger = None


def _log():
    # lazy: setup_logger pulls config; this module is imported by every
    # process before config is necessarily finalized
    global _logger
    if _logger is None:
        from ray_tpu._internal.logging_utils import setup_logger

        _logger = setup_logger("object_store")
    return _logger


class _StoredObject:
    __slots__ = ("value", "is_exception")

    def __init__(self, value: Any, is_exception: bool = False):
        self.value = value
        self.is_exception = is_exception


class MemoryStore:
    """In-process store for small objects owned by this worker."""

    def __init__(self, loop: asyncio.AbstractEventLoop):
        self._loop = loop
        self._objects: dict[ObjectID, _StoredObject] = {}
        self._waiters: dict[ObjectID, list[asyncio.Future]] = {}

    def put(self, object_id: ObjectID, value: Any, is_exception: bool = False):
        obj = _StoredObject(value, is_exception)
        self._objects[object_id] = obj
        # no registered async waiter (the common case: getters are on
        # the sync fast lane or haven't arrived): skip the loop wake —
        # an off-loop put otherwise costs a self-pipe write + a loop
        # iteration PER completion. Safe against the register race:
        # wait_for re-checks the store AFTER appending its future.
        if object_id not in self._waiters:
            return

        def _wake():
            for fut in self._waiters.pop(object_id, []):
                if not fut.done():
                    fut.set_result(obj)
        # loop-affine fast path: puts from the completion path run on the
        # store's loop — waking inline skips a self-pipe write + handle
        if asyncio._get_running_loop() is self._loop:
            _wake()
        else:
            self._loop.call_soon_threadsafe(_wake)

    def contains(self, object_id: ObjectID) -> bool:
        return object_id in self._objects

    def get_if_exists(self, object_id: ObjectID) -> _StoredObject | None:
        return self._objects.get(object_id)

    async def wait_for(self, object_id: ObjectID) -> _StoredObject:
        obj = self._objects.get(object_id)
        if obj is not None:
            return obj
        fut = self._loop.create_future()
        self._waiters.setdefault(object_id, []).append(fut)
        # re-check: an off-loop put between the first check and the
        # append saw no waiter and skipped its wake
        obj = self._objects.get(object_id)
        if obj is not None:
            for fut in self._waiters.pop(object_id, []):
                if not fut.done():
                    fut.set_result(obj)
            return obj
        return await fut

    def delete(self, object_id: ObjectID):
        self._objects.pop(object_id, None)

    def __len__(self):
        return len(self._objects)


def _shm_name(object_id: ObjectID) -> str:
    # FULL hex (53 chars incl. prefix, well under shm NAME_MAX): return
    # ids of one task differ only in the trailing index suffix, so any
    # truncation collapses every return/stream item of a task onto ONE
    # segment (duplicate-create dedup then silently serves item 0's
    # payload for item N)
    return "rayt_" + object_id.hex()


def _unregister_tracker(shm: shared_memory.SharedMemory):
    # The resource tracker would unlink segments when *any* process exits;
    # lifetime is owned by the node manager instead (like plasma).
    try:
        resource_tracker.unregister(shm._name, "shared_memory")  # type: ignore[attr-defined]
    except Exception:
        pass


class ShmObjectStore:
    """Create/open node-local shared-memory objects by ObjectID."""

    def __init__(self):
        self._open: dict[ObjectID, shared_memory.SharedMemory] = {}
        # objects allocated but still being written (streamed pulls,
        # restores): hidden from contains_locally until seal
        self._unsealed: set[ObjectID] = set()
        # unlinked/released segments whose mappings are still pinned by
        # live zero-copy views: kept referenced (not in the cache) so the
        # mapping survives until the views die, then swept closed
        self._zombies: list[shared_memory.SharedMemory] = []
        # guards the _open cache: pin-driven release() now runs from
        # other threads concurrently with get_view/_mapping. RLock — a
        # GC firing ObjectRef.__del__ can re-enter the release path on
        # the same thread mid-critical-section
        self._map_lock = threading.RLock()
        # zombie lifecycle accounting for the observability layer:
        # parked = close() refused by live views, swept = later reclaimed
        self._zombies_parked = 0
        self._zombies_swept = 0

    def create_and_seal(self, object_id: ObjectID, value: Any) -> int:
        chunks = serialize(value)
        size = serialized_size(chunks)
        shm = shared_memory.SharedMemory(
            name=_shm_name(object_id), create=True, size=max(size, 1))
        _unregister_tracker(shm)
        off = 0
        buf = shm.buf
        for c in chunks:
            n = len(c) if isinstance(c, bytes) else c.nbytes
            buf[off:off + n] = bytes(c) if isinstance(c, bytes) else c
            off += n
        with self._map_lock:
            self._open[object_id] = shm
        return size

    def create_from_bytes(self, object_id: ObjectID, data: bytes,
                          hold: bool = False) -> int:
        """Seal a pre-serialized payload (used by node-to-node transfer).
        `hold` is a no-op here: per-object segments are never evicted.
        Duplicate creates (concurrent restores of the same object) keep
        the existing segment, matching the native arena's rc==-1."""
        return self.create_from_chunks(object_id, [data], len(data),
                                       hold=hold)

    def create_from_chunks(self, object_id: ObjectID, chunks, size: int,
                           hold: bool = False) -> int:
        if not self.create_unsealed(object_id, size):
            return size
        off = 0
        for c in chunks:
            n = len(c)
            self.write_at(object_id, off, c)
            off += n
        self.seal(object_id)
        return size

    # --------------------------------------------------- streaming creates
    @staticmethod
    def _unsealed_marker(object_id: ObjectID) -> str:
        # cross-process visibility: the native arena keeps kCreating state
        # in the shared header; this fallback store marks in-progress
        # writes with a sibling file so OTHER processes' contains_locally
        # can't attach a half-written segment by name
        return f"/dev/shm/{_shm_name(object_id)}.unsealed"

    def create_unsealed(self, object_id: ObjectID, size: int) -> bool:
        """Allocate an object to be filled by write_at + seal. False if
        the object already exists (created or being created elsewhere).

        The marker file is the CREATION LOCK (O_EXCL, written before the
        segment exists) so no other process can attach a half-written
        segment; it carries the writer pid so a crashed writer's stale
        marker is detected and cleaned instead of hiding the id forever.
        """
        marker = self._unsealed_marker(object_id)
        try:
            with open(marker, "x") as f:
                f.write(str(os.getpid()))
        except FileExistsError:
            return False  # another creator owns it (or stale: see below)
        try:
            shm = shared_memory.SharedMemory(
                name=_shm_name(object_id), create=True, size=max(size, 1))
        except FileExistsError:
            # sealed object already existed: our marker must not hide it
            try:
                os.remove(marker)
            except OSError:
                pass
            return False
        _unregister_tracker(shm)
        self._unsealed.add(object_id)
        with self._map_lock:
            self._open[object_id] = shm
        return True

    @staticmethod
    def _marker_stale(marker: str) -> bool:
        """True when the writer recorded in the marker is dead."""
        try:
            with open(marker) as f:
                pid = int(f.read().strip() or "0")
        except (OSError, ValueError):
            return False
        if pid <= 0:
            return True
        try:
            os.kill(pid, 0)
            return False
        except ProcessLookupError:
            return True
        except OSError:
            return False

    def write_at(self, object_id: ObjectID, offset: int, data):
        with self._map_lock:
            shm = self._open[object_id]
        n = len(data)
        shm.buf[offset:offset + n] = data

    def seal(self, object_id: ObjectID, hold: bool = False):
        self._unsealed.discard(object_id)
        try:
            os.remove(self._unsealed_marker(object_id))
        except OSError:
            pass

    def abort_unsealed(self, object_id: ObjectID):
        self._unsealed.discard(object_id)
        try:
            os.remove(self._unsealed_marker(object_id))
        except OSError:
            pass
        with self._map_lock:
            shm = self._open.pop(object_id, None)
        if shm is not None:
            try:
                shm.close()
                shm.unlink()
            except Exception:
                pass

    def release_create_ref(self, object_id: ObjectID):
        """Drop the creation mapping cached by create_from_chunks(
        hold=True): the segment is sealed and announced by now, and an
        executor keeping it would (a) leak the mapping until process
        exit and (b) read as a get-pin to the leak watchdog — every shm
        task return would be falsely flagged once the grace window
        passed, since the SUBMITTER owns the ref, not the executor. A
        later local get simply reopens the still-named segment."""
        self.release(object_id)

    def pin(self, object_id: ObjectID) -> bool:
        return True

    def unpin(self, object_id: ObjectID):
        pass

    def contains_locally(self, object_id: ObjectID) -> bool:
        if object_id in self._unsealed:
            return False
        if object_id in self._open:
            return True
        marker = self._unsealed_marker(object_id)
        if os.path.exists(marker):
            if not self._marker_stale(marker):
                return False  # another process is still writing it
            # the writer died mid-write: drop the partial so a re-pull
            # can recreate the object
            try:
                os.remove(marker)
            except OSError:
                pass
            try:
                stale = shared_memory.SharedMemory(
                    name=_shm_name(object_id))
                _unregister_tracker(stale)
                stale.close()
                stale.unlink()
            except FileNotFoundError:
                pass
            return False
        # probe WITHOUT caching: a cached mapping counts as a get-pin
        # (get_ref_counts), so a mere existence check — rt.wait from a
        # borrower that never gets the value — would otherwise hold the
        # segment forever and read as a watchdog leak. The probe handle
        # closes immediately (no view can have been exported from it,
        # so no orphan; actual reads cache via _mapping under the lock).
        try:
            shm = shared_memory.SharedMemory(name=_shm_name(object_id))
        except FileNotFoundError:
            return False
        _unregister_tracker(shm)
        shm.close()
        return True

    def _mapping(self, object_id: ObjectID) -> shared_memory.SharedMemory:
        with self._map_lock:
            shm = self._open.get(object_id)
            if shm is None:
                # open inside the lock: two threads double-opening would
                # orphan the loser's mapping (unclosable once views
                # export from it)
                shm = shared_memory.SharedMemory(name=_shm_name(object_id))
                _unregister_tracker(shm)
                self._open[object_id] = shm
            return shm

    def get_view(self, object_id: ObjectID, size: int) -> memoryview:
        """Zero-copy view of the sealed payload. The mapping is cached
        (the pin): it stays open until release(), and release() keeps it
        open for as long as any exported view is alive (BufferError
        tolerance). Raises FileNotFoundError if the segment is gone.

        The slice happens under _map_lock: release_create_ref (announce
        path) can release the creator's mapping concurrently with a
        get, and a close between _mapping() returning and .buf being
        sliced would hand back a dead buffer. Under the lock either the
        slice lands first (close then BufferError-parks as a zombie) or
        the release landed first and _mapping reopens fresh."""
        with self._map_lock:
            return self._mapping(object_id).buf[:size]

    def get(self, object_id: ObjectID, size: int) -> Any:
        """Zero-copy deserialize; the mapping stays cached so buffer views
        remain valid while this process holds the ref."""
        return deserialize(self.get_view(object_id, size))

    def read_bytes(self, object_id: ObjectID, size: int) -> bytes:
        with self._map_lock:  # see get_view: slice races release paths
            view = self._mapping(object_id).buf[:size]
        return bytes(view)

    def read_range_view(self, object_id: ObjectID, size: int, offset: int,
                        length: int):
        """(view, release_cb) for the push side of chunked transfer: the
        chunk aliases the cached mapping, no copy. release_cb is None —
        the mapping stays cached (same lifetime as every other read) and
        unlink's BufferError tolerance covers views still in flight."""
        with self._map_lock:  # see get_view: slice races release paths
            return (self._mapping(object_id).buf[offset:offset + length],
                    None)

    @staticmethod
    def _silence_del(shm: shared_memory.SharedMemory):
        """A mapping with live exported views cannot close; neutralize the
        instance's close so __del__ at interpreter shutdown doesn't spew
        'Exception ignored ... BufferError' (the map dies with the
        process either way). Only applied at store close() — while the
        store lives, zombies keep their real close so the sweep can
        reclaim them once their views die."""
        shm.close = lambda: None  # type: ignore[method-assign]

    def _park_zombie(self, shm: shared_memory.SharedMemory):
        """Record a mapping whose close() was refused by live views; the
        sweep reclaims it once they die. Counted + named at DEBUG so a
        store that accumulates zombies is diagnosable from logs and the
        rayt_object_store_zombie_* gauges instead of failing silently."""
        with self._map_lock:
            self._zombies.append(shm)
            self._zombies_parked += 1
        _log().debug("segment %s parked as zombie (live views pin the "
                     "mapping past its unlink)", shm.name)

    def _sweep_zombies(self):
        """Retry closing unlinked-but-pinned mappings: views that were
        in flight at unlink time (RawView pushes, spill writes) die
        shortly after, and the mapping must actually be reclaimed then —
        not accumulate until process exit."""
        if not self._zombies:
            return
        with self._map_lock:  # appends race this sweep from other threads
            zombies, self._zombies = self._zombies, []
            alive = []
            swept = []
            for shm in zombies:
                try:
                    shm.close()
                except BufferError:
                    alive.append(shm)
                else:
                    swept.append(shm.name)
                    self._zombies_swept += 1
            self._zombies.extend(alive)
        for name in swept:
            _log().debug("zombie segment %s reclaimed (views died)", name)

    def release(self, object_id: ObjectID):
        self._sweep_zombies()
        with self._map_lock:
            shm = self._open.pop(object_id, None)
        if shm is not None:
            try:
                shm.close()
            except BufferError:
                # Views alive. close() already dropped shm._buf before
                # the mmap refused to unmap, so this instance can never
                # serve another read — park it as a zombie (mapping
                # survives until the views die); a later get reopens the
                # still-named segment fresh. Re-caching it would poison
                # every subsequent access with _buf=None.
                self._park_zombie(shm)

    def unlink(self, object_id: ObjectID):
        """Destroy the segment (node-manager only, when refcount hits 0).

        Order matters for the zero-copy contract: the NAME is unlinked
        first (new opens fail immediately; existing mappings — live
        views — stay valid until their holders drop, plasma's delete
        semantics), and only then is the local mapping closed. A
        BufferError on close (views alive) must never skip the unlink,
        or the segment would leak on /dev/shm for the node's lifetime."""
        self._sweep_zombies()
        with self._map_lock:
            shm = self._open.pop(object_id, None)
        if shm is None:
            try:
                shm = shared_memory.SharedMemory(name=_shm_name(object_id))
            except FileNotFoundError:
                return
            _unregister_tracker(shm)
        # shm.unlink() sends an unregister; balance the one we already
        # sent at open/create time by re-registering first.
        try:
            resource_tracker.register(shm._name, "shared_memory")  # type: ignore[attr-defined]
        except Exception:
            pass
        try:
            shm.unlink()
        except FileNotFoundError:
            _unregister_tracker(shm)
        try:
            shm.close()
        except BufferError:
            # live zero-copy views: keep the (now anonymous) mapping
            # referenced so it survives until the views die; swept (and
            # actually closed) by the next release/unlink once they do
            self._park_zombie(shm)

    def drop_cached_mapping(self, object_id: ObjectID):
        """Release the cached mapping when the owner frees the object.
        The create path caches a mapping that no get-pin tracks; without
        this the creating process keeps the (already-unlinked) segment
        mapped until exit. Live views are safe: release() parks a
        view-pinned mapping as a zombie instead of unmapping it."""
        self.release(object_id)

    # ------------------------------------------------------ observability
    def get_ref_counts(self) -> dict[ObjectID, int]:
        """Live get-pin view for the object-state report / leak
        watchdog: in this store the cached mapping IS the pin, so every
        sealed entry in the cache counts as one held ref."""
        with self._map_lock:
            return {oid: 1 for oid in self._open
                    if oid not in self._unsealed}

    def stats(self) -> dict:
        """Segment-level snapshot for the rayt_object_store_* gauges and
        node object reports (mirrors NativeArenaStore.stats())."""
        with self._map_lock:
            zombie_bytes = 0
            for shm in self._zombies:
                try:
                    zombie_bytes += shm.size
                except Exception:
                    pass
            return {
                "segments": len(self._open),
                "unsealed": len(self._unsealed),
                "zombie_segments": len(self._zombies),
                "zombie_bytes": zombie_bytes,
                "zombies_parked_total": self._zombies_parked,
                "zombies_swept_total": self._zombies_swept,
                "fallback_objects": 0,
                "fallback_bytes": 0,
            }

    def close(self):
        with self._map_lock:
            oids = list(self._open)
        for oid in oids:
            self.release(oid)  # view-pinned mappings become zombies
        self._sweep_zombies()
        for shm in self._zombies:
            self._silence_del(shm)  # still pinned at shutdown: quiet exit
        self._zombies.clear()


def make_shm_store(node_id):
    """Node-scoped store factory: the C++ arena store (plasma-equivalent,
    ray_tpu/_native/shm_store.cpp) when the toolchain can build it, else
    the per-object-segment fallback. All processes on a node derive the
    same arena name from the node id."""
    import os

    from ray_tpu._internal.config import get_config
    from ray_tpu._internal.logging_utils import setup_logger

    logger = setup_logger("object_store")
    mode = os.environ.get("RAYT_SHM_MODE", "")
    if mode != "segments" and not os.environ.get("RAYT_DISABLE_NATIVE_SHM"):
        try:
            from ray_tpu._native import NativeArenaStore

            capacity = get_config().object_store_memory
            if not capacity:
                try:
                    import psutil

                    capacity = int(psutil.virtual_memory().total * 0.2)
                except Exception:
                    capacity = 2 << 30
                capacity = min(capacity, 8 << 30)
            return NativeArenaStore("raytshm_" + node_id.hex()[:16],
                                    capacity)
        except Exception as e:
            if mode == "native":
                # the node manager opened the arena: a per-segment fallback
                # here would silently diverge from every other process on
                # the node — fail loudly instead
                raise RuntimeError(
                    f"node uses the native arena store but this process "
                    f"could not open it: {e!r}") from e
            logger.warning(
                "native shm arena unavailable (%r); falling back to "
                "per-object segments", e)
    return ShmObjectStore()
