"""ActorPool: schedule a stream of work over a fixed set of actors (ref
analog: python/ray/util/actor_pool.py:13)."""

from __future__ import annotations

from typing import Any, Callable, Iterable


class ActorPool:
    def __init__(self, actors: list):
        self._idle = list(actors)
        self._future_to_actor: dict = {}
        self._index_to_future: dict[int, Any] = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: list[tuple[Callable, Any]] = []

    def submit(self, fn: Callable, value: Any):
        """fn(actor, value) -> ObjectRef, e.g.
        pool.submit(lambda a, v: a.double.remote(v), 1)."""
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return self._next_return_index < self._next_task_index or bool(
            self._pending_submits)

    def get_next(self, timeout: float | None = None) -> Any:
        """Next result in submission order."""
        import ray_tpu as rt

        if not self.has_next():
            raise StopIteration("no more results")
        idx = self._next_return_index
        while idx not in self._index_to_future:
            self._drain_one(timeout)
        future = self._index_to_future.pop(idx)
        self._next_return_index += 1
        value = rt.get(future, timeout=timeout)
        self._return_actor_for(future)
        return value

    def get_next_unordered(self, timeout: float | None = None) -> Any:
        """Next result in completion order."""
        import ray_tpu as rt

        if not self.has_next():
            raise StopIteration("no more results")
        while not self._future_to_actor:
            self._drain_one(timeout)
        ready, _ = rt.wait(list(self._future_to_actor), num_returns=1,
                           timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        future = ready[0]
        idx, _ = self._future_to_actor[future]
        self._index_to_future.pop(idx, None)
        # keep return index monotone past consumed entries
        self._next_return_index = max(self._next_return_index, idx + 1)
        value = rt.get(future)
        self._return_actor_for(future)
        return value

    def _drain_one(self, timeout: float | None):
        if not self._pending_submits:
            raise RuntimeError("result requested but no work outstanding")
        raise RuntimeError("internal: pending submits without idle actors "
                           "should be flushed by _return_actor_for")

    def _return_actor_for(self, future):
        _, actor = self._future_to_actor.pop(future)
        if self._pending_submits:
            fn, value = self._pending_submits.pop(0)
            new_future = fn(actor, value)
            self._future_to_actor[new_future] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = new_future
            self._next_task_index += 1
        else:
            self._idle.append(actor)

    def map(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def has_free(self) -> bool:
        return bool(self._idle)

    def pop_idle(self):
        return self._idle.pop() if self._idle else None

    def push(self, actor):
        self._idle.append(actor)
