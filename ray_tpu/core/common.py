"""Shared control-plane types: addresses, task specs, resources, options.

TPU-native analog of ref src/ray/common/task/task_spec.h:258 and
python/ray/_private/ray_option_utils.py. These are plain dataclasses carried
over the RPC layer (pickle-5), the one-language replacement for the
reference's protobuf TaskSpec.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

from ray_tpu._internal.ids import (ActorID, JobID, NodeID, ObjectID,
                                   PlacementGroupID, TaskID, WorkerID)


@dataclasses.dataclass(frozen=True)
class Address:
    """Where to reach a process's RPC server."""
    host: str
    port: int

    def key(self) -> str:
        return f"{self.host}:{self.port}"

    def __reduce__(self):
        # positional tuple: pickled on every TaskSpec/WorkerInfo on the
        # wire; the default dataclass reduce ships field-name strings
        return (Address, (self.host, self.port))


@dataclasses.dataclass
class ResourceSpec:
    """Resource demand of a task/actor. TPU chips are first-class: `tpu`
    counts chips on the host; custom covers pod-slice head resources like
    'TPU-v5p-16-head' (ref: python/ray/_private/accelerators/tpu.py:197)."""
    num_cpus: float = 1.0
    tpu: float = 0.0
    memory: float = 0.0
    custom: dict[str, float] = dataclasses.field(default_factory=dict)

    def to_demand(self) -> dict[str, float]:
        d = {}
        if self.num_cpus:
            d["CPU"] = self.num_cpus
        if self.tpu:
            d["TPU"] = self.tpu
        if self.memory:
            d["memory"] = self.memory
        d.update(self.custom)
        return d


@dataclasses.dataclass
class TaskOptions:
    resources: ResourceSpec = dataclasses.field(default_factory=ResourceSpec)
    max_retries: int = -1            # -1 = use config default
    retry_exceptions: bool = False
    num_returns: int = 1
    name: str = ""
    scheduling_strategy: Any = None  # None | "SPREAD" | PlacementGroupSchedulingStrategy
    runtime_env: dict | None = None
    # jax.Array returns stay device-resident in the executing worker
    # (ref analog: dag nodes annotated with_tensor_transport)
    tensor_transport: bool = False


@dataclasses.dataclass
class ActorOptions:
    resources: ResourceSpec = dataclasses.field(default_factory=ResourceSpec)
    max_restarts: int = 0
    max_task_retries: int = 0
    name: str = ""                   # named actor (GCS-registered)
    namespace: str = ""
    lifetime: str = ""               # "" | "detached"
    max_concurrency: int = 1
    scheduling_strategy: Any = None
    runtime_env: dict | None = None


@dataclasses.dataclass
class TaskSpec:
    """Everything a worker needs to run one task (ref: task_spec.h:258)."""
    task_id: TaskID
    job_id: JobID
    name: str
    # Pickled function (normal task) or (method name, args) for actor tasks.
    function_blob: bytes | None
    args: list[Any]                  # mix of inline values and ObjectRefMeta
    kwargs: dict[str, Any]
    num_returns: int
    resources: dict[str, float]
    owner: "WorkerInfo"
    max_retries: int = 0
    retry_exceptions: bool = False
    # Current attempt number (0-based), set by the submitter before each
    # (re)dispatch so the executing worker's lifecycle events carry it —
    # the GCS task manager resolves a retried task's final verdict from
    # the LATEST attempt (ref: task attempt in gcs_task_manager.h).
    attempt: int = 0
    # Actor-task fields:
    actor_id: ActorID | None = None
    method_name: str = ""
    seq_no: int = -1                 # per-caller ordering for actor tasks
    # Actor-creation fields:
    is_actor_creation: bool = False
    actor_options: ActorOptions | None = None
    scheduling_strategy: Any = None
    # Packaged runtime env (see _internal/runtime_env.py), applied by the
    # executing worker before the function/actor-ctor runs.
    runtime_env: dict | None = None
    # jax.Array returns stay in the executing worker's device memory and
    # the owner records a device-object ref (core/device_objects.py).
    tensor_transport: bool = False
    # W3C traceparent carrier (ref: _private/tracing _inject_tracing):
    # links the executing worker's OTel span to the submitter's trace.
    trace_ctx: dict | None = None
    # Function-table id (core/function_table.py): when set, the code
    # blob travels once per worker connection / via GCS KV instead of
    # riding every spec; function_blob then only carries the piggybacked
    # first-push copy (None on all later pushes).
    function_id: str | None = None

    def __reduce__(self):
        # a spec crosses the wire on EVERY submit: a positional tuple
        # (fields in declaration order) pickles ~2x smaller/faster than
        # the default dataclass __dict__ with its per-field name strings
        return (TaskSpec, (
            self.task_id, self.job_id, self.name, self.function_blob,
            self.args, self.kwargs, self.num_returns, self.resources,
            self.owner, self.max_retries, self.retry_exceptions,
            self.attempt, self.actor_id, self.method_name, self.seq_no,
            self.is_actor_creation, self.actor_options,
            self.scheduling_strategy, self.runtime_env,
            self.tensor_transport, self.trace_ctx, self.function_id))


@dataclasses.dataclass(frozen=True)
class WorkerInfo:
    worker_id: WorkerID
    node_id: NodeID
    address: Address                 # the worker's own RPC server
    # direct-call endpoint (core/direct.py): 0 = none (driver processes,
    # pre-upgrade workers). Owners push eligible tasks here, skipping
    # the asyncio stack on both sides of the round-trip.
    direct_port: int = 0

    def __reduce__(self):
        return (WorkerInfo, (self.worker_id, self.node_id, self.address,
                             self.direct_port))


@dataclasses.dataclass
class NodeInfo:
    node_id: NodeID
    address: Address                 # node manager RPC server
    resources_total: dict[str, float]
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    alive: bool = True
    # TPU topology hints for slice-aware gang scheduling:
    slice_name: str = ""
    slice_worker_index: int = -1


class ActorState:
    PENDING = "PENDING_CREATION"
    ALIVE = "ALIVE"
    RESTARTING = "RESTARTING"
    DEAD = "DEAD"


@dataclasses.dataclass
class ActorInfo:
    actor_id: ActorID
    name: str
    namespace: str
    state: str
    address: Address | None          # actor worker RPC server when ALIVE
    worker_id: WorkerID | None
    node_id: NodeID | None
    num_restarts: int = 0
    max_restarts: int = 0
    death_cause: str = ""
    class_name: str = ""


@dataclasses.dataclass
class ObjectMeta:
    """Owner-side record of where an object lives."""
    object_id: ObjectID
    size: int = -1                   # -1 = unknown/pending
    inline: bool = False             # small object stored in owner memory
    in_shm: bool = False
    node_ids: list[NodeID] = dataclasses.field(default_factory=list)
    error: Any = None                # stored exception, if task failed
    # Device-resident object (payload = jax.Array in the holder worker
    # process's HBM; see core/device_objects.py). holder is a WorkerInfo.
    in_device: bool = False
    holder: Any = None


@dataclasses.dataclass
class PlacementGroupSchedulingStrategy:
    placement_group_id: PlacementGroupID
    bundle_index: int = -1           # -1 = any bundle


@dataclasses.dataclass
class NodeAffinitySchedulingStrategy:
    node_id: NodeID
    soft: bool = False


@dataclasses.dataclass
class NodeLabelSchedulingStrategy:
    """Schedule onto nodes by label (ref analog:
    node_label_scheduling_strategy in scheduling/policy/). `hard` labels
    must ALL match for a node to be feasible; `soft` labels rank matching
    nodes first but don't exclude others."""
    hard: dict = dataclasses.field(default_factory=dict)
    soft: dict = dataclasses.field(default_factory=dict)


def now() -> float:
    return time.time()


class RayTpuError(Exception):
    """Base class for framework errors (ref analog: RayError hierarchy)."""


class TaskError(RayTpuError):
    """Wraps an application exception raised in a task; re-raised on get."""

    def __init__(self, cause: BaseException, task_name: str = "",
                 remote_traceback: str = ""):
        super().__init__(f"task {task_name!r} failed: {cause!r}")
        self.cause = cause
        self.remote_traceback = remote_traceback


class WorkerCrashedError(RayTpuError):
    pass


class ActorDiedError(RayTpuError):
    def __init__(self, actor_id, cause: str = ""):
        super().__init__(f"actor {actor_id} died: {cause}")
        self.actor_id = actor_id
        self.cause = cause


class ObjectLostError(RayTpuError):
    pass


class TaskCancelledError(RayTpuError):
    """Raised by get() on a ref whose task was cancelled (ref analog:
    ray.exceptions.TaskCancelledError via ray.cancel)."""
    pass


class GetTimeoutError(RayTpuError):
    pass
