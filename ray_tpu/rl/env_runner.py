"""EnvRunner actor — CPU sampling fleet (ref analog:
rllib/env/single_agent_env_runner.py:64; episodes stream back as numpy
trajectory dicts, weights arrive as object-store refs broadcast by the
algorithm, exactly the reference's weight-sync pattern)."""

from __future__ import annotations

from typing import Any, Optional

import numpy as np


class EnvRunner:
    def __init__(self, env_name: str, num_envs: int, seed: int,
                 module_cfg_blob: bytes,
                 connector_blob: bytes | None = None):
        from ray_tpu._internal.spawn import wait_site_ready

        wait_site_ready()
        import cloudpickle
        import jax

        jax.config.update("jax_platforms", "cpu")  # sampling is host-side
        from ray_tpu.rl.connectors import default_env_to_module
        from ray_tpu.rl.env import make_vector_env

        self.env = make_vector_env(env_name, num_envs, seed)
        self.module_cfg = cloudpickle.loads(module_cfg_blob)
        # env->module connector pipeline (ref: connector_v2.py:31): the
        # same transforms run here at sampling time and in the learner
        self._to_module = (cloudpickle.loads(connector_blob)
                           if connector_blob is not None
                           else default_env_to_module(self.module_cfg))
        self._key = jax.random.PRNGKey(seed)
        self._obs = self._to_module(self.env.reset(seed))
        self._params = None
        # per-env running episode returns (for metrics)
        self._ep_return = np.zeros(num_envs, np.float32)
        self._completed: list[float] = []

    def set_weights(self, params) -> bool:
        self._params = params
        return True

    def sample_dag(self, weights, num_steps: int) -> dict:
        """Compiled-DAG tick (Podracer Sebulba shape): fresh weights ride
        the DAG's input channel edge when the learner broadcast them this
        tick (None = keep sampling with the current, possibly stale,
        weights — IMPALA's defining asynchrony).

        The weights are COPIED out of the channel: zero-copy reads alias
        the input ring slot, and params held across ticks would pin it
        past the ring's capacity (the slot-pin rule's copy-on-hold
        requirement)."""
        if weights is not None:
            import jax

            self.set_weights(jax.tree.map(lambda x: np.array(x), weights))
        return self.sample(num_steps)

    def sample(self, num_steps: int) -> dict:
        """Rollout num_steps per env; returns flat [T, N, ...] arrays plus
        completed-episode returns for metrics."""
        import jax

        from ray_tpu.rl import module as rlm

        assert self._params is not None, "set_weights first"
        T, N = num_steps, self.env.num_envs
        # buffer shape follows the CONNECTOR OUTPUT (self._obs already
        # went through the env->module pipeline, which may reshape)
        obs_buf = np.zeros((T, N) + tuple(np.shape(self._obs)[1:]),
                           np.float32)
        act_buf = np.zeros((T, N), np.int32)
        logp_buf = np.zeros((T, N), np.float32)
        val_buf = np.zeros((T, N), np.float32)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.bool_)
        trunc_val_buf = np.zeros((T, N), np.float32)
        pending_trunc: list[tuple] = []  # (t, env idxs, final obs rows)
        for t in range(T):
            self._key, sub = jax.random.split(self._key)
            action, logp, value = rlm.sample_actions(
                self._params, self._obs, sub)
            obs_buf[t] = self._obs
            act_buf[t] = action
            logp_buf[t] = logp
            val_buf[t] = value
            (raw_obs, reward, terminated, truncated,
             final_obs) = self.env.step(action)
            self._obs = self._to_module(raw_obs)
            final_obs = self._to_module(final_obs)
            rew_buf[t] = reward
            truncated = truncated & ~terminated
            done = terminated | truncated
            done_buf[t] = done
            if truncated.any():
                idxs = np.nonzero(truncated)[0]
                pending_trunc.append((t, idxs, final_obs[idxs]))
            self._ep_return += reward
            for i in np.nonzero(done)[0]:
                self._completed.append(float(self._ep_return[i]))
                self._ep_return[i] = 0.0
        import jax.numpy as jnp

        # bootstrap value for the final observation
        _, last_value = rlm.forward(self._params, jnp.asarray(self._obs))
        # truncated (not terminated) episodes bootstrap with V(final_obs)
        # rather than 0 — rllib's truncation semantics (ref:
        # rllib postprocessing of truncated episodes)
        if pending_trunc:
            cat = np.concatenate([rows for _, _, rows in pending_trunc])
            _, vals = rlm.forward(self._params, jnp.asarray(cat))
            vals = np.asarray(vals)
            i = 0
            for t, idxs, rows in pending_trunc:
                trunc_val_buf[t, idxs] = vals[i:i + len(idxs)]
                i += len(idxs)
        completed, self._completed = self._completed, []
        return {
            "obs": obs_buf, "actions": act_buf, "logp": logp_buf,
            "values": val_buf, "rewards": rew_buf, "dones": done_buf,
            "trunc_values": trunc_val_buf,
            "last_value": np.asarray(last_value),
            "last_obs": np.asarray(self._obs),  # V-trace bootstrap input
            "episode_returns": completed,
        }

    def ping(self) -> bool:
        return True
