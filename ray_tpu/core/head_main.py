"""Head process: GCS + the head node's manager in one asyncio process
(ref analog: `ray start --head` spawning gcs_server + raylet; merged here
because both are asyncio services and separate daemons buy nothing on a
single host — multi-node tests spawn extra node managers via
cluster_utils).

Prints one JSON line with the bound ports on stdout, then serves forever.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys


async def run(args):
    from ray_tpu._internal.ids import NodeID
    from ray_tpu.core.common import Address
    from ray_tpu.core.gcs import GcsServer
    from ray_tpu.core.node_manager import NodeManager

    gcs = GcsServer(persist_path=args.persist_path or None)
    gcs_port = await gcs.start(port=args.gcs_port)
    nm = None
    if args.gcs_only:
        print(json.dumps({"gcs_port": gcs_port, "nm_port": -1,
                          "node_id": None}), flush=True)
    else:
        resources = json.loads(args.resources)
        nm = NodeManager(
            node_id=NodeID.random(), resources=resources,
            gcs_address=Address("127.0.0.1", gcs_port),
            labels={"head": "1"})
        addr = await nm.start()
        print(json.dumps({"gcs_port": gcs_port, "nm_port": addr.port,
                          "node_id": nm.node_id.hex()}), flush=True)
    # SIGTERM must run the shutdown path (terminate pool workers) — the
    # default handler would kill this process and orphan every worker.
    import signal

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass
    try:
        await stop.wait()
    finally:
        if nm is not None:
            await nm.stop()
        await gcs.stop()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--gcs-port", type=int, default=0)
    p.add_argument("--resources", type=str, default="{}")
    p.add_argument("--persist-path", type=str, default="")
    p.add_argument("--gcs-only", action="store_true")
    args = p.parse_args()
    try:
        asyncio.run(run(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
