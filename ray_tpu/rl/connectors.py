"""Connector-v2 pipelines (ref analog: rllib/connectors/connector_v2.py:31
`ConnectorV2` + connector_pipeline_v2.py:19 `ConnectorPipelineV2`).

Connectors are the composable data-transform stages between environment
and module (env→module: what the runner feeds the policy) and between
episodes and learner (learner pipeline: what the update consumes). Each
connector is a picklable callable `(data, ctx) -> data`; a pipeline
chains them. Runners and learners take pipelines as config so
preprocessing (normalization, dtype casts, frame ops) is declared once
and runs identically at sampling and training time.
"""

from __future__ import annotations

from typing import Any

import numpy as np


class Connector:
    """One transform stage. `data` is a dict of arrays; `ctx` carries
    static info (module config, env spec)."""

    def __call__(self, data: Any, ctx: dict | None = None) -> Any:
        raise NotImplementedError

    def __repr__(self):
        return type(self).__name__


class ConnectorPipeline(Connector):
    def __init__(self, connectors: list | None = None):
        self.connectors = list(connectors or [])

    def append(self, connector: Connector) -> "ConnectorPipeline":
        self.connectors.append(connector)
        return self

    def prepend(self, connector: Connector) -> "ConnectorPipeline":
        self.connectors.insert(0, connector)
        return self

    def __call__(self, data, ctx=None):
        for c in self.connectors:
            data = c(data, ctx)
        return data

    def __repr__(self):
        return f"ConnectorPipeline({self.connectors})"


# ----------------------------------------------------- env->module stages
class CastF32(Connector):
    """Observations to float32 (uint8 pixel envs, float64 physics)."""

    def __call__(self, obs, ctx=None):
        return np.asarray(obs, np.float32)


class NormalizeImage(Connector):
    """Integer pixels ([0, 255] uint8 and friends) -> [0, 1] floats.
    Keyed off the DTYPE, not the frame content: a near-black uint8 frame
    must scale exactly like a bright one, or the policy sees the same
    intensity at two scales. Float inputs pass through unchanged."""

    def __init__(self, scale: float = 255.0):
        self.scale = scale

    def __call__(self, obs, ctx=None):
        obs = np.asarray(obs)
        is_int = np.issubdtype(obs.dtype, np.integer)
        obs = obs.astype(np.float32)
        if is_int:
            obs = obs / self.scale
        return obs


class FlattenObs(Connector):
    """[B, ...] -> [B, prod(...)] for MLP modules on structured obs."""

    def __call__(self, obs, ctx=None):
        obs = np.asarray(obs)
        return obs.reshape(obs.shape[0], -1)


# ------------------------------------------------------- learner stages
class BatchCastF32(Connector):
    """Learner-side: cast the float trajectory arrays of a batch dict."""

    KEYS = ("obs", "rewards", "logp", "trunc_values", "last_obs")

    def __call__(self, batch: dict, ctx=None):
        for k in self.KEYS:
            if k in batch:
                batch[k] = np.asarray(batch[k], np.float32)
        return batch


def default_env_to_module(module_cfg) -> ConnectorPipeline:
    """Image modules normalize pixels; vector modules just cast (ref:
    the default env-to-module pipeline assembled in connector_v2)."""
    from ray_tpu.rl.module import CNNModuleConfig

    if isinstance(module_cfg, CNNModuleConfig):
        return ConnectorPipeline([NormalizeImage()])
    return ConnectorPipeline([CastF32()])


def default_learner_pipeline(module_cfg) -> ConnectorPipeline:
    return ConnectorPipeline([BatchCastF32()])
