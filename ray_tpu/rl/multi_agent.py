"""Multi-agent RL: env API, env runner, and PPO driver (ref analogs:
rllib/env/multi_agent_env.py + multi_agent_env_runner.py,
core/rl_module/multi_rl_module.py MultiRLModule, and the
policy_mapping_fn config surface of algorithm_config.py).

Design: a MultiAgentVectorEnv steps ALL agents in lockstep over N
vectorized env copies (dict-of-arrays per agent — the vectorized analog
of the reference's per-agent obs dicts). A policy_mapping_fn assigns
each agent_id to a policy_id; the runner batches every agent of one
policy into a single forward pass, and the driver trains one JaxLearner
per policy on that policy's combined (agent x env) streams. Each
(agent, env) column is an independent experience stream, so GAE and
minibatching reuse the single-agent code unchanged.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Optional

import numpy as np

import ray_tpu as rt
from ray_tpu.rl.actor_manager import FaultTolerantActorManager
from ray_tpu.rl.env import CartPoleVectorEnv
from ray_tpu.rl.learner import (JaxLearner, PPOLearnerConfig,
                                build_ppo_batch)
from ray_tpu.rl.module import MLPModuleConfig


class MultiAgentVectorEnv:
    """N lockstep copies of a multi-agent episode. All dicts are keyed
    by agent_id; every agent reports every tick (ref:
    multi_agent_env.py, vectorized)."""

    agent_ids: tuple[str, ...]
    num_envs: int

    def observation_size(self, agent_id: str) -> int:
        raise NotImplementedError

    def num_actions(self, agent_id: str) -> int:
        raise NotImplementedError

    def reset(self, seed: int | None = None) -> dict[str, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: dict[str, np.ndarray]):
        """-> (obs, rewards, terminated, truncated, final_obs), each a
        dict agent_id -> [N, ...] array, with auto-reset semantics
        matching VectorEnv.step."""
        raise NotImplementedError


class MultiAgentCartPole(MultiAgentVectorEnv):
    """K independent cart-poles sharing one vectorized env — the
    reference's standard multi-agent smoke env (rllib
    examples/envs/classes/multi_agent/: MultiAgentCartPole). Each agent
    runs its own episode stream; policy mapping decides who controls
    which pole."""

    def __init__(self, num_envs: int = 8, seed: int = 0,
                 num_agents: int = 2):
        self.num_envs = num_envs
        self.agent_ids = tuple(f"agent_{i}" for i in range(num_agents))
        self._envs = {
            aid: CartPoleVectorEnv(num_envs, seed + 97 * i)
            for i, aid in enumerate(self.agent_ids)}

    def observation_size(self, agent_id: str) -> int:
        return self._envs[agent_id].observation_size

    def num_actions(self, agent_id: str) -> int:
        return self._envs[agent_id].num_actions

    def reset(self, seed=None):
        return {aid: env.reset(None if seed is None else seed + 31 * i)
                for i, (aid, env) in enumerate(self._envs.items())}

    def step(self, actions):
        obs, rew, term, trunc, final = {}, {}, {}, {}, {}
        for aid, env in self._envs.items():
            (obs[aid], rew[aid], term[aid], trunc[aid],
             final[aid]) = env.step(actions[aid])
        return obs, rew, term, trunc, final


_MA_ENV_REGISTRY: dict[str, Callable] = {
    "MultiAgentCartPole": MultiAgentCartPole,
}


def register_multi_agent_env(name: str, creator: Callable) -> None:
    """creator(num_envs, seed, **cfg) -> MultiAgentVectorEnv."""
    _MA_ENV_REGISTRY[name] = creator


def make_multi_agent_env(name: str, num_envs: int, seed: int = 0,
                         **env_cfg) -> MultiAgentVectorEnv:
    if name not in _MA_ENV_REGISTRY:
        raise KeyError(f"unknown multi-agent env {name!r}; "
                       "register_multi_agent_env() it first")
    return _MA_ENV_REGISTRY[name](num_envs, seed, **env_cfg)


class MultiAgentEnvRunner:
    """Sampling actor (ref: multi_agent_env_runner.py): one forward pass
    per POLICY per step (all of that policy's agents batched together),
    per-policy trajectory dicts out — shaped exactly like the
    single-agent runner's so the learner stack is reused unchanged."""

    def __init__(self, env_name: str, num_envs: int, seed: int,
                 module_cfg_blob: bytes, mapping_blob: bytes,
                 env_cfg_blob: bytes | None = None):
        from ray_tpu._internal.spawn import wait_site_ready

        wait_site_ready()
        import cloudpickle
        import jax

        jax.config.update("jax_platforms", "cpu")  # sampling is host-side
        env_cfg = (cloudpickle.loads(env_cfg_blob)
                   if env_cfg_blob is not None else {})
        self.env = make_multi_agent_env(env_name, num_envs, seed,
                                        **env_cfg)
        self.module_cfgs: dict = cloudpickle.loads(module_cfg_blob)
        self.policy_mapping: Callable = cloudpickle.loads(mapping_blob)
        # policy -> the agents it controls, in a FIXED order (stream
        # layout: columns [agent0_env0..agent0_envN, agent1_env0..])
        self.policy_agents: dict[str, list[str]] = {}
        for aid in self.env.agent_ids:
            self.policy_agents.setdefault(
                self.policy_mapping(aid), []).append(aid)
        self._key = jax.random.PRNGKey(seed)
        self._params: dict | None = None
        obs = self.env.reset(seed)
        self._obs = {p: self._cat(obs, agents)
                     for p, agents in self.policy_agents.items()}
        n_streams = {p: num_envs * len(a)
                     for p, a in self.policy_agents.items()}
        self._ep_return = {p: np.zeros(n, np.float32)
                           for p, n in n_streams.items()}
        self._completed: dict[str, list[float]] = {
            p: [] for p in self.policy_agents}

    def _cat(self, per_agent: dict, agents: list[str]) -> np.ndarray:
        return np.concatenate([per_agent[a] for a in agents])

    def set_weights(self, params_by_policy: dict) -> bool:
        self._params = params_by_policy
        return True

    def sample(self, num_steps: int) -> dict:
        """-> {"policies": {policy_id: traj dict}, per-policy episode
        returns inside each traj}. Stream axis = agents x envs."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.rl import module as rlm

        assert self._params is not None, "set_weights first"
        T, N = num_steps, self.env.num_envs
        bufs = {}
        for p, agents in self.policy_agents.items():
            S = N * len(agents)
            obs_dim = np.shape(self._obs[p])[1:]
            bufs[p] = {
                "obs": np.zeros((T, S) + tuple(obs_dim), np.float32),
                "actions": np.zeros((T, S), np.int32),
                "logp": np.zeros((T, S), np.float32),
                "values": np.zeros((T, S), np.float32),
                "rewards": np.zeros((T, S), np.float32),
                "dones": np.zeros((T, S), np.bool_),
                "trunc_values": np.zeros((T, S), np.float32),
            }
        pending_trunc: dict[str, list[tuple]] = {
            p: [] for p in self.policy_agents}
        for t in range(T):
            actions_by_agent: dict[str, np.ndarray] = {}
            for p, agents in self.policy_agents.items():
                self._key, sub = jax.random.split(self._key)
                action, logp, value = rlm.sample_actions(
                    self._params[p], self._obs[p], sub)
                b = bufs[p]
                b["obs"][t] = self._obs[p]
                b["actions"][t] = action
                b["logp"][t] = logp
                b["values"][t] = value
                for i, a in enumerate(agents):
                    actions_by_agent[a] = np.asarray(
                        action[i * N:(i + 1) * N])
            obs, rew, term, trunc, final = self.env.step(actions_by_agent)
            for p, agents in self.policy_agents.items():
                b = bufs[p]
                self._obs[p] = self._cat(obs, agents)
                rewards = self._cat(rew, agents)
                terminated = self._cat(term, agents)
                truncated = self._cat(trunc, agents) & ~terminated
                done = terminated | truncated
                b["rewards"][t] = rewards
                b["dones"][t] = done
                if truncated.any():
                    idxs = np.nonzero(truncated)[0]
                    pending_trunc[p].append(
                        (t, idxs, self._cat(final, agents)[idxs]))
                self._ep_return[p] += rewards
                for i in np.nonzero(done)[0]:
                    self._completed[p].append(
                        float(self._ep_return[p][i]))
                    self._ep_return[p][i] = 0.0
        out = {}
        for p, agents in self.policy_agents.items():
            b = bufs[p]
            _, last_value = rlm.forward(self._params[p],
                                        jnp.asarray(self._obs[p]))
            if pending_trunc[p]:
                cat = np.concatenate(
                    [rows for _, _, rows in pending_trunc[p]])
                _, vals = rlm.forward(self._params[p], jnp.asarray(cat))
                vals = np.asarray(vals)
                i = 0
                for t, idxs, rows in pending_trunc[p]:
                    b["trunc_values"][t, idxs] = vals[i:i + len(idxs)]
                    i += len(idxs)
            completed = self._completed[p]
            self._completed[p] = []
            out[p] = {**b, "last_value": np.asarray(last_value),
                      "episode_returns": completed}
        return {"policies": out}

    def ping(self) -> bool:
        return True


@dataclasses.dataclass
class MultiAgentPPOConfig:
    """Config #1's multi-agent extension (ref: AlgorithmConfig
    .multi_agent(policies=..., policy_mapping_fn=...))."""
    env: str = "MultiAgentCartPole"
    env_config: dict = dataclasses.field(default_factory=dict)
    num_env_runners: int = 2
    num_envs_per_runner: int = 8
    rollout_fragment_length: int = 64
    # policy_id -> module-config overrides ({} = defaults); None derives
    # one policy per agent_id
    policies: Optional[dict[str, dict]] = None
    policy_mapping_fn: Optional[Callable[[str], str]] = None
    hidden: tuple = (64, 64)
    lr: float = 3e-4
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    num_epochs: int = 4
    minibatch_size: int = 256
    seed: int = 0

    def learner_config(self) -> PPOLearnerConfig:
        return PPOLearnerConfig(
            lr=self.lr, gamma=self.gamma, gae_lambda=self.gae_lambda,
            clip_eps=self.clip_eps, vf_coeff=self.vf_coeff,
            entropy_coeff=self.entropy_coeff, num_epochs=self.num_epochs,
            minibatch_size=self.minibatch_size)

    def build(self) -> "MultiAgentPPO":
        return MultiAgentPPO(self)


class MultiAgentPPO:
    """One JaxLearner per policy (the MultiRLModule analog: independent
    modules, shared driver); iteration = sample -> per-policy GAE +
    update -> per-policy weight broadcast."""

    def __init__(self, config: MultiAgentPPOConfig):
        import cloudpickle

        self.config = config
        probe = make_multi_agent_env(config.env, 1, config.seed,
                                     **config.env_config)
        mapping = config.policy_mapping_fn or (lambda aid: aid)
        self.policy_agents: dict[str, list[str]] = {}
        for aid in probe.agent_ids:
            self.policy_agents.setdefault(mapping(aid), []).append(aid)
        if config.policies is not None:
            missing = set(self.policy_agents) - set(config.policies)
            if missing:
                raise ValueError(
                    f"policy_mapping_fn produced policies {missing} "
                    f"absent from config.policies")
        self.module_cfgs = {}
        for p, agents in self.policy_agents.items():
            a0 = agents[0]
            # every agent sharing a policy must share spaces — catch the
            # mismatch here with a clear error, not as a shape crash
            # deep inside the runner's concat/forward
            for a in agents[1:]:
                if (probe.observation_size(a) != probe.observation_size(a0)
                        or probe.num_actions(a) != probe.num_actions(a0)):
                    raise ValueError(
                        f"agents {a0!r} and {a!r} map to policy {p!r} "
                        f"but have different spaces (obs "
                        f"{probe.observation_size(a0)} vs "
                        f"{probe.observation_size(a)}, actions "
                        f"{probe.num_actions(a0)} vs "
                        f"{probe.num_actions(a)})")
            overrides = (config.policies or {}).get(p, {})
            self.module_cfgs[p] = MLPModuleConfig(
                observation_size=probe.observation_size(a0),
                num_actions=probe.num_actions(a0),
                hidden=tuple(overrides.get("hidden", config.hidden)))
        module_blob = cloudpickle.dumps(self.module_cfgs)
        mapping_blob = cloudpickle.dumps(mapping)
        env_cfg_blob = cloudpickle.dumps(config.env_config)

        runner_cls = rt.remote(num_cpus=1,
                               max_restarts=-1)(MultiAgentEnvRunner)
        self._runners = FaultTolerantActorManager([
            runner_cls.remote(config.env, config.num_envs_per_runner,
                              config.seed + i, module_blob, mapping_blob,
                              env_cfg_blob)
            for i in range(config.num_env_runners)])

        learner_cls = rt.remote(num_cpus=1)(JaxLearner)
        lcfg_blob = cloudpickle.dumps(config.learner_config())
        self._learners = {
            p: learner_cls.remote(cloudpickle.dumps(cfg), lcfg_blob,
                                  config.seed + 7 * i)
            for i, (p, cfg) in enumerate(sorted(self.module_cfgs.items()))}
        init_refs = {p: lr.get_weights.remote()
                     for p, lr in self._learners.items()}
        self._weights = dict(zip(
            init_refs, rt.get(list(init_refs.values()), timeout=120)))
        self._iteration = 0
        self._recent: dict[str, list[float]] = {
            p: [] for p in self.policy_agents}

    def train(self) -> dict:
        cfg = self.config
        t0 = time.perf_counter()
        weights_ref = rt.put(self._weights)
        self._runners.foreach(lambda a: a.set_weights.remote(weights_ref))
        samples = self._runners.foreach(
            lambda a: a.sample.remote(cfg.rollout_fragment_length))
        if not samples:
            self._runners.probe_unhealthy()
            raise RuntimeError("all multi-agent env runners unhealthy")

        update_refs, steps_total = {}, 0
        for p in self.policy_agents:
            batch, ep_returns, steps = build_ppo_batch(
                [s["policies"][p] for s in samples],
                cfg.gamma, cfg.gae_lambda)
            steps_total += steps
            self._recent[p].extend(ep_returns)
            self._recent[p] = self._recent[p][-100:]
            update_refs[p] = self._learners[p].update.remote(batch)
        # collect in parallel: all refs issued before any get
        policies = list(update_refs)
        aux = dict(zip(policies,
                       rt.get([update_refs[p] for p in policies],
                              timeout=600)))
        weight_refs = {p: lr.get_weights.remote()
                       for p, lr in self._learners.items()}
        self._weights = dict(zip(
            weight_refs,
            rt.get(list(weight_refs.values()), timeout=120)))
        self._runners.probe_unhealthy()
        self._iteration += 1
        per_policy = {
            p: {"episode_return_mean": (float(np.mean(r)) if r else 0.0),
                **{f"learner/{k}": v for k, v in aux[p].items()}}
            for p, r in self._recent.items()}
        all_recent = [x for r in self._recent.values() for x in r]
        return {
            "training_iteration": self._iteration,
            "num_env_steps_sampled": steps_total,
            "episode_return_mean": (float(np.mean(all_recent))
                                    if all_recent else 0.0),
            "policies": per_policy,
            "time_this_iter_s": time.perf_counter() - t0,
        }

    def get_weights(self) -> dict:
        return self._weights

    def stop(self):
        for a in self._runners._actors + list(self._learners.values()):
            try:
                rt.kill(a)
            except Exception:
                pass
