"""Pipeline parallelism, TPU-native: GPipe schedule over a `stage` mesh
axis inside one jit program.

The reference's PP substrate is host-side compiled actor-DAGs with NCCL
channels (ref: python/ray/dag/compiled_dag_node.py:757,
experimental/channel/torch_tensor_nccl_channel.py; our host analog lives
in ray_tpu/dag/). The TPU-first design instead keeps the whole pipeline
INSIDE XLA: layers shard over a `stage` mesh axis, activations hop
stage→stage via `lax.ppermute` over ICI neighbors, and a `lax.scan`
drives the microbatch schedule — so the compiler overlaps compute with
the neighbor transfers and the whole train step stays one GSPMD program
(differentiable end to end: ppermute transposes to the reverse shift, so
jax.grad gives the backward pipeline for free).

Schedule: plain GPipe — T = n_micro + S - 1 ticks; stage s processes
microbatch m = t - s when 0 <= m < n_micro. Bubble fraction
(S-1)/(T) shrinks as n_micro grows, the standard trade.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_local(stage_fn: Callable, params_local: Any,
                    micro_x: jax.Array, axis: str) -> jax.Array:
    """Runs on ONE stage's shard inside shard_map.

    params_local: this stage's slice of the stacked stage params
    (leading stage axis removed by sharding). micro_x: [n_micro, ...]
    microbatches, replicated. Returns [n_micro, ...] outputs of the LAST
    stage (zeros elsewhere; caller psums over the stage axis).
    """
    n_stages = jax.lax.psum(1, axis)
    idx = jax.lax.axis_index(axis)
    n_micro = micro_x.shape[0]
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    state0 = jnp.zeros_like(micro_x[0])
    out0 = jnp.zeros_like(micro_x)

    def tick(carry, t):
        state, outputs = carry
        m = t - idx  # microbatch index this stage works on at tick t
        active = (m >= 0) & (m < n_micro)
        m_c = jnp.clip(m, 0, n_micro - 1)
        # stage 0 ingests a fresh microbatch; later stages take the
        # activation that arrived from the previous stage
        x_in = jnp.where(idx == 0, micro_x[jnp.clip(t, 0, n_micro - 1)],
                         state)
        y = stage_fn(params_local, x_in)
        y = jnp.where(active, y, state)
        # the last stage records its finished microbatch
        is_out = active & (idx == n_stages - 1)
        outputs = outputs.at[m_c].add(jnp.where(is_out, y, 0.0))
        # shift activations to the next stage around the ICI ring
        state = jax.lax.ppermute(y, axis, perm)
        return (state, outputs), None

    (_, outputs), _ = jax.lax.scan(
        tick, (state0, out0), jnp.arange(n_micro + n_stages - 1))
    # replicate the result: only the last stage holds nonzero outputs
    return jax.lax.psum(outputs, axis)


def pipeline_apply(stage_fn: Callable, stage_params: Any, x: jax.Array,
                   mesh: Mesh, *, n_micro: int, axis: str = "stage",
                   remat: bool = False) -> jax.Array:
    """Apply `n_stages` sequential stages to `x` with GPipe over `axis`.

    stage_fn(params_one_stage, x) -> y (same shape as x).
    stage_params: pytree whose leaves carry a LEADING stage axis of size
    mesh.shape[axis]. x: [batch, ...]; batch must divide n_micro.
    """
    n_stages = mesh.shape[axis]
    b = x.shape[0]
    assert b % n_micro == 0, f"batch {b} % n_micro {n_micro} != 0"
    micro_x = x.reshape((n_micro, b // n_micro) + x.shape[1:])

    fn = stage_fn
    if remat:
        fn = jax.checkpoint(fn)

    local = functools.partial(_pipeline_local, fn, axis=axis)

    param_specs = jax.tree.map(lambda _: P(axis), stage_params)
    sharded = shard_map(
        lambda p, mx: local(jax.tree.map(lambda l: l[0], p), mx),
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_rep=False)
    out = sharded(stage_params, micro_x)
    return out.reshape((b,) + out.shape[2:])


def stack_stage_params(per_stage: list[Any]) -> Any:
    """[stage0_tree, stage1_tree, ...] -> one tree with leading stage axis."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *per_stage)
