"""Llama-family transformer, TPU-first.

The reference framework (LydiaXwQ/ray) carries no model code of its own —
LLMs arrive via torch/DeepSpeed examples (ref:
release/air_examples/dolly_v2_lightning_fsdp_finetuning/,
doc/source/train/examples/deepspeed Llama-2 fine-tune). For the TPU build
the model layer is first-class because GSPMD sharding, remat, and kernel
choice must be co-designed with the parallelism layer (SURVEY.md §2.4).

Design (idiomatic JAX, nothing torch-shaped):

* Params are a plain pytree of ``jnp`` arrays; per-layer weights are
  **stacked on a leading "layers" axis** and the block stack is a single
  ``lax.scan`` — one trace/compile of one block regardless of depth.
* Every parameter has a tuple of *logical axis names*
  (``param_logical_axes``); ``ray_tpu.parallel.mesh.shard_params`` maps
  them to mesh axes, so DP/FSDP/TP/SP/EP are just different rule tables.
* Compute in bf16, params f32 (configurable), softmax/norm/rope in f32.
* ``jax.checkpoint`` around each block (policy: save nothing but dots'
  inputs) trades FLOPs for HBM — the standard TPU recipe.
* Attention dispatches to the Pallas flash kernel on TPU, XLA elsewhere,
  and to ring attention (ppermute over the ICI ring) when the mesh has a
  nontrivial ``seq`` axis.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from ray_tpu.ops.attention import _repeat_kv, dot_product_attention
from ray_tpu.ops.cross_entropy import softmax_cross_entropy
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.ops.rope import apply_rope, rope_frequencies


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    hidden_dim: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    remat_save_attn: bool = False
    # activation-saving policy under remat (PERF.md: dots saves ~8.5GB of
    # activations at b8 s2048 on the 410m config and OOMs b16 on 16GB
    # HBM; "nothing" saves only the ~32MB/layer block carry, trading one
    # extra block forward in the backward for the batch headroom):
    #   "dots"    — dots_with_no_batch_dims_saveable (matmul outputs)
    #   "nothing" — full per-block recompute (minimum memory)
    remat_policy: str = "dots"
    # attention impl: "auto" | "xla" | "flash" | "ring" | "ulysses"
    attn_impl: str = "auto"
    # flash-kernel tile shapes (PERF.md: attention is the MFU sink at the
    # bench geometry; wider K blocks feed the MXU a longer contraction
    # between softmax rescales — sweep via tools/mfu_sweep.py)
    attn_block_q: int = 512
    attn_block_k: int = 512
    seq_axis: str = "seq"          # mesh axis used by ring/ulysses attention
    # LoRA: scale numerator for the low-rank path (scale = alpha / rank,
    # rank inferred from the adapter's shape; see models/lora.py)
    lora_alpha: float = 16.0
    # MoE: >0 replaces every dense FFN with a mixture of this many experts
    # (EP over the `expert` mesh axis; see ops/moe.py)
    moe_num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_loss_weight: float = 0.01

    @property
    def moe(self) -> bool:
        return self.moe_num_experts > 0

    def moe_config(self):
        from ray_tpu.ops.moe import MoEConfig

        return MoEConfig(num_experts=self.moe_num_experts,
                         top_k=self.moe_top_k,
                         capacity_factor=self.moe_capacity_factor,
                         aux_loss_weight=self.moe_aux_loss_weight)

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def flops_per_token(self) -> float:
        """Approximate training FLOPs per token (fwd+bwd, 6ND rule plus
        attention quadratic term)."""
        n_params = self.num_params(include_embed=False)
        attn = 12 * self.n_layers * self.dim * self.max_seq_len
        return 6 * n_params + attn

    def num_params(self, include_embed: bool = True) -> int:
        d, h = self.dim, self.hidden_dim
        kv_dim = self.n_kv_heads * self.head_dim
        per_layer = (d * d + 2 * d * kv_dim + d * d) + 3 * d * h + 2 * d
        total = self.n_layers * per_layer + d
        if include_embed:
            total += self.vocab_size * d
            if not self.tie_embeddings:
                total += d * self.vocab_size
        return total


# ----------------------------------------------------------------- presets
PRESETS: dict[str, dict] = {
    # debug-size model for tests / CI (CPU-mesh friendly)
    "debug": dict(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                  n_kv_heads=2, hidden_dim=128, max_seq_len=128),
    "160m": dict(vocab_size=32000, dim=768, n_layers=12, n_heads=12,
                 n_kv_heads=12, hidden_dim=2048, max_seq_len=2048),
    "410m": dict(vocab_size=32000, dim=1024, n_layers=24, n_heads=16,
                 n_kv_heads=16, hidden_dim=2816, max_seq_len=2048),
    # same params/FLOPs as 410m with head_dim=128 (8x128 instead of
    # 16x64): fills the MXU's 128-wide contraction and the 128-lane
    # tiling — the bench geometry matching Llama-2-7B's head_dim
    # (PERF.md: the biggest modeled MFU lever for the attention kernel)
    "410m-hd128": dict(vocab_size=32000, dim=1024, n_layers=24, n_heads=8,
                       n_kv_heads=8, hidden_dim=2816, max_seq_len=2048),
    "1b": dict(vocab_size=32000, dim=2048, n_layers=16, n_heads=16,
               n_kv_heads=8, hidden_dim=5632, max_seq_len=2048),
    "llama2-7b": dict(vocab_size=32000, dim=4096, n_layers=32, n_heads=32,
                      n_kv_heads=32, hidden_dim=11008, max_seq_len=4096),
    "llama2-13b": dict(vocab_size=32000, dim=5120, n_layers=40, n_heads=40,
                       n_kv_heads=40, hidden_dim=13824, max_seq_len=4096),
    "llama3-8b": dict(vocab_size=128256, dim=4096, n_layers=32, n_heads=32,
                      n_kv_heads=8, hidden_dim=14336, max_seq_len=8192,
                      rope_theta=500000.0),
    "llama2-70b": dict(vocab_size=32000, dim=8192, n_layers=80, n_heads=64,
                       n_kv_heads=8, hidden_dim=28672, max_seq_len=4096),
}


def config_for(name: str, **overrides) -> LlamaConfig:
    if name not in PRESETS:
        raise ValueError(f"unknown preset {name!r}; have {sorted(PRESETS)}")
    kw = dict(PRESETS[name])
    kw.update(overrides)
    return LlamaConfig(**kw)


# ------------------------------------------------------------------- params
def init_params(cfg: LlamaConfig, key: jax.Array) -> dict:
    """Initialize a param pytree. Per-layer weights carry a leading
    [n_layers] axis so the block stack scans."""
    pd = cfg.param_dtype
    d, h, L = cfg.dim, cfg.hidden_dim, cfg.n_layers
    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    k = iter(jax.random.split(key, 16))

    def dense(rng, shape, fan_in):
        return (jax.random.normal(rng, shape, jnp.float32)
                * (1.0 / math.sqrt(fan_in))).astype(pd)

    layers: dict = {
        "wq": dense(next(k), (L, d, nh * hd), d),
        "wk": dense(next(k), (L, d, nkv * hd), d),
        "wv": dense(next(k), (L, d, nkv * hd), d),
        "wo": dense(next(k), (L, nh * hd, d), nh * hd),
        "attn_norm": jnp.ones((L, d), pd),
        "mlp_norm": jnp.ones((L, d), pd),
    }
    if cfg.moe:
        E = cfg.moe_num_experts
        layers.update({
            "router": dense(next(k), (L, d, E), d),
            "w_gate": dense(next(k), (L, E, d, h), d),
            "w_up": dense(next(k), (L, E, d, h), d),
            "w_down": dense(next(k), (L, E, h, d), h),
        })
    else:
        layers.update({
            "w_gate": dense(next(k), (L, d, h), d),
            "w_up": dense(next(k), (L, d, h), d),
            "w_down": dense(next(k), (L, h, d), h),
        })
    params = {
        "embed": dense(next(k), (cfg.vocab_size, d), d),
        "layers": layers,
        "final_norm": jnp.ones((d,), pd),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(next(k), (d, cfg.vocab_size), d)
    return params


def param_logical_axes(cfg: LlamaConfig) -> dict:
    """Same tree structure as init_params, leaves = logical-axis tuples
    consumed by parallel.mesh.shard_params."""
    layer_axes: dict = {
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "kv_heads"),
        "wv": ("layers", "embed", "kv_heads"),
        "wo": ("layers", "heads", "embed"),
        "attn_norm": ("layers", None),
        "mlp_norm": ("layers", None),
    }
    if cfg.moe:
        layer_axes.update({
            "router": ("layers", "embed", None),
            "w_gate": ("layers", "expert", "embed", "mlp"),
            "w_up": ("layers", "expert", "embed", "mlp"),
            "w_down": ("layers", "expert", "mlp", "embed"),
        })
    else:
        layer_axes.update({
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        })
    axes = {
        "embed": ("vocab", "embed"),
        "layers": layer_axes,
        "final_norm": (None,),
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


# ------------------------------------------------------------------ forward
def _attention(cfg: LlamaConfig, q, k, v):
    if cfg.attn_impl == "ring":
        return ring_attention(q, k, v, cfg.seq_axis, causal=True)
    if cfg.attn_impl == "ulysses":
        from ray_tpu.ops.ring_attention import ulysses_attention

        return ulysses_attention(q, k, v, cfg.seq_axis, causal=True)
    return dot_product_attention(q, k, v, causal=True, impl=cfg.attn_impl,
                                 block_q=cfg.attn_block_q,
                                 block_k=cfg.attn_block_k)


def _proj(cfg: LlamaConfig, layer: dict, name: str, h):
    """Frozen matmul + optional LoRA low-rank path (shared by the
    training block and the KV-cache decode block so adapters behave
    identically at train and serve time). The [d, out] delta is never
    materialized."""
    dt = cfg.dtype
    out = h @ layer[name].astype(dt)
    a = layer.get(name + "_a")
    if a is not None:
        scale = cfg.lora_alpha / a.shape[-1]
        out = out + ((h @ a.astype(dt)) @ layer[name + "_b"].astype(dt)
                     ) * jnp.asarray(scale, dt)
    return out


def _block(cfg: LlamaConfig, x, layer, cos, sin, positions):
    """One transformer block. x: [b, s, d] (cfg.dtype).
    Returns (x, moe_aux_loss) — aux is 0 for the dense path.

    When the layer dict carries LoRA adapters ("<w>_a"/"<w>_b", stacked
    like the base weights — see models/lora.py), the low-rank path
    ``h @ A @ B * (alpha/r)`` is added next to the frozen matmul; the
    full-rank delta is never materialized.
    """
    b, s, d = x.shape
    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    dt = cfg.dtype

    def proj(name, h):
        return _proj(cfg, layer, name, h)

    h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q = proj("wq", h).reshape(b, s, nh, hd)
    kk = proj("wk", h).reshape(b, s, nkv, hd)
    vv = proj("wv", h).reshape(b, s, nkv, hd)
    q = apply_rope(q, cos, sin, positions)
    kk = apply_rope(kk, cos, sin, positions)
    attn = _attention(cfg, q, kk, vv).reshape(b, s, nh * hd)
    # Named so the remat policy can save it: attention outputs are dots
    # WITH batch dims, so dots_with_no_batch_dims_saveable would rerun
    # the whole flash kernel forward inside the backward pass (~+33% on
    # the attention budget) to rebuild this one activation.
    attn = checkpoint_name(attn, "attn_out")
    x = x + proj("wo", attn)

    h = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    if cfg.moe:
        from ray_tpu.ops.moe import moe_ffn

        moe_params = {"router": layer["router"], "w_gate": layer["w_gate"],
                      "w_up": layer["w_up"], "w_down": layer["w_down"]}
        out, aux = moe_ffn(moe_params, h, cfg.moe_config())
        return x + out, aux
    gate = jax.nn.silu(proj("w_gate", h))
    up = proj("w_up", h)
    x = x + proj("w_down", gate * up)
    return x, jnp.zeros((), jnp.float32)


def backbone(params: dict, tokens: jax.Array, cfg: LlamaConfig,
             positions: jax.Array | None = None,
             with_aux: bool = False):
    """tokens: [b, s] int32 -> final hidden states [b, s, d] (cfg.dtype),
    or (hidden, moe_aux_loss) when with_aux.

    The layer stack is one lax.scan over stacked weights; each step is
    optionally rematerialized.
    """
    dt = cfg.dtype
    x = jnp.take(params["embed"], tokens, axis=0).astype(dt)
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    scanned_layers = params["layers"]
    if "lora" in params:
        # adapters are stacked on the same leading [n_layers] axis, so
        # they ride the same scan as the base weights (models/lora.py)
        scanned_layers = {**scanned_layers, **params["lora"]["layers"]}

    def step(carry, layer):
        x, aux_sum = carry
        x, aux = _block(cfg, x, layer, cos, sin, positions)
        return (x, aux_sum + aux), None

    if cfg.remat:
        if cfg.remat_policy == "nothing":
            policy = None   # save only the block carry; recompute all
        elif cfg.remat_policy == "dots":
            policy = \
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        else:
            raise ValueError(
                f"unknown remat_policy {cfg.remat_policy!r}")
        if cfg.remat_save_attn:
            # also save flash-attention outputs (dots WITH batch dims are
            # not covered by the base policy, so the kernel forward would
            # rerun inside the backward); costs b*s*d*2B per layer
            save_attn = jax.checkpoint_policies.save_only_these_names(
                "attn_out")
            policy = (save_attn if policy is None else
                      jax.checkpoint_policies.save_from_both_policies(
                          policy, save_attn))
        step = jax.checkpoint(step, policy=policy)
    (x, aux_sum), _ = jax.lax.scan(
        step, (x, jnp.zeros((), jnp.float32)), scanned_layers)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    if with_aux:
        return x, aux_sum
    return x


def _head_matrix(params: dict, cfg: LlamaConfig) -> jax.Array:
    return (params["embed"].T if cfg.tie_embeddings
            else params["lm_head"]).astype(cfg.dtype)


def forward(params: dict, tokens: jax.Array, cfg: LlamaConfig,
            positions: jax.Array | None = None) -> jax.Array:
    """tokens: [b, s] int32 -> logits [b, s, vocab] (f32)."""
    x = backbone(params, tokens, cfg, positions)
    return (x @ _head_matrix(params, cfg)).astype(jnp.float32)


def loss_fn(params: dict, batch: dict, cfg: LlamaConfig):
    """batch: {"tokens": [b, s], "targets": [b, s]} -> (loss, aux).

    Uses the fused lm-head + cross entropy (ops/cross_entropy.py) so the
    [b*s, vocab] f32 logits tensor is never materialized.
    """
    from ray_tpu.ops.cross_entropy import fused_lm_head_cross_entropy

    x, moe_aux = backbone(params, batch["tokens"], cfg, with_aux=True)
    ce_loss, n_tok = fused_lm_head_cross_entropy(
        x, _head_matrix(params, cfg), batch["targets"])
    loss = ce_loss + moe_aux
    return loss, {"loss": ce_loss, "tokens": n_tok, "moe_aux": moe_aux}


# ----------------------------------------------------------------- decoding
def init_kv_cache(cfg: LlamaConfig, batch: int, max_len: int | None = None
                  ) -> dict:
    max_len = max_len or cfg.max_seq_len
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
        "length": jnp.zeros((), jnp.int32),
        # per-row first REAL slot: left-padded batched serving writes pad
        # tokens into cache slots [0, start); they are masked out and rope
        # positions are start-relative (vLLM-style batched decode)
        "start": jnp.zeros((batch,), jnp.int32),
    }


def kv_cache_logical_axes() -> dict:
    return {"k": ("layers", "batch", None, "kv_heads", "head_dim"),
            "v": ("layers", "batch", None, "kv_heads", "head_dim"),
            "length": (), "start": ("batch",)}


def _decode_block(cfg: LlamaConfig, x, layer, k_cache, v_cache, cos, sin,
                  positions, cache_len, start=None, abs_positions=None):
    """Single-step (or chunked prefill) block with KV cache.

    x: [b, s, d]; k_cache/v_cache: [b, max_len, nkv, hd]. Writes new K/V at
    [cache_len, cache_len+s) via dynamic_update_slice (static shapes).
    `positions` are rope positions (start-relative for left-padded rows);
    `abs_positions` are cache-slot positions used for masking; `start` [b]
    hides the left-pad slots of each row.
    """
    b, s, d = x.shape
    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    dt = cfg.dtype
    h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q = _proj(cfg, layer, "wq", h).reshape(b, s, nh, hd)
    kk = _proj(cfg, layer, "wk", h).reshape(b, s, nkv, hd)
    vv = _proj(cfg, layer, "wv", h).reshape(b, s, nkv, hd)
    q = apply_rope(q, cos, sin, positions)
    kk = apply_rope(kk, cos, sin, positions)
    if jnp.ndim(cache_len) == 0:
        # whole batch advances together (left-padded batched decode)
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, kk, (0, cache_len, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, vv, (0, cache_len, 0, 0))
    else:
        # per-row write offsets (continuous-batching slots: each row is
        # an independent request at its own depth — vLLM-style)
        def _upd(c, new, off):
            return jax.lax.dynamic_update_slice(c, new, (off, 0, 0))
        k_cache = jax.vmap(_upd)(k_cache, kk, cache_len)
        v_cache = jax.vmap(_upd)(v_cache, vv, cache_len)
    # mask: key slot j visible iff start <= j <= query slot
    max_len = k_cache.shape[1]
    q_pos = positions if abs_positions is None else abs_positions  # [b, s]
    k_pos = jnp.arange(max_len)[None, :]
    mask = k_pos[:, None, :] <= q_pos[..., None]          # [b, s, max_len]
    if start is not None:
        mask = mask & (k_pos[:, None, :] >= start[:, None, None])
    kr = _repeat_kv(k_cache, nh // nkv)
    vr = _repeat_kv(v_cache, nh // nkv)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, kr,
                        preferred_element_type=jnp.float32) * (hd ** -0.5)
    logits = jnp.where(mask[:, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(dt)
    attn = jnp.einsum("bhqk,bkhd->bqhd", probs, vr).reshape(b, s, nh * hd)
    x = x + _proj(cfg, layer, "wo", attn)
    h = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    x = x + _proj(cfg, layer, "w_down",
                  jax.nn.silu(_proj(cfg, layer, "w_gate", h))
                  * _proj(cfg, layer, "w_up", h))
    return x, k_cache, v_cache


def decode_step(params: dict, cache: dict, tokens: jax.Array,
                cfg: LlamaConfig) -> tuple[jax.Array, dict]:
    """Append `tokens` [b, s] to the cache, return logits for the last
    position [b, vocab] and the updated cache. jit-able with static s
    (s=1 for autoregressive decode; larger s = chunked prefill).

    cache["length"] may be a scalar (whole batch in lock-step, the
    left-padded batched path) or shape [b] (per-row depths: the
    continuous-batching slot path, where each row is an independent
    request and writes at its own cache offset)."""
    b, s = tokens.shape
    dt = cfg.dtype
    cache_len = cache["length"]
    if jnp.ndim(cache_len) == 0:
        abs_positions = cache_len + jnp.arange(s)[None, :].repeat(b, 0)
    else:
        abs_positions = cache_len[:, None] + jnp.arange(s)[None, :]
    start = cache.get("start")
    if start is None:
        positions = abs_positions
    else:
        # rope positions are relative to each row's first real token
        positions = jnp.maximum(abs_positions - start[:, None], 0)
    x = jnp.take(params["embed"], tokens, axis=0).astype(dt)
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    scanned_layers = params["layers"]
    if "lora" in params:
        # serve-time adapters: stacked on the same [n_layers] axis, they
        # ride the decode scan exactly like the training path's (the
        # _proj low-rank branch fires per layer; models/lora.py)
        scanned_layers = {**scanned_layers, **params["lora"]["layers"]}

    def step(x, inputs):
        layer, kc, vc = inputs
        x, kc, vc = _decode_block(cfg, x, layer, kc, vc, cos, sin,
                                  positions, cache_len, start=start,
                                  abs_positions=abs_positions)
        return x, (kc, vc)

    x, (k_new, v_new) = jax.lax.scan(
        step, x, (scanned_layers, cache["k"], cache["v"]))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = (params["embed"].T if cfg.tie_embeddings
            else params["lm_head"]).astype(dt)
    logits = (x[:, -1] @ head).astype(jnp.float32)
    new_cache = {"k": k_new, "v": v_new, "length": cache_len + s}
    if start is not None:
        new_cache["start"] = start
    return logits, new_cache
