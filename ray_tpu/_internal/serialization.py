"""Object serialization: cloudpickle + pickle5 out-of-band buffers.

TPU-native analog of the reference's serialization stack
(ref: python/ray/_private/serialization.py and the cloudpickle fork):
we use stock cloudpickle (protocol 5) with a ``buffer_callback`` so large
contiguous payloads (numpy arrays, jax host arrays, arrow buffers) are
extracted zero-copy into a separate buffer list. The wire/shm format is::

    [8-byte header: n_buffers (u32) | pickled_len (u32)]
    [pickled bytes]
    [for each buffer: 8-byte length][buffer bytes, 8-byte aligned]

which lets the shared-memory store hand workers read-only memoryviews over
the buffers without copying (the plasma idea, ref:
src/ray/object_manager/plasma/protocol.cc, re-done host-side only — device
arrays never pass through here, they ride the mesh as jax.Array).
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, Callable

import cloudpickle

_HEADER = struct.Struct("<II")
_BUFLEN = struct.Struct("<Q")
_ALIGN = 8


def _pad(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


class _NeedCloudpickle(Exception):
    """The object graph needs cloudpickle's by-value semantics (a plain
    pickle save_global would succeed but emit a reference the unpickling
    worker cannot import)."""


def _fast_payload_hazard(payload: bytes) -> bool:
    """Did the C pickler emit a by-reference global that cloudpickle
    would have shipped by value? save_global always writes the module
    name as a verbatim string, so a stream free of ``__main__`` (and of
    every ship_code_by_value-registered module name) cannot reference
    them. False positives (a user STRING containing "__main__") merely
    fall back to cloudpickle — slower, never wrong."""
    if payload.find(b"__main__") != -1:
        return True
    for mod_name in _by_value_registered:
        if payload.find(mod_name.encode()) != -1:
            return True
    return False


def serialize(obj: Any) -> list[bytes | memoryview]:
    """Serialize to a list of chunks (zero-copy for out-of-band buffers).

    The caller concatenates (for sockets, writev-style) or copies into a
    single shm allocation.

    Fast path: the stdlib C pickler (~5-10x cheaper per control message
    than CloudPickler, byte-compatible with pickle.loads). Anything it
    cannot pickle (lambdas, closures, dynamic classes) falls back to
    cloudpickle, as does any stream that references __main__ or a
    registered driver-local module (see _fast_payload_hazard).
    """
    buffers: list[pickle.PickleBuffer] = []
    try:
        payload = pickle.dumps(obj, protocol=5,
                               buffer_callback=buffers.append)
        if _fast_payload_hazard(payload):
            raise _NeedCloudpickle
    except Exception:
        buffers = []  # drop buffers extracted before the abort
        payload = cloudpickle.dumps(obj, protocol=5,
                                    buffer_callback=buffers.append)
    chunks: list[bytes | memoryview] = [
        _HEADER.pack(len(buffers), len(payload)),
        payload,
    ]
    pad = _pad(len(payload)) - len(payload)
    if pad:
        chunks.append(b"\x00" * pad)
    for pb in buffers:
        raw = pb.raw()
        chunks.append(_BUFLEN.pack(raw.nbytes))
        chunks.append(raw)
        pad = _pad(raw.nbytes) - raw.nbytes
        if pad:
            chunks.append(b"\x00" * pad)
    return chunks


def serialized_size(chunks: list[bytes | memoryview]) -> int:
    return sum(len(c) if isinstance(c, bytes) else c.nbytes for c in chunks)


def chunks_to_bytes(chunks: list[bytes | memoryview]) -> bytes:
    """Join a serialize() chunk list into one contiguous blob with exactly
    one copy (``bytes.join`` consumes memoryviews directly — no per-chunk
    ``bytes()`` materialization)."""
    if len(chunks) == 1 and isinstance(chunks[0], bytes):
        return chunks[0]
    return b"".join(chunks)


def serialize_to_bytes(obj: Any) -> bytes:
    return chunks_to_bytes(serialize(obj))


def deserialize(data: bytes | memoryview, *, buffer_wrapper=None) -> Any:
    """Deserialize from a contiguous buffer, zero-copy for buffers.

    When ``data`` is a memoryview over shared memory, the out-of-band
    buffers alias that memory: the resulting numpy arrays are views, not
    copies (callers must keep the mapping alive; ObjectRef holders do).

    ``buffer_wrapper``, when given, is applied to each out-of-band buffer
    view before it is handed to pickle — the zero-copy get path uses it
    to interpose weakref-able pin holders so the shm segment stays mapped
    exactly as long as any reconstructed array aliases it.
    """
    mv = memoryview(data)
    n_buffers, plen = _HEADER.unpack_from(mv, 0)
    off = _HEADER.size
    payload = mv[off:off + plen]
    off += _pad(plen)
    buffers = []
    for _ in range(n_buffers):
        (blen,) = _BUFLEN.unpack_from(mv, off)
        off += _BUFLEN.size
        view = mv[off:off + blen]
        buffers.append(view if buffer_wrapper is None else buffer_wrapper(view))
        off += _pad(blen)
    return pickle.loads(payload, buffers=buffers)


class SerializationContext:
    """Pluggable reducers, mirroring ref _private/serialization.py's
    custom-serializer registry (ray.util.register_serializer)."""

    def __init__(self):
        self._custom: dict[type, tuple[Callable, Callable]] = {}

    def register(self, typ: type, serializer: Callable, deserializer: Callable):
        self._custom[typ] = (serializer, deserializer)
        # cloudpickle honors copyreg-style dispatch via __reduce__; simplest
        # robust hook is a pickle-by-value wrapper:
        import copyreg

        def _reduce(obj, _ser=serializer, _de=deserializer):
            return (_de, (_ser(obj),))

        copyreg.pickle(typ, _reduce)

    def deregister(self, typ: type):
        self._custom.pop(typ, None)


_context = SerializationContext()


def get_context() -> SerializationContext:
    return _context


# ------------------------------------------------- driver-local code shipping
_by_value_registered: set[str] = set()
_scanned_modules: set[str] = set()


def ship_code_by_value(fn: Any) -> None:
    """Make cloudpickle serialize `fn`'s defining module by value when that
    module is driver-local (not installed in site/dist-packages), so workers
    without the driver's sys.path can still unpickle it. Walks the module's
    globals transitively so sibling driver-local modules it imports ship
    too.

    Ref analog: the function table ships pickled definitions through GCS KV
    (python/ray/_private/function_manager.py:58); here the definition rides
    inside the task spec instead, and by-value registration covers
    module-level functions (closures/lambdas/__main__ are by-value already).
    """
    _register_module_tree(getattr(fn, "__module__", None))


def _is_driver_local(mod) -> bool:
    import sys

    mod_file = getattr(mod, "__file__", None)
    if mod_file is None:
        return False
    path = mod_file.replace("\\", "/")
    if "/site-packages/" in path or "/dist-packages/" in path:
        return False
    return not path.startswith(getattr(sys, "base_prefix", "\0"))


def _register_module_tree(mod_name: str | None) -> None:
    import sys
    import types

    if not mod_name or mod_name in ("__main__", "builtins"):
        return
    if mod_name.split(".")[0] == "ray_tpu" or mod_name in _scanned_modules:
        return
    _scanned_modules.add(mod_name)
    mod = sys.modules.get(mod_name)
    if mod is None or not _is_driver_local(mod):
        return
    try:
        cloudpickle.register_pickle_by_value(mod)
        _by_value_registered.add(mod_name)
    except Exception:
        return
    for value in list(vars(mod).values()):
        if isinstance(value, types.ModuleType):
            _register_module_tree(value.__name__)
        else:
            sub = getattr(value, "__module__", None)
            if isinstance(sub, str):
                _register_module_tree(sub)


def dumps_code(fn: Any) -> bytes:
    """Pickle a function/class for remote execution, shipping driver-local
    module trees by value first. If by-value capture hits an unpicklable
    module-level global (open connections, locks), fall back to
    by-reference for that tree — same-host workers can import it via
    PYTHONPATH."""
    ship_code_by_value(fn)
    try:
        return cloudpickle.dumps(fn)
    except Exception:
        _unregister_module_tree(getattr(fn, "__module__", None))
        return cloudpickle.dumps(fn)


def _unregister_module_tree(mod_name: str | None) -> None:
    import sys

    if not mod_name:
        return
    for name in list(_by_value_registered):
        mod = sys.modules.get(name)
        if mod is None:
            continue
        try:
            cloudpickle.unregister_pickle_by_value(mod)
            _by_value_registered.discard(name)
            _scanned_modules.discard(name)
        except Exception:
            pass
