"""Rotary position embeddings (RoPE)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_len: int,
                     theta: float = 10000.0) -> tuple[jax.Array, jax.Array]:
    """Precompute cos/sin tables, shape [max_len, head_dim // 2], fp32."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                           dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array,
               positions: jax.Array | None = None) -> jax.Array:
    """Rotate pairs (split-half convention, llama-style).

    x: [..., seq, heads, head_dim]; cos/sin: [max_len, head_dim//2] or
    already gathered [..., seq, head_dim//2]. positions: [..., seq] int32
    (defaults to arange, which is the common pre-fill case).
    """
    seq = x.shape[-3]
    if positions is None and cos.ndim == 2:
        cos = cos[:seq]
        sin = sin[:seq]
    elif positions is not None:
        cos = jnp.take(cos, positions, axis=0)
        sin = jnp.take(sin, positions, axis=0)
    # broadcast over heads: [..., seq, 1, head_dim//2]
    cos = cos[..., :, None, :]
    sin = sin[..., :, None, :]
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    dtype = x.dtype
    x1f, x2f = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out = jnp.concatenate(
        [x1f * cos - x2f * sin, x2f * cos + x1f * sin], axis=-1)
    return out.astype(dtype)
