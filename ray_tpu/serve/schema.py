"""Declarative serve config: deploy applications from a YAML/dict spec
(ref analog: python/ray/serve/schema.py ServeDeploySchema + the REST/CLI
`serve deploy` path).

Config shape:

    applications:
      - name: app1
        import_path: my_module:app        # Application OR builder fn
        args: {size: 3}                   # builder kwargs (optional)
        deployments:                      # per-deployment overrides
          - name: Model
            num_replicas: 2
            max_ongoing_requests: 8
"""

from __future__ import annotations

import importlib
from typing import Any

from ray_tpu.serve.deployment import Application


def _load_import_path(import_path: str):
    module_name, _, attr = import_path.partition(":")
    if not attr:
        raise ValueError(
            f"import_path {import_path!r} must be 'module:attribute'")
    module = importlib.import_module(module_name)
    return getattr(module, attr)


def build_app(app_config: dict) -> Application:
    """Materialize one application entry: import, call the builder if
    needed, apply per-deployment overrides."""
    target = _load_import_path(app_config["import_path"])
    args = app_config.get("args") or {}
    if isinstance(target, Application):
        app = target
    else:
        app = target(**args)
        if not isinstance(app, Application):
            raise TypeError(
                f"{app_config['import_path']} returned {type(app)}, "
                "expected a bound Application")
    overrides = {d["name"]: d for d in app_config.get("deployments", [])}
    if overrides:
        _apply_overrides(app, overrides)
    return app


def _apply_overrides(app: Application, overrides: dict[str, dict]):
    for node in app.walk():
        ov = overrides.get(node.deployment.name)
        if not ov:
            continue
        opts = {k: v for k, v in ov.items() if k != "name"}
        node.deployment = node.deployment.options(**opts)


def deploy_config(config: Any, *, _blocking: bool = True) -> dict:
    """Deploy every application in a config dict / YAML string / YAML file
    path. Returns {app_name: ingress handle}."""
    import os

    from ray_tpu import serve

    if isinstance(config, str):
        import yaml

        if os.path.exists(config):
            with open(config) as f:
                config = yaml.safe_load(f)
        else:
            config = yaml.safe_load(config)
    if not isinstance(config, dict) or "applications" not in config:
        raise ValueError("config must contain an 'applications' list")
    handles = {}
    for app_config in config["applications"]:
        name = app_config.get("name", "default")
        app = build_app(app_config)
        handles[name] = serve.run(app, name=name, _blocking=_blocking)
    return handles
