"""Sustained-load serve data-plane floor gate (slow-marked so tier-1
stays fast; ISSUE 10 acceptance leg).

Runs the serve_bench ``sustained`` leg — open-loop arrival through the
HTTP ingress with a >=30s steady state and a burst at ~2x min-replica
capacity — and floors:

* max-QPS: admitted throughput at steady state and under the burst,
* admitted-request p99 latency in both phases,
* shed behavior: the burst MUST shed (503 + Retry-After), MUST NOT
  time out an admitted request, and MUST NOT 500,
* the closed loop E2E: the autoscaler scales replicas up under the
  burst and back to min after the drain,
* Prometheus counters: rayt_serve_{shed,admitted}_total and the
  autoscale decision gauge are emitting cluster-wide.

CLI twin refreshing SERVE_BENCH.json:
``python tools/serve_bench.py --leg sustained``.
"""

from __future__ import annotations

import os
import signal
import sys

import pytest

pytestmark = pytest.mark.slow

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

# committed SERVE_BENCH.json sustained_load leg on this class of box:
# steady 13.4 qps / p99 ~160ms, burst 44 qps admitted / p99 ~1.4s with
# shed_rate ~0.17 and peak_replicas 3. Floors sit 2-4x below committed,
# clearing loaded-suite noise while still failing a reintroduced
# unbounded-queueing or broken-autoscaler regression by an order of
# magnitude.
STEADY_QPS_FLOOR = 8.0
STEADY_P99_MS_CEIL = 1500.0
BURST_QPS_FLOOR = 20.0
BURST_P99_MS_CEIL = 4000.0
BURST_SHED_RATE_CEIL = 0.9

# latency leg (ISSUE 16): the paced app yields its first chunk
# immediately, so client TTFT is pure serve-path overhead (proxy
# admission + routing + dispatch + replica queue + first yield).
# Committed SERVE_BENCH.json measures p99 ~= tens of ms on this class
# of box; the ceiling sits an order of magnitude above to clear
# loaded-suite noise while still failing a reintroduced
# poll-loop/blocking-dispatch regression (which lands at seconds).
LATENCY_TTFT_P99_MS_CEIL = 1000.0
# server-side proxy waterfall stages must tile the proxied e2e: the
# stage means (admission+router+dispatch+stream) must sum to within
# 10% of the mean recorded e2e, or a stage is unaccounted for.
WATERFALL_TILE_TOL = 0.10


def test_sustained_load_floors_and_closed_loop():
    signal.alarm(600)  # tier-1 SIGALRM budget is sized for fast tests
    from serve_bench import run_sustained

    import ray_tpu as rt
    from ray_tpu import serve

    rt.init(num_cpus=4)
    try:
        res = run_sustained(steady_s=30.0, burst_s=10.0)
    finally:
        serve.shutdown()
        rt.shutdown()

    steady, burst, drain = res["steady"], res["burst"], res["drain"]
    # steady state: everything admitted, latency flat
    assert steady["achieved_qps"] >= STEADY_QPS_FLOOR, steady
    assert steady["timeouts"] == 0 and steady["errors"] == 0, steady
    assert steady["latency_p99_ms"] <= STEADY_P99_MS_CEIL, steady

    # burst at 2x min-capacity: excess SHEDS, admitted requests never
    # time out, nothing turns into a 500/transport error
    assert burst["shed"] > 0, burst
    assert burst["shed_rate"] <= BURST_SHED_RATE_CEIL, burst
    assert burst["timeouts"] == 0, burst
    assert burst["errors"] == 0, burst
    assert burst["achieved_qps"] >= BURST_QPS_FLOOR, burst
    assert burst["latency_p99_ms"] <= BURST_P99_MS_CEIL, burst

    # the closed loop E2E: scale-up under the burst, back to min after
    assert burst["peak_replicas"] >= 2, burst
    assert drain["final_replicas"] == 1, drain

    # Prometheus family emitted cluster-wide (GCS metrics store)
    metrics = res["metrics"]
    assert metrics.get("rayt_serve_shed_total", 0) > 0, metrics
    assert metrics.get("rayt_serve_admitted_total", 0) > 0, metrics
    assert "rayt_serve_autoscale_decision" in metrics, metrics


def test_request_latency_floors_and_waterfall_tiling():
    """ISSUE 16 floor gate: streaming TTFT p99 through the full proxy
    path stays bounded, and the server-side waterfall stages account
    for the request — stage means sum to within 10% of the recorded
    e2e mean (nothing slips between the instrumentation points)."""
    signal.alarm(600)
    from serve_bench import run_latency

    import ray_tpu as rt
    from ray_tpu import serve

    rt.init(num_cpus=4)
    try:
        res = run_latency(rate_qps=8.0, duration_s=10.0)
    finally:
        serve.shutdown()
        rt.shutdown()

    assert res["outcomes"].get("ok", 0) >= 40, res["outcomes"]
    assert res["ttft_p99_ms"] is not None
    assert res["ttft_p99_ms"] <= LATENCY_TTFT_P99_MS_CEIL, res
    assert res["tpot_p50_ms"] is not None, res

    wf = res["waterfall"]
    assert wf.get("count", 0) >= 40, wf  # records landed in the GCS
    stage_sum = sum(wf.get(k, 0.0) for k in (
        "admission_mean_ms", "router_mean_ms", "dispatch_mean_ms",
        "stream_mean_ms"))
    e2e = wf.get("e2e_mean_ms")
    assert e2e and stage_sum > 0, wf
    assert abs(stage_sum - e2e) <= WATERFALL_TILE_TOL * e2e + 0.5, (
        stage_sum, e2e, wf)
    # the replica-side nest and the client/server TTFT clocks agree to
    # within the same order of magnitude
    assert wf.get("replica_service_mean_ms") is not None, wf
    assert wf.get("ttft_mean_ms") is not None, wf
