"""Test harness: force an 8-device virtual CPU mesh before jax imports.

This is the TPU-build analog of the reference's in-process multi-node
Cluster fixture (ref: python/ray/cluster_utils.py:135): SPMD/sharding tests
run against 8 virtual CPU devices standing in for a pod slice, so CI needs
no real TPU hardware.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # force: env may pin a TPU platform
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
# Keep XLA from oversubscribing the (often single-core) CI host.
os.environ.setdefault("XLA_PYTHON_CLIENT_PREALLOCATE", "false")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# A TPU-plugin sitecustomize may have pinned jax_platforms before this file
# runs; force the CPU client (must happen before any backend initializes).
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

import signal  # noqa: E402

import pytest  # noqa: E402

# Per-test wall-clock cap (the reference sets 3 min in pytest.ini:14).
# pytest-timeout isn't in the image, so use SIGALRM directly.
TEST_TIMEOUT_S = int(os.environ.get("RAYT_TEST_TIMEOUT_S", "180"))


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    def _on_timeout(signum, frame):
        raise TimeoutError(
            f"test exceeded {TEST_TIMEOUT_S}s (RAYT_TEST_TIMEOUT_S)")

    old = signal.signal(signal.SIGALRM, _on_timeout)
    signal.alarm(TEST_TIMEOUT_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    import jax

    devices = jax.devices("cpu")
    assert len(devices) >= 8, f"expected 8 virtual devices, got {len(devices)}"
    return devices


@pytest.fixture
def local_cluster():
    """A started single-node cluster, shut down after the test."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, resources={"TPU": 8})
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()
