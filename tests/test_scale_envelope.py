"""Scale-envelope push (VERDICT weak #3): 10-30x the sandbox envelope on
one core — >=160 virtual nodes, >=640 actors, >=500 placement groups —
asserting CORRECTNESS (everything registers/answers/places) and BOUNDED
MEMORY of delta resource sync (GCS RSS per heartbeating node) and the
hybrid scheduler (driver RSS per actor/PG).

Slow-marked: the legs are dominated by process spawn on a 1-core box
(each virtual node is a real node_main subprocess). The CLI twin is
``python tools/envelope_bench.py --profile scale`` which records the
same dimensions into ENVELOPE.json.
"""

from __future__ import annotations

import os
import signal
import sys

import pytest

pytestmark = pytest.mark.slow

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

NODES = int(os.environ.get("RAYT_SCALE_NODES", "160"))
ACTORS = int(os.environ.get("RAYT_SCALE_ACTORS", "640"))
PGS = int(os.environ.get("RAYT_SCALE_PGS", "500"))


@pytest.fixture(scope="module")
def scale_cluster():
    # the conftest SIGALRM budget (180s) is sized for tier-1 tests; this
    # module legitimately runs for tens of minutes on one core
    signal.alarm(0)
    os.environ.setdefault("RAYT_SITE_IMPORT", "lazy")
    # serialized spawn on 1 core: late members of a 640-actor fleet wait
    # minutes for their turn — measure capacity, not spawn latency
    os.environ.setdefault("RAYT_WORKER_STARTUP_TIMEOUT_S", "1800")
    os.environ.setdefault("RAYT_ACTOR_CREATION_PUSH_TIMEOUT_S", "2400")
    os.environ.setdefault("RAYT_LEASE_TIMEOUT_S", "600")

    import ray_tpu as rt
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_resources={"CPU": 4.0})
    try:
        yield cluster, rt
    finally:
        cluster.shutdown()


def _alarm(seconds: int):
    signal.alarm(seconds)


def test_scale_nodes_register_with_bounded_gcs_memory(scale_cluster):
    from envelope_bench import rss_kb

    cluster, rt = scale_cluster
    _alarm(1800)
    head_rss0 = rss_kb(cluster.head_proc.pid)
    for _ in range(NODES - 1):
        cluster.add_node(num_cpus=2, startup_timeout_s=120.0)
    view = cluster._cluster_view()
    alive = sum(1 for v in view.values() if v.get("alive"))
    assert alive >= NODES, f"only {alive}/{NODES} nodes alive"
    import time

    time.sleep(3.0)  # several delta-sync rounds at full cluster size
    per_node_kb = (rss_kb(cluster.head_proc.pid) - head_rss0) / NODES
    # delta resource sync must not hoard per-node history: the GCS pays
    # a node table entry + resource view per node, far under 2MB each
    assert per_node_kb < 2048, f"GCS grew {per_node_kb:.0f}KB per node"


def test_scale_actor_fleet_all_answer(scale_cluster):
    from envelope_bench import rss_kb

    cluster, rt = scale_cluster
    _alarm(2400)
    cluster.connect()

    @rt.remote(num_cpus=0.01)
    class Trivial:
        def ping(self):
            return 1

    rss0 = rss_kb()
    actors = [Trivial.remote() for _ in range(ACTORS)]
    assert all(rt.get([a.ping.remote() for a in actors], timeout=2000))
    per_actor_kb = (rss_kb() - rss0) / ACTORS
    for a in actors:
        rt.kill(a)
    # driver-side actor bookkeeping (handles, submitter state) stays
    # small per actor; worker processes live in their own RSS
    assert per_actor_kb < 512, f"driver grew {per_actor_kb:.0f}KB/actor"


def test_scale_placement_groups_reserve_and_release(scale_cluster):
    from envelope_bench import rss_kb

    cluster, rt = scale_cluster
    _alarm(1800)
    rss0 = rss_kb()
    pgs = [rt.placement_group([{"CPU": 0.01}], strategy="PACK")
           for _ in range(PGS)]
    assert all(pg.placement for pg in pgs), "unplaced PGs in storm"
    per_pg_kb = (rss_kb() - rss0) / PGS
    for pg in pgs:
        rt.remove_placement_group(pg)
    assert per_pg_kb < 256, f"driver grew {per_pg_kb:.0f}KB/PG"
    # hybrid scheduler correctness after the storm: resources released
    @rt.remote(num_cpus=1)
    def probe():
        return os.getpid()

    assert rt.get(probe.remote(), timeout=120) > 0
