"""Control-plane fastpath tests: function-table caching (serialize the
code blob once per (function, job); GCS KV as the miss path) and batched
lease grants (a submit burst costs O(burst/batch) request_lease RPCs,
surplus leases recycle through the warm pool).

Ref analogs: function export-once via GCS KV
(python/ray/_private/function_manager.py:58) and the per-SchedulingKey
lease pipeline (src/ray/core_worker/transport/normal_task_submitter.cc).
"""

import os
import time

import pytest

import ray_tpu as rt
from ray_tpu.api import _core_worker


def test_blob_cache_miss_recovers_via_gcs_kv():
    """A worker whose loaded-code LRU evicted a function (capacity 1
    here — the restart/spillback analog: the blob is gone locally and
    the owner connection will not re-piggyback it) recovers by fetching
    the blob from GCS KV. Runs FIRST in this module, before the shared
    cluster fixture exists — it boots its own cluster with the tiny
    cache."""
    os.environ["RAYT_FN_CACHE_SIZE"] = "1"
    try:
        rt.init(num_cpus=1, resources={"TPU": 8})
        try:
            @rt.remote
            def fa(x):
                return ("a", x)

            @rt.remote
            def fb(x):
                return ("b", x)

            # same worker (1 CPU, lease reuse): fa loads, fb evicts fa
            # (capacity 1), fa again arrives blob-less on a connection
            # that already pushed it -> GCS KV fetch or bust
            assert rt.get(fa.remote(1)) == ("a", 1)
            assert rt.get(fb.remote(2)) == ("b", 2)
            assert rt.get(fa.remote(3)) == ("a", 3)
            assert rt.get(fb.remote(4)) == ("b", 4)
        finally:
            rt.shutdown()
    finally:
        del os.environ["RAYT_FN_CACHE_SIZE"]


@pytest.fixture(scope="module")
def cluster():
    ctx = rt.init(num_cpus=8, resources={"TPU": 8})
    yield ctx
    rt.shutdown()


# ------------------------------------------------------- function table
def test_code_blob_serialized_once_per_function(cluster):
    """N submits of one function run dumps_code exactly once; a second
    function adds exactly one more table entry."""
    cw = _core_worker()

    @rt.remote
    def f(x):
        return x * 2

    @rt.remote
    def g(x):
        return x + 1

    before = cw.fn_table.dumps_count
    assert rt.get([f.remote(i) for i in range(50)]) == \
        [i * 2 for i in range(50)]
    assert cw.fn_table.dumps_count == before + 1, \
        "same function re-serialized on repeat submits"
    assert rt.get([f.remote(i) for i in range(50)]) == \
        [i * 2 for i in range(50)]
    assert cw.fn_table.dumps_count == before + 1
    assert rt.get(g.remote(1)) == 2
    assert cw.fn_table.dumps_count == before + 2


def test_code_blob_published_to_gcs_kv(cluster):
    """Every function id lands in the GCS fn_table KV namespace (the
    durable miss path for spillback/retry onto fresh workers)."""
    from ray_tpu.core.function_table import KV_NAMESPACE

    cw = _core_worker()

    @rt.remote
    def h(x):
        return x - 1

    assert rt.get(h.remote(5)) == 4
    fid, blob = cw.fn_table.register(h._fn, cw.job_id)
    got = None
    for _ in range(40):  # background publish: allow a few ms
        got = cw.io.run(cw.gcs.kv_get(fid, namespace=KV_NAMESPACE))
        if got is not None:
            break
        time.sleep(0.05)
    assert got == blob, "function blob not published to GCS KV"


# ------------------------------------------------------- batched leases
def test_burst_uses_batched_lease_requests(cluster):
    """A 500-task burst issues far fewer than 500 request_lease RPCs:
    the pool sizes batched requests to its queue depth and hot leases
    chain task-to-task without returning to the node manager."""
    cw = _core_worker()

    @rt.remote
    def tiny(x):
        return x

    rt.get(tiny.remote(0))  # warm the pool/worker
    before = cw.lease_rpcs_sent
    assert rt.get([tiny.remote(i) for i in range(500)]) == list(range(500))
    used = cw.lease_rpcs_sent - before
    assert used < 50, \
        f"500-task burst used {used} request_lease RPCs (want ≪ 500)"


def test_surplus_leases_recycle(cluster):
    """Tasks submitted right after a burst reuse the warm leases —
    zero additional request_lease round-trips."""
    cw = _core_worker()

    @rt.remote
    def tiny(x):
        return x

    rt.get([tiny.remote(i) for i in range(64)])
    time.sleep(0.1)  # let in-flight grants land as idle leases
    before = cw.lease_rpcs_sent
    assert rt.get([tiny.remote(i) for i in range(8)]) == list(range(8))
    assert cw.lease_rpcs_sent == before, \
        "post-burst tasks did not reuse warm leases"
