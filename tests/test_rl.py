"""RL library tests: env physics, GAE, fault-tolerant fleet, PPO learning
(ref analogs: rllib tests + tuned_examples learning assertions)."""

import numpy as np
import pytest

from ray_tpu.rl.env import CartPoleVectorEnv
from ray_tpu.rl.learner import compute_gae


def test_cartpole_env_basics():
    env = CartPoleVectorEnv(num_envs=4, seed=0)
    obs = env.reset(0)
    assert obs.shape == (4, 4)
    total_done = 0
    for _ in range(300):
        obs, rew, term, trunc, _ = env.step(np.random.randint(0, 2, 4))
        assert obs.shape == (4, 4) and rew.shape == (4,)
        total_done += int((term | trunc).sum())
    # random policy falls over well before 300 steps
    assert total_done > 0


def test_cartpole_balancing_vs_random():
    """A crude hand policy (push toward the pole lean) survives longer
    than random — sanity-checks the dynamics' sign conventions."""
    def run(policy):
        env = CartPoleVectorEnv(num_envs=8, seed=1)
        obs = env.reset(1)
        lengths = []
        steps = np.zeros(8)
        for _ in range(200):
            acts = policy(obs)
            obs, _, term, trunc, _ = env.step(acts)
            done = term | trunc
            steps += 1
            for i in np.nonzero(done)[0]:
                lengths.append(steps[i])
                steps[i] = 0
        return np.mean(lengths) if lengths else 200.0

    rng = np.random.RandomState(0)
    random_len = run(lambda obs: rng.randint(0, 2, len(obs)))
    lean_len = run(lambda obs: (obs[:, 2] > 0).astype(int))
    assert lean_len > random_len


def test_gae_matches_naive():
    T, N = 5, 2
    rng = np.random.RandomState(0)
    rewards = rng.randn(T, N).astype(np.float32)
    values = rng.randn(T, N).astype(np.float32)
    dones = np.zeros((T, N), bool)
    dones[2, 0] = True
    last = rng.randn(N).astype(np.float32)
    gamma, lam = 0.9, 0.8
    adv, ret = compute_gae(rewards, values, dones, last, gamma, lam)

    # naive per-env recursion
    for n in range(N):
        gae = 0.0
        next_v = last[n]
        expect = np.zeros(T)
        for t in range(T - 1, -1, -1):
            nonterm = 0.0 if dones[t, n] else 1.0
            delta = rewards[t, n] + gamma * next_v * nonterm - values[t, n]
            gae = delta + gamma * lam * nonterm * gae
            expect[t] = gae
            next_v = values[t, n]
        np.testing.assert_allclose(adv[:, n], expect, rtol=1e-5)
    np.testing.assert_allclose(ret, adv + values, rtol=1e-6)


def test_fault_tolerant_actor_manager(local_cluster):
    import ray_tpu as rt
    from ray_tpu.rl.actor_manager import FaultTolerantActorManager

    @rt.remote
    class W:
        def __init__(self):
            self.n = 0

        def work(self):
            self.n += 1
            return self.n

        def ping(self):
            return True

    actors = [W.remote() for _ in range(3)]
    mgr = FaultTolerantActorManager(actors)
    assert mgr.foreach(lambda a: a.work.remote()) == [1, 1, 1]
    rt.kill(actors[1])
    results = mgr.foreach(lambda a: a.work.remote(), timeout=30)
    assert len(results) == 2  # dead actor dropped, marked unhealthy
    assert mgr.num_healthy == 2
    results = mgr.foreach(lambda a: a.work.remote())
    assert len(results) == 2


def test_ppo_learns_cartpole(local_cluster):
    from ray_tpu.rl import PPOConfig

    algo = PPOConfig(
        num_env_runners=2, num_envs_per_runner=8,
        rollout_fragment_length=64, lr=1e-3, entropy_coeff=0.0,
        minibatch_size=256, num_epochs=6, seed=3).build()
    first = None
    best = 0.0
    for i in range(25):
        result = algo.train()
        ret = result["episode_return_mean"]
        if first is None and ret > 0:
            first = ret
        best = max(best, ret)
        if best >= 80.0 and i >= 4:
            break
    algo.stop()
    assert first is not None, "no episodes completed"
    assert best >= 80.0, f"PPO failed to learn: first={first} best={best}"
    assert best > 2 * min(first, 40.0)


def test_ppo_checkpoint_roundtrip(local_cluster, tmp_path):
    from ray_tpu.rl import PPOConfig

    algo = PPOConfig(num_env_runners=1, num_envs_per_runner=4,
                     rollout_fragment_length=16, seed=0).build()
    algo.train()
    path = algo.save_to_path(str(tmp_path / "ck"))
    it = algo._iteration
    algo.stop()

    algo2 = PPOConfig(num_env_runners=1, num_envs_per_runner=4,
                      rollout_fragment_length=16, seed=0).build()
    algo2.restore_from_path(path)
    assert algo2._iteration == it
    w1 = algo2._weights["pi"]["w"]
    result = algo2.train()
    assert result["training_iteration"] == it + 1
    algo2.stop()
