"""Streaming executor: pull-based operator pipeline over block refs (ref
analogs: data/_internal/execution/streaming_executor.py:48,
streaming_executor_state.py, operators/{map_operator,
task_pool_map_operator,actor_pool_map_operator}.py).

Map stages stream: at most `max_in_flight` block tasks are outstanding per
stage, so a long pipeline holds O(window) blocks in memory instead of the
whole dataset — the reference's backpressure idea without its resource
budgets. All-to-all stages (shuffle/sort/repartition) are barriers.
"""

from __future__ import annotations

import collections
import dataclasses
import random
from typing import Any, Callable, Iterator, Optional

import ray_tpu as rt
from ray_tpu.data.block import (Block, block_rows, concat_blocks,
                                from_batch, iter_rows, split_block,
                                to_batch)


@dataclasses.dataclass
class ActorPoolStrategy:
    """Actor-pool compute for map_batches. `size` is the fixed size when
    min/max are not given; with min_size/max_size the topology executor
    autoscales the pool with input-queue depth (ref:
    data/_internal/execution/autoscaler/)."""
    size: int = 2
    min_size: int | None = None
    max_size: int | None = None

    def __post_init__(self):
        if self.min_size is None:
            self.min_size = self.size
        if self.max_size is None:
            self.max_size = max(self.size, self.min_size)
        if self.min_size > self.max_size:
            raise ValueError(
                f"ActorPoolStrategy min_size={self.min_size} > "
                f"max_size={self.max_size}")


@dataclasses.dataclass
class MapSpec:
    kind: str                     # map | map_batches | filter | flat_map
    fn: Any                       # callable or class (for actor compute)
    batch_size: Optional[int] = None
    batch_format: str = "numpy"
    compute: Optional[ActorPoolStrategy] = None
    fn_constructor_args: tuple = ()
    fn_kwargs: dict = dataclasses.field(default_factory=dict)


def apply_map_spec(spec: MapSpec, fn, block: Block) -> Block:
    """Run one map stage over one block (inside a task/actor)."""
    from ray_tpu.data.block import batch_iter

    if spec.kind == "fused":
        # planner-fused chain: run every sub-stage in this one task
        for sub in spec.fn:
            block = apply_map_spec(sub, sub.fn, block)
        return block

    if spec.kind == "map":
        return [fn(row, **spec.fn_kwargs) for row in iter_rows(block)]
    if spec.kind == "filter":
        return [row for row in iter_rows(block) if fn(row, **spec.fn_kwargs)]
    if spec.kind == "flat_map":
        out: list = []
        for row in iter_rows(block):
            out.extend(fn(row, **spec.fn_kwargs))
        return out
    if spec.kind == "map_batches":
        outs = []
        for sub in batch_iter(block, spec.batch_size):
            batch = to_batch(sub, spec.batch_format)
            outs.append(from_batch(fn(batch, **spec.fn_kwargs)))
        if len(outs) == 1:
            return outs[0]
        return concat_blocks(outs)  # arrow-aware concat
    raise ValueError(f"unknown map kind {spec.kind!r}")


def _map_task(block: Block, spec: MapSpec) -> Block:
    return apply_map_spec(spec, spec.fn, block)


class _MapActor:
    """Actor-pool compute: constructs the callable once, reuses it per
    block (ref: actor_pool_map_operator.py)."""

    def __init__(self, spec: MapSpec):
        self.spec = spec
        fn = spec.fn
        if isinstance(fn, type):
            fn = fn(*spec.fn_constructor_args)
        self.fn = fn

    def apply(self, block: Block) -> Block:
        return apply_map_spec(self.spec, self.fn, block)


def _ship_spec_code(spec: MapSpec) -> None:
    """Register the spec's user code for by-value pickling. Fused specs hold
    a list of sub-specs in `fn`, so recurse rather than handing the list to
    ship_code_by_value (a list has no __module__ and would silently no-op)."""
    from ray_tpu._internal.serialization import ship_code_by_value

    if spec.kind == "fused":
        for sub in spec.fn:
            _ship_spec_code(sub)
    else:
        ship_code_by_value(spec.fn)


class StreamingExecutor:
    def __init__(self, max_in_flight: int = 8, execution_options=None):
        self.max_in_flight = max_in_flight
        self.execution_options = execution_options
        self.last_topology = None   # stats hook for tests/observability

    # --------------------------------------------------------- map pipeline
    def stream_pipeline(self, refs: Iterator, specs: list) -> Iterator:
        """Run consecutive map-family stages as one operator topology with
        per-op queues, backpressure budgets, and actor-pool autoscaling
        (data/streaming_executor.py)."""
        from ray_tpu.data.streaming_executor import (ExecutionOptions,
                                                     StreamingTopology)

        opts = self.execution_options or ExecutionOptions(
            max_in_flight=self.max_in_flight)
        topo = StreamingTopology(list(specs), iter(refs), opts)
        self.last_topology = topo
        return topo.run()

    # ------------------------------------------------------------- map stage
    def stream_map(self, refs: Iterator, spec: MapSpec) -> Iterator:
        """Single-stage convenience wrapper over the topology executor
        (kept as API; Dataset batches consecutive stages itself)."""
        return self.stream_pipeline(refs, [spec])

    # --------------------------------------------------------- all-to-all
    def repartition(self, refs: list, n: int) -> list:
        """Distributed repartition: count -> per-block slice tasks ->
        per-output concat tasks. No block ever lands on the driver (ref:
        data/_internal/planner/exchange/ split+merge task pattern)."""
        m = len(refs)
        if m == 0:
            return [rt.put([]) for _ in range(n)]

        def count(block: Block) -> int:
            return len(block_rows(block))

        count_task = rt.remote(num_cpus=0)(count)
        counts = rt.get([count_task.remote(r) for r in refs])
        total = sum(counts)
        # global row range of output partition j: [j*total//n, (j+1)*...)
        bounds = [(j * total) // n for j in range(n + 1)]
        offsets = [0]
        for c in counts:
            offsets.append(offsets[-1] + c)

        def slice_block(block: Block, start: int, cuts: list) -> list:
            rows = block_rows(block)
            return [rows[max(0, lo - start):max(0, hi - start)]
                    for lo, hi in cuts]

        slice_task = rt.remote(num_cpus=1, num_returns=n)(slice_block)
        parts = []
        for i, ref in enumerate(refs):
            cuts = [(bounds[j], bounds[j + 1]) for j in range(n)]
            result = slice_task.remote(ref, offsets[i], cuts)
            parts.append(result if isinstance(result, list) else [result])

        def merge(*shards: Block) -> Block:
            return concat_blocks(shards)

        merge_task = rt.remote(num_cpus=1)(merge)
        return [merge_task.remote(*[p[j] for p in parts]) for j in range(n)]

    def random_shuffle(self, refs: list, seed: Optional[int] = None) -> list:
        """Distributed shuffle: map each block into N shards, then N
        reduce tasks concatenate + locally shuffle their shard (ref:
        data/_internal/planner/exchange/)."""
        n = max(1, len(refs))

        def shard(block: Block, n: int, seed) -> list[Block]:
            rng = random.Random(seed)
            shards: list[Block] = [[] for _ in range(n)]
            for row in iter_rows(block):
                shards[rng.randrange(n)].append(row)
            return shards

        def reduce_shards(seed, *shards: Block) -> Block:
            out = concat_blocks(shards)
            random.Random(seed).shuffle(out)
            return out

        shard_task = rt.remote(num_cpus=1, num_returns=n)(shard)
        reduce_task = rt.remote(num_cpus=1)(reduce_shards)
        parts = []
        for i, ref in enumerate(refs):
            s = seed + i if seed is not None else None
            result = shard_task.remote(ref, n, s)
            parts.append(result if isinstance(result, list) else [result])
        out = []
        for j in range(n):
            s2 = seed + 10_000 + j if seed is not None else None
            out.append(reduce_task.remote(s2, *[p[j] for p in parts]))
        return out

    def sort(self, refs: list, key: Callable, descending: bool) -> list:
        """Distributed sample sort (ref: planner/exchange/sort_task_spec.py
        TaskBasedShuffle): per-block local sort + key sampling, driver sees
        ONLY the samples (tiny), range-partition tasks split each sorted
        block at the sample quantiles, merge tasks heapq-merge shards."""
        n = max(1, len(refs))
        if not refs:
            return []

        def sort_and_sample(block: Block, s: int) -> tuple:
            rows = sorted(block_rows(block), key=key, reverse=descending)
            step = max(1, len(rows) // s)
            return rows, [key(r) for r in rows[::step]]

        sas_task = rt.remote(num_cpus=1, num_returns=2)(sort_and_sample)
        sorted_refs, sample_refs = [], []
        for ref in refs:
            b, s = sas_task.remote(ref, 16)
            sorted_refs.append(b)
            sample_refs.append(s)
        samples = sorted(
            (x for sub in rt.get(sample_refs) for x in sub),
            reverse=descending)
        if not samples:  # every block empty
            return sorted_refs
        # n-1 partition boundaries at the sample quantiles
        bounds = [samples[(len(samples) * j) // n] for j in range(1, n)] \
            if samples else []

        def partition(rows: Block, bounds: list) -> list:
            import bisect

            keys = [key(r) for r in rows]
            if descending:  # bisect needs ascending; flip
                keys = [_Neg(k) for k in keys]
                bounds = [_Neg(b) for b in bounds]
            shards, lo = [], 0
            for b in bounds:
                hi = bisect.bisect_right(keys, b, lo)
                shards.append(rows[lo:hi])
                lo = hi
            shards.append(rows[lo:])
            return shards

        part_task = rt.remote(num_cpus=1, num_returns=n)(partition)
        parts = []
        for ref in sorted_refs:
            result = part_task.remote(ref, bounds)
            parts.append(result if isinstance(result, list) else [result])

        def merge(*shards: Block) -> Block:
            import heapq

            return list(heapq.merge(
                *[block_rows(s) for s in shards], key=key,
                reverse=descending))

        merge_task = rt.remote(num_cpus=1)(merge)
        return [merge_task.remote(*[p[j] for p in parts]) for j in range(n)]


class _Neg:
    """Order-reversing key wrapper for descending range partitioning."""

    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, other):
        return other.v < self.v

    def __eq__(self, other):
        return self.v == other.v
