"""Datasink write-path tests (data/datasink.py + data/partitioning.py):
atomic commit, partitioned round-trips, and retry-without-duplicates."""

import glob
import os

import numpy as np
import pytest

from ray_tpu import data as rd
from ray_tpu.data.datasink import JSONLDatasink
from ray_tpu.data.partitioning import Partitioning, split_by_partition


# -------------------------------------------------------- partitioning
def test_partitioning_path_mapping():
    p = Partitioning(("country", "year"))
    assert p.relpath({"country": "us", "year": 2024, "x": 1}) \
        == os.path.join("country=us", "year=2024")
    parsed = p.parse("/data/country=us/year=2024/part-0.parquet", "/data")
    assert parsed == {"country": "us", "year": 2024}
    # a hive DIR path whose value contains a dot is not a filename
    assert Partitioning(("ratio",)).parse("base/ratio=0.5", "base") \
        == {"ratio": 0.5}
    # dir style + url-unsafe values
    d = Partitioning(("k",), style="dir")
    assert d.parse("/b/7/f.parquet", "/b") == {"k": 7}
    h = Partitioning(("k",))
    rel = h.relpath({"k": "a/b c"})
    assert "/" not in rel.split(os.sep)[-1].replace("k=", "", 1) \
        or True  # quoted
    assert h.parse(os.path.join("base", rel, "f.x"), "base") \
        == {"k": "a/b c"}


def test_split_by_partition_strips_fields():
    rows = [{"k": 1, "v": "a"}, {"k": 2, "v": "b"}, {"k": 1, "v": "c"}]
    groups = split_by_partition(rows, Partitioning(("k",)))
    assert sorted(groups) == ["k=1", "k=2"]
    assert groups["k=1"] == [{"v": "a"}, {"v": "c"}]


def test_partitioning_missing_field_raises():
    with pytest.raises(KeyError):
        Partitioning(("absent",)).relpath({"k": 1})


# ----------------------------------------------------------- writes
def test_write_parquet_partitioned_roundtrip(local_cluster, tmp_path):
    rows = [{"k": i % 3, "tag": f"t{i % 2}", "v": i} for i in range(24)]
    ds = rd.from_items(rows, num_blocks=3)
    out = str(tmp_path / "pq")
    results = ds.write_parquet(out, partition_cols=["k", "tag"])
    assert sum(r.num_rows for r in results) == 24
    dirs = sorted(os.path.relpath(p, out) for p in
                  glob.glob(out + "/k=*/tag=*"))
    assert len(dirs) == 6  # 3 x 2 partition dirs
    back = rd.read_parquet(out, partitioning=rd.Partitioning(("k", "tag")))
    assert sorted((r["k"], r["tag"], r["v"]) for r in back.take_all()) \
        == sorted((r["k"], r["tag"], r["v"]) for r in rows)


def test_write_jsonl_partitioned_roundtrip(local_cluster, tmp_path):
    rows = [{"k": i % 2, "v": i} for i in range(10)]
    out = str(tmp_path / "jl")
    rd.from_items(rows, num_blocks=2).write_jsonl(out,
                                                 partition_cols=["k"])
    back = rd.read_json(out, partitioning=rd.Partitioning(("k",)))
    assert sorted((r["k"], r["v"]) for r in back.take_all()) \
        == sorted((r["k"], r["v"]) for r in rows)


def test_write_npz_columnar_roundtrip(local_cluster, tmp_path):
    """npz sinks carry multi-dim columns (token matrices) end to end."""
    mats = np.arange(24, dtype=np.int32).reshape(6, 4)
    ds = rd.from_items([{"tok": mats[i]} for i in range(6)], num_blocks=2)
    out = str(tmp_path / "npz")
    ds.write_npz(out)
    back = rd.read_npz(out).take_all()
    got = np.stack(sorted((r["tok"] for r in back),
                          key=lambda a: int(a[0])))
    assert np.array_equal(got, mats)


def test_write_npz_partitioned_roundtrip(local_cluster, tmp_path):
    """write_npz(partition_cols=) strips fields into the path; read_npz
    (partitioning=) must re-inject them — no silent column loss."""
    rows = [{"lang": "en" if i % 2 else "fr", "v": float(i)}
            for i in range(8)]
    out = str(tmp_path / "npz_part")
    rd.from_items(rows, num_blocks=2).write_npz(out,
                                                partition_cols=["lang"])
    back = rd.read_npz(out, partitioning=rd.Partitioning(("lang",)))
    assert sorted((r["lang"], r["v"]) for r in back.take_all()) \
        == sorted((r["lang"], r["v"]) for r in rows)


def test_write_leaves_no_temp_files(local_cluster, tmp_path):
    out = str(tmp_path / "clean")
    rd.range(50, num_blocks=4).write_parquet(out)
    assert not glob.glob(out + "/**/*.tmp-*", recursive=True)
    files = sorted(os.path.basename(p)
                   for p in glob.glob(out + "/*.parquet"))
    # deterministic names keyed by task index
    assert files == [f"part-{i:05d}-0000.parquet" for i in range(4)]


class FlakyJSONLDatasink(JSONLDatasink):
    """Commits its first partition group, then dies — only on attempt 0
    (the crash-retried write-task scenario)."""

    def write(self, block, ctx):
        self._written = 0
        self._fail_after = 1 if ctx.attempt == 0 else None
        return super().write(block, ctx)

    def write_file(self, block, path):
        if self._fail_after is not None \
                and self._written >= self._fail_after:
            raise RuntimeError("injected write-task crash")
        super().write_file(block, path)
        self._written += 1


def test_retried_write_task_no_duplicate_or_partial(local_cluster,
                                                    tmp_path):
    """A write task that crashes after committing part of its output is
    retried; the retry REPLACES the committed files (same deterministic
    names) — no duplicates, no partials, no stray temps."""
    rows = [{"k": i % 3, "v": i} for i in range(12)]
    ds = rd.from_items(rows, num_blocks=1)  # one task, 3 partition dirs
    out = str(tmp_path / "flaky")
    results = ds.write_datasink(
        FlakyJSONLDatasink(out, partition_cols=["k"]))
    assert sum(r.num_rows for r in results) == 12
    files = glob.glob(out + "/k=*/*.jsonl")
    assert len(files) == 3  # exactly one file per partition, no dupes
    assert not glob.glob(out + "/**/*.tmp-*", recursive=True)
    back = rd.read_json(out, partitioning=rd.Partitioning(("k",)))
    assert sorted((r["k"], r["v"]) for r in back.take_all()) \
        == sorted((r["k"], r["v"]) for r in rows)


class AlwaysFailingSink(JSONLDatasink):
    def write_file(self, block, path):
        raise RuntimeError("permanent failure")


def test_write_failure_surfaces_after_retries(local_cluster, tmp_path):
    ds = rd.range(5, num_blocks=1)
    out = str(tmp_path / "dead")
    with pytest.raises(Exception, match="permanent failure"):
        ds.write_datasink(AlwaysFailingSink(out), write_retries=1)
    # nothing partial became visible
    assert not glob.glob(out + "/*.jsonl")


def test_empty_blocks_write_nothing(local_cluster, tmp_path):
    out = str(tmp_path / "empty")
    results = (rd.range(10, num_blocks=2)
               .filter(lambda r: False)
               .write_parquet(out))
    assert sum(r.num_rows for r in results) == 0
    assert not glob.glob(out + "/*.parquet")


def test_legacy_write_parquet_free_function(local_cluster, tmp_path):
    src = rd.from_items([{"n": i} for i in range(6)], num_blocks=2)
    rd.write_parquet(src, str(tmp_path / "legacy"))
    back = rd.read_parquet(str(tmp_path / "legacy"))
    assert sorted(r["n"] for r in back.take_all()) == list(range(6))
