"""Chaos harness — schedule-driven fault injection for recovery drills
(ref analog: the reference's chaos-testing utilities,
python/ray/_private/test_utils.py get_and_run_resource_killer and
release/nightly chaos_test suites: kill nodes/actors on a cadence under
load, then assert the workload's recovery SLOs).

Fault primitives cover the planes this runtime can lose:

* ``kill_actor`` / ``kill_random_actor`` — a worker actor (restartable
  actors exercise GCS auto-restart; DAG ring runners exercise
  recompile-and-resume, dag/recovery.py);
* ``kill_worker_node`` — SIGKILL a node manager (sudden node loss:
  lineage re-execution, lease revocation, object recovery);
* ``drain_node`` — deadline-bound graceful drain (planned preemption:
  make-before-break actor migration, serve replica handoff, PG gang
  rescheduling, object evacuation — the node ends DRAINED, not DEAD);
* ``bounce_head`` — SIGKILL + same-port restart of the GCS (head HA:
  snapshot reload, client reconnect, serve controller checkpoint);
* ``kill_serve_controller`` — the serve control plane (handles keep
  routing on their last table and self-heal the controller, which
  restores its GCS checkpoint).

Used three ways: tests/test_chaos.py (tier-1 smoke legs), ``python
tools/envelope_bench.py --only chaos`` (the full schedule under load,
SLOs recorded in ENVELOPE.json), or interactively::

    monkey = ChaosMonkey(cluster)
    monkey.at(2.0, monkey.kill_random_actor, runners)
    monkey.at(5.0, monkey.kill_serve_controller)
    monkey.start()
    ... drive load ...
    monkey.stop()
    assert all(e["ok"] for e in monkey.log)
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Optional

__all__ = ["ChaosMonkey"]


class ChaosMonkey:
    """Runs a schedule of fault injections on a background thread and
    keeps a structured log of what it killed and when, so tests can
    correlate observed recoveries with injected faults."""

    def __init__(self, cluster=None, *, seed: int = 0):
        self.cluster = cluster            # cluster_utils.Cluster or None
        self.rng = random.Random(seed)
        # one row per fired fault: {"t", "fault", "ok", "detail"|"error"}
        self.log: list[dict] = []
        self._events: list[tuple[float, str, Callable[[], Any]]] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------- fault primitives
    def kill_actor(self, handle, *, no_restart: bool = False) -> str:
        """SIGKILL-equivalent actor death (rt.kill). With
        ``no_restart=False`` a ``max_restarts`` actor comes back via the
        GCS restart path — the fault recovery code must survive, not a
        permanent capacity loss."""
        import ray_tpu as rt

        rt.kill(handle, no_restart=no_restart)
        return handle._actor_id.hex()

    def kill_random_actor(self, handles: list, *,
                          no_restart: bool = False) -> str:
        return self.kill_actor(self.rng.choice(list(handles)),
                               no_restart=no_restart)

    def kill_named_actor(self, name: str, *,
                         no_restart: bool = True) -> str:
        import ray_tpu as rt

        return self.kill_actor(rt.get_actor(name), no_restart=no_restart)

    def kill_serve_controller(self) -> str:
        """Kill the serve control plane. Replicas are NOT owned by the
        controller, so the data plane keeps serving; a surviving handle
        recreates the controller, which restores its GCS checkpoint and
        ADOPTS the live replicas (serve/controller.py)."""
        from ray_tpu.serve.controller import CONTROLLER_NAME

        return self.kill_named_actor(CONTROLLER_NAME, no_restart=True)

    def kill_worker_node(self, index: Optional[int] = None) -> str:
        """Sudden node loss (SIGKILL the node manager): every worker on
        it dies, shm objects on it are gone — lineage re-execution and
        actor restarts must cover."""
        if self.cluster is None or not self.cluster.worker_nodes:
            raise RuntimeError("no worker nodes to kill")
        nodes = self.cluster.worker_nodes
        handle = (self.rng.choice(nodes) if index is None
                  else nodes[index])
        self.cluster.remove_node(handle, graceful=False)
        return handle.node_id_hex

    def drain_node(self, index: Optional[int] = None, *,
                   deadline_s: Optional[float] = None,
                   reason: str = "chaos drain") -> str:
        """Graceful drain (the preemption-notice path minus the notice
        file): placement stops, workloads migrate make-before-break,
        the node ends DRAINED — the opposite contract to
        kill_worker_node, which tests the unplanned-loss paths."""
        import ray_tpu as rt

        if self.cluster is None or not self.cluster.worker_nodes:
            raise RuntimeError("no worker nodes to drain")
        nodes = self.cluster.worker_nodes
        handle = (self.rng.choice(nodes) if index is None
                  else nodes[index])
        if not rt.drain_node(handle.node_id_hex, deadline_s, reason):
            raise RuntimeError(f"drain of {handle.node_id_hex} rejected")
        return handle.node_id_hex

    def bounce_head(self, down_s: float = 0.5) -> str:
        """SIGKILL the head (GCS) and restart it on the SAME port after
        ``down_s``: clients/nodes ride their reconnect loops, the GCS
        reloads its snapshot, serve handles full-resync their tables."""
        if self.cluster is None:
            raise RuntimeError("bounce_head needs a Cluster handle")
        self.cluster.kill_head(graceful=False)
        time.sleep(down_s)
        self.cluster.restart_head()
        return f"gcs:{self.cluster.gcs_port}"

    # ---------------------------------------------------------- schedule
    def at(self, t_s: float, fault: Callable, *args,
           **kwargs) -> "ChaosMonkey":
        """Fire ``fault(*args, **kwargs)`` ``t_s`` seconds after
        start(); chainable."""
        label = getattr(fault, "__name__", str(fault))
        self._events.append(
            (float(t_s), label, lambda: fault(*args, **kwargs)))
        return self

    def every(self, period_s: float, count: int, fault: Callable, *args,
              start_s: Optional[float] = None, **kwargs) -> "ChaosMonkey":
        """``count`` firings, one per ``period_s``, first at ``start_s``
        (default: one period in)."""
        t = period_s if start_s is None else start_s
        for _ in range(count):
            self.at(t, fault, *args, **kwargs)
            t += period_s
        return self

    def start(self) -> "ChaosMonkey":
        if self._thread is not None:
            raise RuntimeError("chaos schedule already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="chaos-monkey", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 30.0):
        """Stop firing further faults and wait for the thread; faults
        already injected are NOT undone."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def join(self, timeout: float = 600.0):
        """Wait for the whole schedule to finish firing."""
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    # ---------------------------------------------------------- internals
    def _run(self):
        t0 = time.monotonic()
        for at_s, label, fire in sorted(self._events, key=lambda e: e[0]):
            delay = at_s - (time.monotonic() - t0)
            if delay > 0 and self._stop.wait(delay):
                return
            if self._stop.is_set():
                return
            row = {"t": round(time.monotonic() - t0, 3), "fault": label}
            try:
                row["detail"] = fire()
                row["ok"] = True
            except Exception as e:  # record honestly; keep the schedule
                row["ok"] = False
                row["error"] = f"{type(e).__name__}: {e}"
            self.log.append(row)
