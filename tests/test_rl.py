"""RL library tests: env physics, GAE, fault-tolerant fleet, PPO learning
(ref analogs: rllib tests + tuned_examples learning assertions)."""

import numpy as np
import pytest

from ray_tpu.rl.env import CartPoleVectorEnv
from ray_tpu.rl.learner import compute_gae


def test_cartpole_env_basics():
    env = CartPoleVectorEnv(num_envs=4, seed=0)
    obs = env.reset(0)
    assert obs.shape == (4, 4)
    total_done = 0
    for _ in range(300):
        obs, rew, term, trunc, _ = env.step(np.random.randint(0, 2, 4))
        assert obs.shape == (4, 4) and rew.shape == (4,)
        total_done += int((term | trunc).sum())
    # random policy falls over well before 300 steps
    assert total_done > 0


def test_cartpole_balancing_vs_random():
    """A crude hand policy (push toward the pole lean) survives longer
    than random — sanity-checks the dynamics' sign conventions."""
    def run(policy):
        env = CartPoleVectorEnv(num_envs=8, seed=1)
        obs = env.reset(1)
        lengths = []
        steps = np.zeros(8)
        for _ in range(200):
            acts = policy(obs)
            obs, _, term, trunc, _ = env.step(acts)
            done = term | trunc
            steps += 1
            for i in np.nonzero(done)[0]:
                lengths.append(steps[i])
                steps[i] = 0
        return np.mean(lengths) if lengths else 200.0

    rng = np.random.RandomState(0)
    random_len = run(lambda obs: rng.randint(0, 2, len(obs)))
    lean_len = run(lambda obs: (obs[:, 2] > 0).astype(int))
    assert lean_len > random_len


def test_gae_matches_naive():
    T, N = 5, 2
    rng = np.random.RandomState(0)
    rewards = rng.randn(T, N).astype(np.float32)
    values = rng.randn(T, N).astype(np.float32)
    dones = np.zeros((T, N), bool)
    dones[2, 0] = True
    last = rng.randn(N).astype(np.float32)
    gamma, lam = 0.9, 0.8
    adv, ret = compute_gae(rewards, values, dones, last, gamma, lam)

    # naive per-env recursion
    for n in range(N):
        gae = 0.0
        next_v = last[n]
        expect = np.zeros(T)
        for t in range(T - 1, -1, -1):
            nonterm = 0.0 if dones[t, n] else 1.0
            delta = rewards[t, n] + gamma * next_v * nonterm - values[t, n]
            gae = delta + gamma * lam * nonterm * gae
            expect[t] = gae
            next_v = values[t, n]
        np.testing.assert_allclose(adv[:, n], expect, rtol=1e-5)
    np.testing.assert_allclose(ret, adv + values, rtol=1e-6)


def test_fault_tolerant_actor_manager(local_cluster):
    import ray_tpu as rt
    from ray_tpu.rl.actor_manager import FaultTolerantActorManager

    @rt.remote
    class W:
        def __init__(self):
            self.n = 0

        def work(self):
            self.n += 1
            return self.n

        def ping(self):
            return True

    actors = [W.remote() for _ in range(3)]
    mgr = FaultTolerantActorManager(actors)
    assert mgr.foreach(lambda a: a.work.remote()) == [1, 1, 1]
    rt.kill(actors[1])
    results = mgr.foreach(lambda a: a.work.remote(), timeout=30)
    assert len(results) == 2  # dead actor dropped, marked unhealthy
    assert mgr.num_healthy == 2
    results = mgr.foreach(lambda a: a.work.remote())
    assert len(results) == 2


def test_ppo_learns_cartpole(local_cluster):
    from ray_tpu.rl import PPOConfig

    algo = PPOConfig(
        num_env_runners=2, num_envs_per_runner=8,
        rollout_fragment_length=64, lr=1e-3, entropy_coeff=0.0,
        minibatch_size=256, num_epochs=6, seed=3).build()
    first = None
    best = 0.0
    for i in range(25):
        result = algo.train()
        ret = result["episode_return_mean"]
        if first is None and ret > 0:
            first = ret
        best = max(best, ret)
        if best >= 80.0 and i >= 4:
            break
    algo.stop()
    assert first is not None, "no episodes completed"
    assert best >= 80.0, f"PPO failed to learn: first={first} best={best}"
    assert best > 2 * min(first, 40.0)


def test_ppo_checkpoint_roundtrip(local_cluster, tmp_path):
    from ray_tpu.rl import PPOConfig

    algo = PPOConfig(num_env_runners=1, num_envs_per_runner=4,
                     rollout_fragment_length=16, seed=0).build()
    algo.train()
    path = algo.save_to_path(str(tmp_path / "ck"))
    it = algo._iteration
    algo.stop()

    algo2 = PPOConfig(num_env_runners=1, num_envs_per_runner=4,
                      rollout_fragment_length=16, seed=0).build()
    algo2.restore_from_path(path)
    assert algo2._iteration == it
    w1 = algo2._weights["pi"]["w"]
    result = algo2.train()
    assert result["training_iteration"] == it + 1
    algo2.stop()


def test_vtrace_on_policy_reduces_to_returns():
    """With target == behavior policy and rho/c clips inactive, vs equals
    the discounted TD(lambda=1)-style corrected values; sanity-check the
    recursion against a tiny hand-rolled rollout."""
    import jax.numpy as jnp

    from ray_tpu.rl.vtrace import vtrace

    T, B = 4, 1
    logp = np.log(np.full((T, B), 0.5, np.float32))
    rewards = np.ones((T, B), np.float32)
    values = np.zeros((T, B), np.float32)
    boot = np.zeros((B,), np.float32)
    dones = np.zeros((T, B), np.float32)
    vs, pg_adv = vtrace(jnp.asarray(logp), jnp.asarray(logp),
                        jnp.asarray(rewards), jnp.asarray(values),
                        jnp.asarray(boot), jnp.asarray(dones),
                        jnp.zeros((T, B), jnp.float32), gamma=1.0)
    # on-policy, V=0, gamma=1: vs_t = sum of future rewards
    np.testing.assert_allclose(np.asarray(vs)[:, 0], [4, 3, 2, 1], atol=1e-5)
    np.testing.assert_allclose(np.asarray(pg_adv)[:, 0], [4, 3, 2, 1],
                               atol=1e-5)


def test_vtrace_done_cuts_bootstrap():
    import jax.numpy as jnp

    from ray_tpu.rl.vtrace import vtrace

    T, B = 3, 1
    logp = np.zeros((T, B), np.float32)
    rewards = np.ones((T, B), np.float32)
    values = np.zeros((T, B), np.float32)
    dones = np.array([[0.0], [1.0], [0.0]], np.float32)
    vs, _ = vtrace(jnp.asarray(logp), jnp.asarray(logp),
                   jnp.asarray(rewards), jnp.asarray(values),
                   jnp.asarray(np.full((B,), 100.0, np.float32)),
                   jnp.asarray(dones), jnp.zeros((T, B), jnp.float32),
                   gamma=1.0)
    # episode ends at t=1: vs[0] = r0 + r1 = 2 (no leak across the cut);
    # vs[2] bootstraps into the final value
    np.testing.assert_allclose(np.asarray(vs)[:, 0], [2.0, 1.0, 101.0],
                               atol=1e-5)


def test_impala_learns_cartpole(local_cluster):
    """Learning-curve gate (ref: rllib tuned_examples --as-test): IMPALA
    must reach a mean return well above the random baseline (~20).

    Doubles as the compiled-DAG plane + throughput gate: the loop must
    ride the channel DAG (Podracer Sebulba shape — no per-call
    fallback) and sustain committed env-steps/s + learner-updates/s
    floors across the learning run (measured ~1270 steps/s / ~1.2
    updates/s on a loaded 1-core CI box; floors sit ~5x below)."""
    import time

    from ray_tpu.dag.channel_exec import ChannelCompiledDAG
    from ray_tpu.rl import IMPALA, IMPALAConfig

    algo = IMPALAConfig(
        env="CartPole-v1", num_env_runners=2, num_envs_per_runner=8,
        rollout_fragment_length=64, train_batch_size=512, vf_coeff=0.25,
        lr=1e-3, entropy_coeff=0.01, seed=1).build()
    best = 0.0
    try:
        assert isinstance(algo._dag.dag, ChannelCompiledDAG), \
            "IMPALA fell back off the compiled-DAG plane"
        assert algo._dag.channel_kinds["shm"] > 0
        # device edges are ON by default (ISSUE 12): agg→learner
        # batches, learner→driver weights, and the weight-broadcast
        # input edges all ride the raw-shard-bytes framing
        assert algo._dag.channel_kinds["device"] > 0, \
            algo._dag.channel_kinds
        algo.train()                      # warmup (jit compile)
        s0 = algo._total_steps
        t0 = time.perf_counter()
        updates = 0
        for _ in range(40):
            result = algo.train()
            updates += result["num_learner_updates"]
            best = max(best, result["episode_return_mean"])
            if best >= 100.0:
                break
        dt = time.perf_counter() - t0
        assert best >= 100.0, f"IMPALA failed to learn: best={best}"
        steps_per_s = (algo._total_steps - s0) / dt
        assert steps_per_s >= 250.0, \
            f"IMPALA-on-DAG env throughput regressed: {steps_per_s:.0f}/s"
        assert updates / dt >= 0.25, \
            f"IMPALA-on-DAG update rate regressed: {updates / dt:.2f}/s"
        # zero-host-pickle acceptance: the steady-state tick path
        # actually shipped weight arrays through the device framing —
        # the driver-side input wrappers counted packed jax leaves
        # (learning happened, so broadcasts happened), and
        # pack_device_tree leaves no jax.Array for pickle to see
        # (tests/test_dag_device.py asserts the pack coverage itself)
        import jax

        import ray_tpu as rt
        from ray_tpu.dag.device_channel import pack_device_tree

        dev_inputs = algo._dag.dag._device_input_channels
        assert dev_inputs, "weight-broadcast edges are not device-kind"
        assert sum(ch.device_arrays for ch in dev_inputs) > 0, \
            "no weight arrays rode the device framing"
        w = rt.get(algo._learner.get_weights.remote(), timeout=60)
        packed, n = pack_device_tree(
            jax.tree.map(jax.numpy.asarray, w))
        assert n == len(jax.tree.leaves(w))    # full pack coverage
    finally:
        algo.stop()


def test_replay_buffer_ring_and_sampling():
    from ray_tpu.rl.replay import ReplayBuffer

    buf = ReplayBuffer(capacity=10, seed=0)
    buf.add({"x": np.arange(6, dtype=np.float32),
             "a": np.arange(6, dtype=np.int32)})
    assert buf.size() == 6
    buf.add({"x": np.arange(6, 14, dtype=np.float32),
             "a": np.arange(6, 14, dtype=np.int32)})
    assert buf.size() == 10  # capacity-capped ring
    s = buf.sample(4)
    assert s["x"].shape == (4,) and s["a"].shape == (4,)
    np.testing.assert_array_equal(s["x"].astype(np.int32), s["a"])
    # the oldest entries (0..3) were overwritten by the wrap
    many = buf.sample(10)
    assert many["x"].min() >= 4.0
    assert buf.sample(11) is None


def test_dqn_learns_cartpole(local_cluster):
    """Learning gate (ref: rllib tuned_examples --as-test thresholds)."""
    from ray_tpu.rl.dqn import DQNConfig

    algo = DQNConfig(
        env="CartPole-v1", num_env_runners=2, num_envs_per_runner=8,
        rollout_fragment_length=32, learning_starts=500,
        train_batch_size=128, updates_per_iteration=48,
        target_update_freq=50, epsilon_decay_steps=4000,
        lr=1e-3, seed=0).build()
    first, best = None, -1.0
    try:
        for i in range(70):
            result = algo.train()
            ret = result["episode_return_mean"]
            if ret is not None:
                if first is None:
                    first = ret
                best = max(best, ret)
            if best >= 120.0:
                break
    finally:
        algo.stop()
    assert first is not None, "no episodes completed"
    assert best >= 120.0, f"DQN failed to learn: first={first} best={best}"


# ----------------------------------------------------- image RL (round 4)
def test_catch_env_mechanics():
    from ray_tpu.rl.env import CatchVectorEnv

    env = CatchVectorEnv(num_envs=4, seed=0)
    obs = env.reset(0)
    assert obs.shape == (4, 10, 10, 1)
    assert obs.sum(axis=(1, 2, 3)).max() <= 2.0  # fruit + paddle pixels
    total_reward = np.zeros(4)
    dones = 0
    for _ in range(30):
        obs, r, term, trunc, _ = env.step(np.ones(4, np.int64))  # stay
        total_reward += r
        dones += int(term.sum())
    assert dones >= 4  # fruit lands within GRID steps, episodes recycle
    assert np.all(np.abs(total_reward) >= 1.0)  # every env saw an outcome


def test_cnn_module_forward_and_grad():
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.rl import module as rlm

    cfg = rlm.CNNModuleConfig(obs_shape=(10, 10, 1), num_actions=3)
    params = rlm.init_params(cfg, jax.random.PRNGKey(0))
    obs = jnp.zeros((5, 10, 10, 1), jnp.float32)
    logits, value = rlm.forward(params, obs)
    assert logits.shape == (5, 3) and value.shape == (5,)

    # optimizer round-trip: conv stride metadata must be invisible to
    # gradients/updates (static pytree node)
    opt = optax.adam(1e-3)
    state = opt.init(params)

    def loss(p):
        lg, v = rlm.forward(p, obs)
        return (lg ** 2).mean() + (v ** 2).mean()

    grads = jax.grad(loss)(params)
    updates, state = opt.update(grads, state, params)
    new_params = optax.apply_updates(params, updates)
    assert new_params["conv"][0]["meta"].stride == 2

    # sampling path used by env runners
    a, logp, v = rlm.sample_actions(params, np.zeros((3, 10, 10, 1),
                                                     np.float32),
                                    jax.random.PRNGKey(1))
    assert a.shape == (3,) and logp.shape == (3,)


def test_connector_pipeline():
    from ray_tpu.rl.connectors import (ConnectorPipeline, FlattenObs,
                                       NormalizeImage)

    pipe = ConnectorPipeline([NormalizeImage(), FlattenObs()])
    obs = np.full((2, 4, 4, 1), 255, np.uint8)
    out = pipe(obs)
    assert out.shape == (2, 16)
    assert out.dtype == np.float32 and float(out.max()) == 1.0


def test_impala_learns_catch_with_cnn(local_cluster):
    """Config #4 shape at CI scale: image observations stream from the
    runner fleet into a CNN V-trace learner; mean return must clear a
    committed threshold well above the random policy (~-0.8)."""
    from ray_tpu.rl.impala import IMPALAConfig
    from ray_tpu.rl.module import CNNModuleConfig

    algo = IMPALAConfig(
        env="Catch-v0", num_env_runners=2, num_envs_per_runner=16,
        rollout_fragment_length=32, train_batch_size=1024,
        # fine iteration granularity: the break-on-threshold check below
        # runs every 2 updates, so the CNN learner does little work past
        # the committed bar (keeps the test inside its CI budget)
        min_updates_per_iteration=2,
        lr=3e-3, entropy_coeff=0.01, seed=0).build()
    assert isinstance(algo.module_cfg, CNNModuleConfig)
    try:
        first = None
        best = -1.0
        for _ in range(60):
            result = algo.train()
            if first is None and result["episode_return_mean"] != 0.0:
                first = result["episode_return_mean"]
            best = max(best, result["episode_return_mean"])
            if best >= -0.2:
                break
        # random policy sits at ~-0.8; the committed CI threshold is a
        # clear learning signal within the test budget (the full curve to
        # >=+0.8 is committed by tools/rl_image_bench.py at bench scale)
        assert best >= -0.2, \
            f"CNN IMPALA failed to learn Catch: best={best} first={first}"
    finally:
        algo.stop()


def test_appo_learns(local_cluster):
    """APPO (ref: algorithms/appo): IMPALA's async pipeline with the
    clipped-surrogate objective learns CartPole."""
    from ray_tpu.rl import APPOConfig

    algo = APPOConfig(
        env="CartPole-v1", num_env_runners=2, num_envs_per_runner=4,
        rollout_fragment_length=32, train_batch_size=512,
        call_timeout_s=600.0, seed=0).build()
    try:
        first = algo.train()
        last = first
        # 5 more iterations at min_updates_per_iteration=4 ≈ 24 learner
        # updates on the compiled-DAG plane — the curve moves decisively
        # (measured ~22 → ~33-42 mean return) where the old per-call
        # loop barely budged in 9 iterations
        for _ in range(5):
            last = algo.train()
        assert last["episode_return_mean"] > first["episode_return_mean"]
        assert last["num_env_steps_sampled"] > 0
    finally:
        algo.stop()
