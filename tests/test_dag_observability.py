"""Execution-plane observability: DAG/channel introspection, stall
attribution, and per-tick tracing (ref analogs: the reference's
dashboard/state-API execution visibility — gcs_task_manager.h shape
applied to compiled graphs)."""

from __future__ import annotations

import time

import pytest


# ---------------------------------------------------------------- units
def test_channel_stats_counters():
    """ShmChannel instrumentation: tick/byte counters, occupancy, and
    blocked-time accumulation on both the full-ring and empty-ring
    parks."""
    import numpy as np

    from ray_tpu.dag.channel import ShmChannel

    prod = ShmChannel.create(slot_size=1 << 16, n_slots=2)
    cons = ShmChannel.attach(prod.spec)
    try:
        prod.write({"x": 1})
        prod.write(np.arange(8))
        assert prod.stats.writes == 2
        assert prod.stats.bytes_written > 0
        assert prod.occupancy() == 2
        # ring full: a bounded write park accumulates write_block_s
        with pytest.raises(TimeoutError):
            prod.write_bytes(b"z", timeout=0.15)
        assert prod.stats.write_block_s >= 0.1
        assert prod.stats.write_blocked_since is None  # park ended
        v = cons.read()
        assert v == {"x": 1}
        arr = cons.read()
        assert list(arr[:8].tobytes()) or True  # view alive
        assert cons.stats.reads == 2
        assert cons.stats.bytes_read == prod.stats.bytes_written
        assert cons.stats.pins_sealed >= 1       # numpy view aliased slot
        assert cons.pinned_slots() >= 1
        snap = cons.snapshot()
        assert snap["n_slots"] == 2 and snap["reads"] == 2
        # empty ring: a bounded read park accumulates read_block_s
        with pytest.raises(TimeoutError):
            cons.read_bytes(timeout=0.15)
        assert cons.stats.read_block_s >= 0.1
        del arr
    finally:
        cons.close()
        prod.close()


def test_gcs_dag_manager_unit():
    """Register → report → stall attribution → teardown clears; per-job
    eviction with dropped accounting; derived metric records."""
    from ray_tpu.core.gcs_dag_manager import GcsDagManager

    states = {"aaaa": "ALIVE", "bbbb": "DEAD"}
    m = GcsDagManager(max_dags=2, stall_grace_s=1.0,
                      actor_state=lambda h: states.get(h))
    m.ingest({"kind": "register", "dag_id": "d1", "job_id": "j1",
              "driver": "w0", "ts": 1.0,
              "channel_kinds": {"shm": 2, "dcn": 0},
              "edges": [
                  {"edge": "e0", "channel": "c0", "kind": "shm",
                   "n_slots": 4, "slot_size": 1024, "role": "edge",
                   "producer": {"actor": "bbbb", "label": "Runner:bbbb"},
                   "consumer": {"actor": "aaaa", "label": "Sink:aaaa"}},
                  {"edge": "e1", "channel": "c1", "kind": "shm",
                   "n_slots": 4, "slot_size": 1024, "role": "output",
                   "producer": {"actor": "aaaa", "label": "Sink:aaaa"},
                   "consumer": {"actor": "", "label": "driver"}},
              ]})
    # healthy report: deltas accumulate, no stall
    m.ingest({"kind": "report", "dag_id": "d1", "ts": 2.0, "channels": {
        "c0": {"role": "producer", "writes": 5, "bytes_written": 500,
               "write_block_s": 0.0, "write_blocked_s_now": 0.0},
        "c0#c": {"role": "consumer"},  # unknown channel key: ignored
    }})
    m.ingest({"kind": "report", "dag_id": "d1", "ts": 2.0, "channels": {
        "c0": {"role": "consumer", "reads": 5, "read_block_s": 0.2,
               "read_blocked_s_now": 0.0, "occupancy": 1,
               "pinned_slots": 0, "gc_nudges": 0},
    }})
    rec = m.list(dag_id="d1")["dags"][0]
    edge = next(e for e in rec["edges"] if e["edge"] == "e0")
    assert edge["ticks"] == 5 and edge["bytes"] == 500
    assert edge["reads"] == 5 and edge["stall"] is None
    recs = m.drain_metric_records()
    names = {r["name"] for r in recs}
    assert "rayt_dag_ticks_total" in names
    assert "rayt_dag_bytes_total" in names
    assert "rayt_dag_ring_occupancy" in names
    assert "rayt_dag_stalled_edges" in names
    tick_rec = next(r for r in recs
                    if r["name"] == "rayt_dag_ticks_total")
    assert tick_rec["value"] == 5.0
    assert tick_rec["tags"] == {"dag": "d1", "edge": "e0"}

    # consumer parked past grace on e0: culprit is the PRODUCER, whose
    # actor is DEAD -> dead peer named
    m.ingest({"kind": "report", "dag_id": "d1", "ts": 3.0, "channels": {
        "c0": {"role": "consumer", "reads": 5, "read_block_s": 1.7,
               "read_blocked_s_now": 1.5, "occupancy": 0,
               "pinned_slots": 0, "gc_nudges": 0},
    }})
    rec = m.list(dag_id="d1", stalled_only=True)["dags"][0]
    edge = next(e for e in rec["edges"] if e["edge"] == "e0")
    assert edge["stall"]["blocked"] == "read"
    assert edge["stall"]["culprit"] == "Runner:bbbb"
    assert edge["stall"]["dead_peer"] == "bbbb"
    assert rec["stalled_edges"] == ["e0"]
    assert m.num_stalled_edges() == 1
    summ = m.summarize()
    assert summ["totals"]["stalled_edges"] == 1
    assert summ["stalls"][0]["dead_peer"] == "bbbb"
    gauge = [r for r in m.drain_metric_records()
             if r["name"] == "rayt_dag_stalled_edges"]
    assert gauge and gauge[-1]["value"] == 1.0

    # producer parked on a FULL ring points at the CONSUMER (alive)
    m.ingest({"kind": "report", "dag_id": "d1", "ts": 4.0, "channels": {
        "c1": {"role": "producer", "writes": 5, "bytes_written": 10,
               "write_block_s": 2.0, "write_blocked_s_now": 2.0},
    }})
    rec = m.list(dag_id="d1")["dags"][0]
    e1 = next(e for e in rec["edges"] if e["edge"] == "e1")
    assert e1["stall"]["blocked"] == "write"
    assert e1["stall"]["culprit"] == "driver"
    assert e1["stall"]["dead_peer"] == ""

    # teardown clears every stall flag and marks the record
    m.ingest({"kind": "teardown", "dag_id": "d1", "ts": 5.0})
    rec = m.list(dag_id="d1")["dags"][0]
    assert rec["state"] == "TORN_DOWN"
    assert rec["stalled_edges"] == []
    assert m.num_stalled_edges() == 0
    # a straggler blocked report after teardown cannot re-flag
    m.ingest({"kind": "report", "dag_id": "d1", "ts": 6.0, "channels": {
        "c0": {"role": "consumer", "reads": 5, "read_block_s": 9.9,
               "read_blocked_s_now": 9.0, "occupancy": 0,
               "pinned_slots": 0, "gc_nudges": 0},
    }})
    assert m.num_stalled_edges() == 0

    # cap: the job with the most records evicts oldest-first, accounted
    m.ingest({"kind": "register", "dag_id": "d2", "job_id": "j2",
              "driver": "w0", "ts": 6.0, "edges": []})
    m.ingest({"kind": "register", "dag_id": "d3", "job_id": "j2",
              "driver": "w0", "ts": 7.0, "edges": []})
    assert m.num_dags() == 2
    assert m.dropped_counts()["j2"] == 1
    assert [d["dag_id"] for d in m.list()["dags"]] == ["d3", "d1"]
    m.on_job_finished("j1")
    assert m.list(dag_id="d1")["total"] == 0


# ----------------------------------------------------------- E2E fixture
@pytest.fixture
def dag_obs_cluster(monkeypatch):
    """Single-node cluster with a fast report cadence + a short stall
    grace so the watchdog E2E completes in seconds."""
    monkeypatch.setenv("RAYT_DAG_STALL_GRACE_S", "1.0")
    monkeypatch.setenv("RAYT_DAG_STATE_REPORT_INTERVAL_S", "0.25")
    from ray_tpu._internal import config as cfg_mod

    old = cfg_mod._config
    cfg_mod.set_config(cfg_mod.load_config())
    import ray_tpu

    ray_tpu.init(num_cpus=4)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()
        cfg_mod._config = old


def _wait_for(predicate, timeout=20.0, interval=0.25):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = predicate()
        if out:
            return out
        time.sleep(interval)
    return None


def test_dag_record_lifecycle(dag_obs_cluster):
    """Compile → RUNNING record with edge topology + growing tick
    counts; teardown → TORN_DOWN (the registration/report/teardown
    round-trip over the dag_state channel)."""
    rt = dag_obs_cluster
    from ray_tpu import state_api
    from ray_tpu.dag import InputNode

    @rt.remote(num_cpus=0)
    class Stage:
        def apply(self, x):
            return x + 1

    a, b = Stage.remote(), Stage.remote()
    with InputNode() as inp:
        out = b.apply.bind(a.apply.bind(inp))
    dag = out.experimental_compile(channels=True)
    for i in range(10):
        assert dag.execute(i).get(timeout=30) == i + 2

    def record_ready():
        recs = state_api.list_dags(dag_id=dag.dag_id)
        if recs and recs[0]["ticks"] >= 10:
            return recs[0]
        return None

    rec = _wait_for(record_ready)
    assert rec is not None, "dag record never reached 10 ticks"
    assert rec["state"] == "RUNNING"
    assert rec["channel_kinds"] == {"shm": 3, "dcn": 0, "device": 0}
    roles = sorted(e["role"] for e in rec["edges"])
    assert roles == ["edge", "input", "output"]
    edge = next(e for e in rec["edges"] if e["role"] == "edge")
    assert edge["producer"]["label"].startswith("Stage:")
    assert edge["consumer"]["label"].startswith("Stage:")
    assert edge["bytes"] > 0 and edge["history"]
    summ = state_api.summarize_dags()
    assert summ["totals"]["dags"] >= 1
    assert summ["by_state"].get("RUNNING", 0) >= 1

    dag.teardown()
    rec = _wait_for(lambda: (state_api.list_dags(dag_id=dag.dag_id)
                             or [None])[0], timeout=10.0)
    assert rec and rec["state"] == "TORN_DOWN"
    for a_ in (a, b):
        rt.kill(a_)


def test_stall_watchdog_e2e_dead_runner(dag_obs_cluster, capsys):
    """THE acceptance path: kill a runner actor mid-DAG and the GCS
    record flags the stalled edge with the dead peer named; the
    enriched _get_tick timeout error carries the culprit + per-channel
    cursors; `rayt list dags`/`rayt dag` render the same attribution;
    the flag clears on teardown."""
    rt = dag_obs_cluster
    from ray_tpu import state_api
    from ray_tpu.dag import InputNode
    from ray_tpu.scripts.cli import _print_dag

    @rt.remote(num_cpus=0)
    class Runner:
        def produce(self, x):
            return x * 2

    @rt.remote(num_cpus=0)
    class Sink:
        def consume(self, x):
            return x + 1

    runner, sink = Runner.remote(), Sink.remote()
    with InputNode() as inp:
        out = sink.consume.bind(runner.produce.bind(inp))
    dag = out.experimental_compile(channels=True)
    for i in range(3):
        assert dag.execute(i).get(timeout=30) == 2 * i + 1
    runner_hex = runner._actor_id.hex()

    # kill the runner: the Runner->Sink ring goes silent, the sink's
    # loop parks on an empty ring, its reporter keeps publishing the
    # growing read-block, and the GCS watchdog attributes the stall
    rt.kill(runner)

    def stalled_with_dead_peer():
        recs = state_api.list_dags(dag_id=dag.dag_id)
        if not recs:
            return None
        for e in recs[0]["edges"]:
            s = e.get("stall")
            if s and s["dead_peer"] == runner_hex:
                return (recs[0], e)
        return None

    hit = _wait_for(stalled_with_dead_peer, timeout=25.0)
    assert hit is not None, "stall with dead peer never flagged"
    rec, edge = hit
    assert edge["stall"]["blocked"] == "read"
    assert edge["stall"]["culprit"].startswith("Runner:")
    assert edge["stall"]["culprit_state"] == "DEAD"
    assert edge["edge"] in rec["stalled_edges"]
    # stalled_only server-side filter finds it too
    assert state_api.list_dags(stalled_only=True)

    # the enriched timeout error names the culprit edge + dead peer and
    # carries per-output-channel cursor positions
    ref = dag.execute(99)
    with pytest.raises(TimeoutError) as ei:
        ref.get(timeout=2.0)
    msg = str(ei.value)
    assert "cursors:" in msg and "out0=" in msg
    assert "stalled edge" in msg
    assert runner_hex in msg and "DEAD" in msg

    # `rayt dag <id>` renders the same attribution
    _print_dag(state_api.list_dags(dag_id=dag.dag_id)[0])
    cli_out = capsys.readouterr().out
    assert "read-blocked" in cli_out and "DEAD" in cli_out

    # the Prometheus gauge derived from the same reports is nonzero
    cw = state_api._cw()
    snap = cw.io.run(cw.gcs.call("metrics_snapshot"))
    gauge = next((m for m in snap
                  if m["name"] == "rayt_dag_stalled_edges"), None)
    assert gauge is not None and gauge["value"] >= 1.0

    # teardown clears the flag and marks the record
    dag.teardown()

    def torn_down_clean():
        recs = state_api.list_dags(dag_id=dag.dag_id)
        if recs and recs[0]["state"] == "TORN_DOWN" \
                and not recs[0]["stalled_edges"]:
            return recs[0]
        return None

    assert _wait_for(torn_down_clean, timeout=15.0) is not None
    rt.kill(sink)


def test_tick_timeout_env_and_partial_wave_cursors(dag_obs_cluster,
                                                   monkeypatch):
    """RAYT_DAG_TICK_TIMEOUT_S replaces the hardcoded 300s default for
    BOTH _get_tick and execute's input writes, and a no-arg get() that
    times out reports the per-output cursor positions."""
    rt = dag_obs_cluster
    from ray_tpu._internal.config import get_config
    from ray_tpu.dag import InputNode

    @rt.remote(num_cpus=0)
    class Slow:
        def apply(self, x):
            import time as _t

            _t.sleep(30.0)
            return x

    monkeypatch.setattr(get_config(), "dag_tick_timeout_s", 1.0)
    s = Slow.remote()
    with InputNode() as inp:
        out = s.apply.bind(inp)
    dag = out.experimental_compile(channels=True)
    ref = dag.execute(1)
    t0 = time.monotonic()
    with pytest.raises(TimeoutError) as ei:
        ref.get()  # no explicit timeout -> config default (1s, not 300)
    assert time.monotonic() - t0 < 10.0
    msg = str(ei.value)
    assert "1.0s" in msg and "cursors:" in msg
    assert "out0=read:0/written:0" in msg
    assert "0/1 outputs consumed" in msg
    dag.teardown()
    rt.kill(s)


def test_per_tick_tracing_spans_stitch(monkeypatch, tmp_path):
    """Per-tick distributed tracing (env-gated; replaces the old
    RAYT_DAG_TRACE print path): one tick's spans share the driver's
    trace id across producer/consumer PROCESSES and export into the
    Chrome timeline."""
    trace_dir = str(tmp_path / "spans")
    monkeypatch.setenv("RAYT_TRACING_DIR", trace_dir)
    from ray_tpu._internal import otel

    # the driver process may have cached "tracing off": reset the gate
    monkeypatch.setattr(otel, "_enabled", None)
    monkeypatch.setattr(otel, "_out_path", None)
    import ray_tpu as rt

    rt.init(num_cpus=4)
    try:
        from ray_tpu.dag import InputNode

        @rt.remote(num_cpus=0)
        class Hop:
            def fwd(self, x):
                return x + 1

        h1, h2 = Hop.remote(), Hop.remote()
        with InputNode() as inp:
            out = h2.fwd.bind(h1.fwd.bind(inp))
        dag = out.experimental_compile(channels=True)
        for i in range(3):
            assert dag.execute(i).get(timeout=30) == i + 2
        dag.teardown()
    finally:
        rt.shutdown()
    spans = otel.read_spans(trace_dir)
    exec_spans = [s for s in spans if s["name"] == "dag.execute"]
    assert len(exec_spans) >= 3
    tick0 = next(s for s in exec_spans
                 if s["attributes"].get("tick") == 0)
    same_trace = [s for s in spans
                  if s["trace_id"] == tick0["trace_id"]]
    names = {s["name"] for s in same_trace}
    assert "execute dag.fwd" in names   # actor-side compute spans
    # ...in at least two distinct processes besides the driver's span
    pids = {s["pid"] for s in same_trace}
    assert len(pids) >= 3, f"tick spans did not stitch across pids: {pids}"
    # actor spans are REMOTE CHILDREN of the driver's execute span
    child = next(s for s in same_trace if s["name"] == "execute dag.fwd")
    assert child["parent_id"] == tick0["span_id"]
    # and the existing Chrome exporter renders them
    out_path = str(tmp_path / "dag_trace.json")
    n = otel.export_chrome_trace(trace_dir, out_path)
    assert n >= len(spans)
    import json

    doc = json.load(open(out_path))
    assert any(ev["name"] == "dag.execute"
               for ev in doc["traceEvents"])


def test_dag_state_disabled_no_records(monkeypatch):
    """RAYT_DAG_STATE_ENABLED=0 removes registration + reports: the
    GCS dag store stays empty and schedules carry no dag id."""
    monkeypatch.setenv("RAYT_DAG_STATE_ENABLED", "0")
    from ray_tpu._internal import config as cfg_mod

    old = cfg_mod._config
    cfg_mod.set_config(cfg_mod.load_config())
    import ray_tpu as rt

    rt.init(num_cpus=2)
    try:
        from ray_tpu import state_api
        from ray_tpu.dag import InputNode

        @rt.remote(num_cpus=0)
        class E:
            def apply(self, x):
                return x

        e = E.remote()
        with InputNode() as inp:
            out = e.apply.bind(inp)
        dag = out.experimental_compile(channels=True)
        assert dag.execute(5).get(timeout=30) == 5
        time.sleep(0.8)
        assert state_api.list_dags(detail=True)["total"] == 0
        dag.teardown()
    finally:
        rt.shutdown()
        cfg_mod._config = old
