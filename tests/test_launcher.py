"""Cluster launcher CLI (ref analog: `ray up/down/exec` + cluster YAML):
up starts a head with the configured provider, exec runs drivers against
it, down terminates slices and the head."""

import json
import os
import subprocess
import sys
import time

import pytest

_YAML = """
cluster_name: lnch-test
provider:
  type: local
head:
  resources: {CPU: 2}
  dashboard_port: 0
node_types:
  - name: tpu-v5p-8
    resources_per_host: {CPU: 2, TPU: 4}
    hosts: 1
    max_slices: 2
    min_slices: 1
autoscaler:
  idle_timeout_s: 600
  reconcile_interval_s: 0.5
"""

_DRIVER = """
import os
import ray_tpu as rt

rt.init(address=os.environ["RAYT_ADDRESS"])

@rt.remote(num_tpus=4)
def on_tpu():
    return os.environ["RAYT_NODE_ID"]

print("TPU_NODE", rt.get(on_tpu.remote(), timeout=120))
rt.shutdown()
"""


def _cli(*args, timeout=180):
    return subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", *args],
        capture_output=True, text=True, timeout=timeout,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": os.path.dirname(os.path.dirname(
                 os.path.abspath(__file__)))
             + os.pathsep + os.environ.get("PYTHONPATH", "")})


def test_up_exec_down(tmp_path):
    state_file = os.path.expanduser("~/.rayt/clusters/lnch-test.json")
    if os.path.exists(state_file):
        os.remove(state_file)
    cfg = tmp_path / "cluster.yaml"
    cfg.write_text(_YAML)
    drv = tmp_path / "driver.py"
    drv.write_text(_DRIVER)

    r = _cli("up", str(cfg))
    assert r.returncode == 0, r.stderr[-800:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["cluster"] == "lnch-test" and ":" in out["address"]
    assert os.path.exists(state_file)
    try:
        # exec: a driver reaches the cluster via RAYT_ADDRESS and lands a
        # TPU task on the pre-launched (min_slices) slice
        r = _cli("exec", "lnch-test", "--", sys.executable, str(drv),
                 timeout=240)
        assert r.returncode == 0, r.stderr[-800:]
        assert "TPU_NODE" in r.stdout
    finally:
        r = _cli("down", "lnch-test")
        assert r.returncode == 0, r.stderr[-500:]
    assert not os.path.exists(state_file)
    time.sleep(1)
