"""JaxLearner + LearnerGroup (ref analogs: rllib/core/learner/learner.py:109
`compute_losses/compute_gradients`, learner_group.py:80, DDP wrapping in
torch_learner.py:409).

TPU-first: the whole PPO update (GAE, minibatch epochs, clipped losses,
optimizer) is one jitted function on the learner's devices; multi-learner
data parallelism averages gradients over the host-plane collective group
(cross-host path — in-slice DP is a mesh axis inside the jit)."""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np


@dataclasses.dataclass
class PPOLearnerConfig:
    lr: float = 3e-4
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    num_epochs: int = 4
    minibatch_size: int = 256
    max_grad_norm: float = 0.5


def compute_gae(rewards, values, dones, last_value, gamma, lam,
                trunc_values=None):
    """[T, N] arrays -> (advantages, returns), numpy (host side).

    `trunc_values[t, i]` is V(final_obs) where env i was *truncated*
    (time-limit cut, not a true terminal) at step t, 0 elsewhere: the GAE
    recursion still cuts at those steps, but the bootstrap target is the
    critic's value of the final state instead of 0.
    """
    T = rewards.shape[0]
    adv = np.zeros_like(rewards)
    gae = np.zeros(rewards.shape[1], rewards.dtype)
    next_value = last_value
    for t in range(T - 1, -1, -1):
        nonterminal = 1.0 - dones[t].astype(rewards.dtype)
        boot = next_value * nonterminal
        if trunc_values is not None:
            boot = boot + trunc_values[t]
        delta = rewards[t] + gamma * boot - values[t]
        gae = delta + gamma * lam * nonterminal * gae
        adv[t] = gae
        next_value = values[t]
    return adv, adv + values


def build_ppo_batch(samples: list, gamma: float, lam: float):
    """Fold sampled [T, N] trajectories into one flat PPO batch:
    GAE per trajectory, flatten, concat. Shared by the single-agent and
    multi-agent drivers (per-policy streams are the same shape).
    Returns (batch, episode_returns, env_steps)."""
    obs, acts, logps, advs, rets = [], [], [], [], []
    ep_returns: list[float] = []
    steps = 0
    for s in samples:
        adv, ret = compute_gae(
            s["rewards"], s["values"], s["dones"], s["last_value"],
            gamma, lam, s.get("trunc_values"))
        T, N = s["rewards"].shape
        steps += T * N
        obs.append(s["obs"].reshape((T * N,) + s["obs"].shape[2:]))
        acts.append(s["actions"].reshape(T * N))
        logps.append(s["logp"].reshape(T * N))
        advs.append(adv.reshape(T * N))
        rets.append(ret.reshape(T * N))
        ep_returns.extend(s["episode_returns"])
    batch = {
        "obs": np.concatenate(obs),
        "actions": np.concatenate(acts),
        "logp_old": np.concatenate(logps),
        "advantages": np.concatenate(advs).astype(np.float32),
        "returns": np.concatenate(rets).astype(np.float32),
    }
    return batch, ep_returns, steps


class JaxLearner:
    """One learner process; jit-compiled minibatch PPO update."""

    def __init__(self, module_cfg_blob: bytes, learner_cfg_blob: bytes,
                 seed: int = 0, group_name: Optional[str] = None,
                 world_size: int = 1, rank: int = 0):
        from ray_tpu._internal.spawn import wait_site_ready

        wait_site_ready()  # PJRT plugin may still be registering
        import os

        import cloudpickle
        import jax

        if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
            # an explicit CPU pin must win even though a sitecustomize TPU
            # hook may have overridden jax_platforms at import time —
            # probing an unreachable TPU plugin can hang indefinitely
            jax.config.update("jax_platforms", "cpu")
        else:
            try:
                jax.devices()
            except Exception:
                # env points at a backend whose plugin isn't available in
                # this worker: fall back to CPU rather than dying
                jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import optax

        from ray_tpu.rl import module as rlm

        self.cfg: PPOLearnerConfig = cloudpickle.loads(learner_cfg_blob)
        self.module_cfg = cloudpickle.loads(module_cfg_blob)
        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        if group_name is not None and world_size > 1:
            from ray_tpu.util import collective

            collective.init_collective_group(world_size, rank,
                                             group_name=group_name)
        self.params = rlm.init_params(self.module_cfg,
                                      jax.random.PRNGKey(seed))
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(self.cfg.max_grad_norm),
            optax.adam(self.cfg.lr))
        self.opt_state = self.optimizer.init(self.params)
        cfg = self.cfg

        def loss_fn(params, batch):
            logits, value = rlm.forward(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=1)[:, 0]
            ratio = jnp.exp(logp - batch["logp_old"])
            adv = batch["advantages"]
            pg = -jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - cfg.clip_eps, 1 + cfg.clip_eps) * adv)
            vf = 0.5 * (value - batch["returns"]) ** 2
            entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
            loss = (pg.mean() + cfg.vf_coeff * vf.mean()
                    - cfg.entropy_coeff * entropy.mean())
            return loss, {"loss": loss, "pg_loss": pg.mean(),
                          "vf_loss": vf.mean(), "entropy": entropy.mean()}

        def grad_step(params, opt_state, batch):
            (_, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            return grads, aux

        self._grad_step = jax.jit(grad_step)

        def apply(params, opt_state, grads):
            updates, new_opt = self.optimizer.update(grads, opt_state,
                                                     params)
            import optax as _optax

            return _optax.apply_updates(params, updates), new_opt

        self._apply = jax.jit(apply)

    # ---------------------------------------------------------------- update
    def update(self, batch: dict) -> dict:
        """batch: flat [B, ...] numpy arrays (obs, actions, logp_old,
        advantages, returns). Runs epochs x minibatches."""
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        B = batch["obs"].shape[0]
        adv = batch["advantages"]
        batch = dict(batch)
        batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
        rng = np.random.RandomState(0)
        mb = min(cfg.minibatch_size, B)
        n_mb = max(1, B // mb)
        aux_last: dict = {}
        for _ in range(cfg.num_epochs):
            perm = rng.permutation(B)[:n_mb * mb].reshape(n_mb, mb)
            for idx in perm:
                mb_batch = {k: jnp.asarray(v[idx]) for k, v in batch.items()}
                grads, aux = self._grad_step(self.params, self.opt_state,
                                             mb_batch)
                grads = self._sync_grads(grads)
                self.params, self.opt_state = self._apply(
                    self.params, self.opt_state, grads)
                aux_last = aux
        return {k: float(v) for k, v in aux_last.items()}

    def _sync_grads(self, grads):
        if self.group_name is None or self.world_size <= 1:
            return grads
        import jax
        import jax.numpy as jnp

        from ray_tpu.util import collective

        flat, tree = jax.tree.flatten(grads)
        host = [np.asarray(g) for g in flat]
        summed = [collective.allreduce(g, group_name=self.group_name)
                  for g in host]
        return jax.tree.unflatten(
            tree, [jnp.asarray(g / self.world_size) for g in summed])

    def get_weights(self):
        import jax

        return jax.tree.map(lambda x: np.asarray(x), self.params)

    def set_weights(self, params) -> bool:
        import jax
        import jax.numpy as jnp

        self.params = jax.tree.map(jnp.asarray, params)
        return True

    def save_state(self) -> dict:
        import jax

        return {"params": jax.tree.map(lambda x: np.asarray(x), self.params)}

    def load_state(self, state: dict) -> bool:
        import jax.numpy as jnp
        import jax

        self.params = jax.tree.map(jnp.asarray, state["params"])
        return True
