"""IMPALA scale bench: N rollout runners + aggregators + learner, records
steady-state samples/s into RL_BENCH.json (BASELINE config #4 shape at
CI scale; ref harness discipline: rllib release smoke tests).

Usage: python tools/rl_scale_bench.py [num_runners] [iters]
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"  # ambient env pins axon; setdefault would keep it
# A 1-core CI box boots ~40 jax-importing worker processes serially; the
# production timeouts would declare them dead mid-boot and thrash.
os.environ.setdefault("RAYT_WORKER_STARTUP_TIMEOUT_S", "900")
os.environ.setdefault("RAYT_LEASE_TIMEOUT_S", "600")
os.environ.setdefault("RAYT_RPC_REQUEST_TIMEOUT_S", "300")
os.environ.setdefault("RAYT_NODE_DEATH_TIMEOUT_S", "300")
os.environ.setdefault("RAYT_ACTOR_SCHEDULING_DEADLINE_S", "1800")
os.environ.setdefault("RAYT_ACTOR_CREATION_PUSH_TIMEOUT_S", "1200")


def _bench_body(num_runners: int, iters: int) -> dict:
    from ray_tpu.rl.impala import IMPALAConfig

    algo = IMPALAConfig(
        env="CartPole-v1",
        num_env_runners=num_runners,
        num_envs_per_runner=2,
        rollout_fragment_length=32,
        num_aggregators=4,
        train_batch_size=2048,
        max_requests_in_flight=2,
        boot_wave=4,
        call_timeout_s=600.0,
        seed=0).build()
    # warmup: let the pipeline fill
    r = algo.train()
    t0 = time.perf_counter()
    steps0 = r["num_env_steps_sampled"]
    last = r
    for _ in range(iters):
        last = algo.train()
    dt = time.perf_counter() - t0
    steps = last["num_env_steps_sampled"] - steps0
    out = {
        "bench": "impala_scale",
        "num_env_runners": num_runners,
        "num_envs_per_runner": 2,
        "host_cores": os.cpu_count(),
        "iterations": iters,
        "env_steps": steps,
        "samples_per_s": round(steps / dt, 1),
        "episode_return_mean": last["episode_return_mean"],
        "learner_updates_total": last["training_iteration"],
    }
    algo.stop()
    return out


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import ray_tpu as rt

    num_runners = int(sys.argv[1]) if len(sys.argv) > 1 else 32
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 10

    # resource fiction on a small box: the point is control-plane scale
    # (N actors pipelining through aggregators), not per-core throughput
    rt.init(num_cpus=max(num_runners + 8, os.cpu_count() or 1),
            resources={"TPU": 8})
    try:
        out = _bench_body(num_runners, iters)
    except BaseException:
        try:  # diagnosis: which actor (if any) never became ALIVE?
            from ray_tpu import state_api

            for a in state_api.list_actors():
                if a.get("state") != "ALIVE":
                    print("NOT-ALIVE ACTOR:", a, file=sys.stderr)
            print("STATUS:", state_api.cluster_status(), file=sys.stderr)
            s = state_api.summary()
            print("RESOURCES total:", s.get("resources_total"),
                  file=sys.stderr)
            print("RESOURCES avail:", s.get("resources_available"),
                  file=sys.stderr)
        except Exception as e:
            print("state dump failed:", e, file=sys.stderr)
        raise
    finally:
        rt.shutdown()
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "RL_BENCH.json")
    existing = {}
    if os.path.exists(path):
        with open(path) as f:
            existing = json.load(f)
    existing["impala_scale"] = out
    with open(path, "w") as f:
        json.dump(existing, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
