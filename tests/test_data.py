"""Data library tests (ref analogs: python/ray/data/tests/)."""

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu import data as rd


def test_map_filter_count(local_cluster):
    ds = rd.range(100, num_blocks=4)
    out = (ds.map(lambda r: {"id": r["id"], "sq": r["id"] ** 2})
             .filter(lambda r: r["sq"] % 2 == 0))
    assert out.count() == 50
    rows = out.take(3)
    assert rows[0] == {"id": 0, "sq": 0}


def test_map_batches_numpy(local_cluster):
    ds = rd.range(32, num_blocks=4)

    def add_col(batch):
        batch["double"] = batch["id"] * 2
        return batch

    out = ds.map_batches(add_col, batch_size=8)
    rows = out.take_all()
    assert len(rows) == 32
    assert all(r["double"] == 2 * r["id"] for r in rows)


def test_map_batches_actor_pool(local_cluster):
    ds = rd.range(24, num_blocks=4)

    class AddOffset:
        def __init__(self, offset):
            self.offset = offset

        def __call__(self, batch):
            batch["plus"] = batch["id"] + self.offset
            return batch

    out = ds.map_batches(AddOffset, compute=rd.ActorPoolStrategy(size=2),
                         fn_constructor_args=(100,))
    rows = sorted(out.take_all(), key=lambda r: r["id"])
    assert [r["plus"] for r in rows] == [i + 100 for i in range(24)]


def test_flat_map_repartition(local_cluster):
    ds = rd.from_items([1, 2, 3], num_blocks=2)
    out = ds.flat_map(lambda r: [{"v": r["item"]}] * r["item"])
    assert out.count() == 6
    rep = out.repartition(3)
    assert rep.materialize().num_blocks() == 3
    assert rep.count() == 6


def test_random_shuffle_preserves_rows(local_cluster):
    ds = rd.range(60, num_blocks=4)
    shuffled = ds.random_shuffle(seed=7)
    ids = [r["id"] for r in shuffled.take_all()]
    assert sorted(ids) == list(range(60))
    assert ids != list(range(60))


def test_sort_limit_take(local_cluster):
    ds = rd.from_items([5, 3, 9, 1, 7], num_blocks=2)
    out = ds.sort(key=lambda r: r["item"])
    assert [r["item"] for r in out.take_all()] == [1, 3, 5, 7, 9]
    assert [r["item"] for r in out.limit(2).take_all()] == [1, 3]


def test_union_zip(local_cluster):
    a = rd.from_items([1, 2], num_blocks=1)
    b = rd.from_items([3], num_blocks=1)
    assert a.union(b).count() == 3
    za = rd.from_items([{"x": 1}, {"x": 2}], num_blocks=1)
    zb = rd.from_items([{"y": 10}, {"y": 20}], num_blocks=1)
    assert za.zip(zb).take_all() == [{"x": 1, "y": 10}, {"x": 2, "y": 20}]


def test_groupby_aggregate(local_cluster):
    rows = [{"k": i % 3, "v": i} for i in range(12)]
    ds = rd.from_items(rows, num_blocks=3)
    agg = ds.groupby("k").sum("v").take_all()
    by_key = {r["k"]: r["sum(v)"] for r in agg}
    assert by_key == {0: 0 + 3 + 6 + 9, 1: 1 + 4 + 7 + 10, 2: 2 + 5 + 8 + 11}
    counts = {r["k"]: r["count"] for r in
              ds.groupby("k").count().take_all()}
    assert counts == {0: 4, 1: 4, 2: 4}


def test_iter_batches_shapes(local_cluster):
    ds = rd.range(10, num_blocks=3)
    batches = list(ds.iter_batches(batch_size=4))
    sizes = [len(b["id"]) for b in batches]
    assert sizes == [4, 4, 2]
    assert isinstance(batches[0]["id"], np.ndarray)
    full = np.concatenate([b["id"] for b in batches])
    assert sorted(full.tolist()) == list(range(10))


def test_aggregates(local_cluster):
    ds = rd.from_items([{"v": float(i)} for i in range(5)], num_blocks=2)
    assert ds.sum("v") == 10.0
    assert ds.min("v") == 0.0
    assert ds.max("v") == 4.0
    assert ds.mean("v") == 2.0


def test_streaming_split(local_cluster):
    ds = rd.range(20, num_blocks=4)
    shards = ds.streaming_split(2, equal=True)
    counts = [s.count() for s in shards]
    assert counts == [10, 10]
    all_ids = sorted(r["id"] for s in shards for r in s.iter_rows())
    assert all_ids == list(range(20))


def test_streaming_split_usable_in_workers(local_cluster):
    import ray_tpu as rt

    ds = rd.range(16, num_blocks=4)
    shards = ds.streaming_split(2, equal=True)

    @rt.remote
    def consume(it):
        return sum(r["id"] for r in it.iter_rows())

    totals = rt.get([consume.remote(s) for s in shards])
    assert sum(totals) == sum(range(16))


def test_read_text_csv_parquet_json(local_cluster, tmp_path):
    (tmp_path / "a.txt").write_text("hello\nworld\n")
    ds = rd.read_text(str(tmp_path / "a.txt"))
    assert [r["text"] for r in ds.take_all()] == ["hello", "world"]

    (tmp_path / "b.csv").write_text("x,y\n1,2\n3,4\n")
    rows = rd.read_csv(str(tmp_path / "b.csv")).take_all()
    # the arrow csv reader type-infers columns (ref read_csv behavior)
    assert rows == [{"x": 1, "y": 2}, {"x": 3, "y": 4}]

    (tmp_path / "c.json").write_text('[{"a": 1}, {"a": 2}]')
    assert rd.read_json(str(tmp_path / "c.json")).count() == 2

    src = rd.from_items([{"n": i} for i in range(6)], num_blocks=2)
    rd.write_parquet(src, str(tmp_path / "pq"))
    back = rd.read_parquet(str(tmp_path / "pq"))
    assert sorted(r["n"] for r in back.take_all()) == list(range(6))


def test_pipeline_streams(local_cluster):
    """Chained map stages run streamingly over many blocks."""
    ds = rd.range(200, num_blocks=16)
    out = (ds.map(lambda r: {"v": r["id"] * 2})
             .filter(lambda r: r["v"] % 4 == 0)
             .map_batches(lambda b: {"v": b["v"] + 1}, batch_size=None))
    vals = sorted(r["v"] for r in out.take_all())
    assert vals == [4 * i + 1 for i in range(100)]


def test_arrow_parquet_roundtrip(local_cluster, tmp_path):
    """Parquet reads produce COLUMNAR arrow blocks that flow through the
    pipeline (ref analog: data/_internal/arrow_block.py arrow-first)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from ray_tpu.data import read_parquet
    from ray_tpu.data.block import is_arrow_block

    src = tmp_path / "in"
    src.mkdir()
    for part in range(2):
        table = pa.table({
            "x": list(range(part * 50, part * 50 + 50)),
            "y": [float(i) * 0.5 for i in range(part * 50, part * 50 + 50)],
        })
        pq.write_table(table, src / f"p{part}.parquet")

    ds = read_parquet(str(src))
    # blocks are arrow tables end to end
    first_block = rt.get(next(ds._iter_block_refs()))
    assert is_arrow_block(first_block)
    assert ds.count() == 100
    # columnar numpy batches (train-ingest path)
    batch = next(ds.iter_batches(batch_size=32, batch_format="numpy"))
    assert set(batch) == {"x", "y"} and batch["x"].shape == (32,)
    # row ops work across arrow blocks
    assert ds.filter(lambda r: r["x"] < 10).count() == 10
    assert ds.sum("x") == sum(range(100))
    # write back
    out = tmp_path / "out"
    ds.write_parquet(str(out))
    again = read_parquet(str(out))
    assert again.count() == 100


def test_arrow_map_batches_pyarrow_format(local_cluster, tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    from ray_tpu.data import read_parquet

    pq.write_table(pa.table({"v": list(range(40))}),
                   tmp_path / "d.parquet")
    ds = read_parquet(str(tmp_path / "d.parquet"))

    def double(table: "pa.Table") -> "pa.Table":
        import pyarrow.compute as pc

        return table.set_column(0, "v", pc.multiply(table.column("v"), 2))

    out = ds.map_batches(double, batch_format="pyarrow", batch_size=16)
    rows = out.take_all()
    assert [r["v"] for r in rows] == [2 * i for i in range(40)]


def test_arrow_csv_reader(local_cluster, tmp_path):
    from ray_tpu.data import read_csv
    from ray_tpu.data.block import is_arrow_block

    (tmp_path / "t.csv").write_text("a,b\n1,x\n2,y\n3,z\n")
    ds = read_csv(str(tmp_path / "t.csv"))
    block = rt.get(next(ds._iter_block_refs()))
    assert is_arrow_block(block)
    rows = ds.take_all()
    assert rows == [{"a": 1, "b": "x"}, {"a": 2, "b": "y"},
                    {"a": 3, "b": "z"}]


def test_plan_fuses_maps_and_pushes_limit(local_cluster):
    """Logical-plan rewrite rules (ref analogs: data/_internal/plan.py,
    logical/rules operator fusion + limit pushdown)."""
    from ray_tpu import data

    ds = (data.range(100)
          .map(lambda r: {"id": r["id"] + 1})
          .map(lambda r: {"id": r["id"] * 2})
          .filter(lambda r: r["id"] % 4 == 0)
          .limit(5))
    plan = ds.explain()
    # three task maps fused into one stage; limit hopped before the
    # 1:1 maps but NOT before the filter (which changes row counts)
    assert any(p.startswith("Fused[") for p in plan)
    assert plan.index("limit[5]") == len(plan) - 1
    rows = ds.take_all()
    assert rows == [{"id": v} for v in (4, 8, 12, 16, 20)]

    # redundant shuffle before sort is dropped
    ds2 = data.range(20).random_shuffle(seed=1).sort("id")
    plan2 = ds2.explain()
    assert "all_to_all:shuffle" not in plan2
    assert [r["id"] for r in ds2.take_all()] == list(range(20))


# --------------------------------------------- topology executor (round 4)
def test_backpressure_bounds_upstream(local_cluster):
    """A slow downstream op bounds the upstream op's materialized blocks:
    the fast producer pauses when the consumer's queue hits the budget
    (ref: backpressure_policy/backpressure_policy.py)."""
    import time

    from ray_tpu import data
    from ray_tpu.data.executor import StreamingExecutor
    from ray_tpu.data.streaming_executor import ExecutionOptions

    # blocks ~= 80KB; budget of 3 blocks worth, window of 8 — the BYTE
    # budget (not the concurrency cap) must be what binds upstream
    opts = ExecutionOptions(max_in_flight=8,
                            op_budget_bytes=3 * 80_000,
                            block_size_estimate=80_000)
    execu = StreamingExecutor(execution_options=opts)
    n_rows = 240
    ds = data.from_items([{"x": list(range(2500)), "i": i}
                          for i in range(n_rows)], num_blocks=24)
    ds._executor = execu

    def fast(row):
        return row

    def slow(row):
        time.sleep(0.01)
        return {"i": row["i"]}

    # two actor-pool stages: they don't fuse, so the topology has a real
    # producer->consumer edge with a queue between them
    from ray_tpu.data.executor import ActorPoolStrategy

    out = ds.map_batches(lambda b: b, batch_size=10,
                         compute=ActorPoolStrategy(size=2)) \
            .map_batches(lambda b: {"i": b["i"]}, batch_size=10,
                         compute=ActorPoolStrategy(size=1)) \
            .take_all()
    assert len(out) == n_rows
    stats = execu.last_topology.stats()
    # upstream (op 0) backlog must have been bounded by the budget: it
    # could have materialized all 24 blocks; the budget allows ~3 plus
    # one in-flight round of slack
    assert stats[0].backlog_peak_blocks <= 6, stats
    assert stats[0].paused_on_backpressure > 0, stats


def test_actor_pool_autoscales_with_queue_depth(local_cluster):
    """ActorPoolStrategy(min_size, max_size): the pool grows while the
    input queue is deep (ref: data-internal actor-pool autoscaler)."""
    import time

    from ray_tpu import data
    from ray_tpu.data.executor import ActorPoolStrategy, StreamingExecutor
    from ray_tpu.data.streaming_executor import ExecutionOptions

    execu = StreamingExecutor(execution_options=ExecutionOptions(
        max_in_flight=8, actor_scale_interval_s=0.0))
    ds = data.from_items(list(range(200)), num_blocks=20)
    ds._executor = execu

    class Slow:
        def __call__(self, batch):
            time.sleep(0.05)
            return batch

    out = ds.map_batches(Slow, batch_size=10,
                         compute=ActorPoolStrategy(min_size=1, max_size=4)
                         ).take_all()
    assert len(out) == 200
    stats = execu.last_topology.stats()
    assert stats[0].pool_peak > 1, stats  # it grew under load
    assert stats[0].pool_peak <= 4, stats


def test_streaming_split_feeds_training_under_pressure(local_cluster):
    """streaming_split output of a backpressured pipeline feeds per-worker
    iteration (the Train ingest shape, config #2)."""
    import numpy as np

    from ray_tpu import data
    from ray_tpu.data.executor import StreamingExecutor
    from ray_tpu.data.streaming_executor import ExecutionOptions

    execu = StreamingExecutor(execution_options=ExecutionOptions(
        max_in_flight=2, op_budget_bytes=64_000,
        block_size_estimate=32_000))
    ds = data.from_items([{"x": float(i)} for i in range(400)],
                         num_blocks=16)
    ds._executor = execu
    ds = ds.map(lambda r: {"x": r["x"] * 2})
    shards = ds.streaming_split(2, equal=True)
    seen = []
    for shard in shards:
        batches = list(shard.iter_batches(batch_size=50))
        assert all(len(b["x"]) == 50 for b in batches)
        seen.extend(float(x) for b in batches for x in np.asarray(b["x"]))
    assert sorted(seen) == [float(i * 2) for i in range(400)]


# ---------------------------------------------------- columnar blocks (r5)
def test_map_batches_output_stays_columnar(local_cluster):
    """VERDICT r4 missing #3: a dict-of-arrays batch from map_batches
    becomes a columnar NumpyBlock, NOT a list of per-row dicts."""
    import numpy as np

    from ray_tpu import data
    from ray_tpu.data.block import is_columnar_block

    ds = data.from_items([{"x": float(i)} for i in range(100)],
                         num_blocks=4)
    ds = ds.map_batches(lambda b: {"y": np.asarray(b["x"]) * 2.0})
    blocks = [rt.get(r) for r in ds._iter_block_refs()]
    assert blocks and all(is_columnar_block(b) for b in blocks), blocks
    got = sorted(float(v) for b in blocks for v in b.cols["y"])
    assert got == [float(i) * 2.0 for i in range(100)]


def test_parquet_map_batches_iter_batches_no_row_dicts(local_cluster,
                                                       tmp_path):
    """The VERDICT done-criterion: read_parquet -> map_batches ->
    iter_batches flows columnar end-to-end. Guard: any driver-side
    row materialization (to_pylist / to_rows) trips the monkeypatch."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from ray_tpu import data
    from ray_tpu.data import block as block_mod

    pq.write_table(pa.table({"v": list(range(64))}),
                   str(tmp_path / "a.parquet"))
    pq.write_table(pa.table({"v": list(range(64, 128))}),
                   str(tmp_path / "b.parquet"))

    ds = data.read_parquet(str(tmp_path / "*.parquet"))
    ds = ds.map_batches(lambda b: {"v2": np.asarray(b["v"]) + 1})

    def _forbidden(*a, **k):
        raise AssertionError("row materialization on the batch path")

    orig = block_mod.NumpyBlock.to_rows
    block_mod.NumpyBlock.to_rows = _forbidden
    try:
        batches = list(ds.iter_batches(batch_size=50))
    finally:
        block_mod.NumpyBlock.to_rows = orig
    assert [len(b["v2"]) for b in batches] == [50, 50, 28]
    flat = sorted(int(x) for b in batches for x in b["v2"])
    assert flat == list(range(1, 129))


def test_columnar_multidim_columns_roundtrip(local_cluster):
    """NumpyBlock carries multi-dim columns (token matrices) that plain
    Arrow columns can't: the train-ingest shape."""
    import numpy as np

    from ray_tpu import data

    ds = data.from_items([{"i": i} for i in range(32)], num_blocks=2)
    ds = ds.map_batches(
        lambda b: {"tokens": np.stack([np.arange(8) + i
                                       for i in np.asarray(b["i"])])})
    batches = list(ds.iter_batches(batch_size=12))
    assert [b["tokens"].shape for b in batches] == [(12, 8), (12, 8), (8, 8)]
    total = np.concatenate([b["tokens"] for b in batches])
    assert total.shape == (32, 8)


def test_numpy_block_pickles_out_of_band():
    """NumpyBlock arrays ride protocol-5 out-of-band buffers — the
    zero-copy path into the shm arena."""
    import pickle

    import numpy as np

    from ray_tpu.data.block import NumpyBlock

    blk = NumpyBlock({"x": np.arange(4096, dtype=np.float64)})
    bufs = []
    payload = pickle.dumps(blk, protocol=5, buffer_callback=bufs.append)
    assert bufs, "array was serialized in-band (copied), not out-of-band"
    restored = pickle.loads(payload, buffers=bufs)
    np.testing.assert_array_equal(restored.cols["x"], blk.cols["x"])


def test_columnar_zero_copy_batch_views(local_cluster):
    """iter_batches over columnar blocks yields numpy views sharing
    memory with the block (no per-batch copies when a batch falls inside
    one block)."""
    import numpy as np

    from ray_tpu.data.block import NumpyBlock, iter_batches_from_blocks

    base = np.arange(100, dtype=np.int64)
    blk = NumpyBlock({"x": base})
    batches = list(iter_batches_from_blocks([blk], 25, "numpy", False))
    assert len(batches) == 4
    assert all(np.shares_memory(b["x"], base) for b in batches)


def test_aggregate_plugin_api(local_cluster):
    """AggregateFn plugin surface (ref: data/aggregate.py built-ins):
    global + grouped aggregation via distributive accumulators."""
    import numpy as np

    from ray_tpu import data

    rows = [{"g": i % 3, "v": float(i)} for i in range(30)]
    ds = data.from_items(rows, num_blocks=4)
    out = ds.aggregate(data.Count(), data.Sum("v"), data.Mean("v"),
                       data.Min("v"), data.Max("v"), data.Std("v"))
    vals = [r["v"] for r in rows]
    assert out["count()"] == 30
    assert out["sum(v)"] == sum(vals)
    assert abs(out["mean(v)"] - np.mean(vals)) < 1e-9
    assert out["min(v)"] == 0.0 and out["max(v)"] == 29.0
    assert abs(out["std(v)"] - np.std(vals, ddof=1)) < 1e-9

    by_g = {r["g"]: r for r in
            ds.groupby("g").aggregate(data.Sum("v"), data.Count()).take_all()}
    for g in (0, 1, 2):
        want = [r["v"] for r in rows if r["g"] == g]
        assert by_g[g]["sum(v)"] == sum(want)
        assert by_g[g]["count()"] == len(want)


def test_ragged_batch_degrades_to_rows(local_cluster):
    """Variable-length list columns can't be columnar — they degrade to
    row blocks instead of failing the pipeline."""
    from ray_tpu import data

    ds = data.from_items([{"i": i} for i in range(4)], num_blocks=1)
    ds = ds.map_batches(
        lambda b: {"tokens": [list(range(i + 1)) for i in b["i"]]},
        batch_format="numpy")
    rows = ds.take_all()
    assert [len(r["tokens"]) for r in rows] == [1, 2, 3, 4]


def test_numpy_batches_are_readonly_views(local_cluster):
    """Zero-copy batches alias stored blocks, so they are read-only: an
    in-place mutation raises instead of silently corrupting the block
    for other readers."""
    import numpy as np
    import pytest as _pytest

    from ray_tpu import data

    ds = data.from_items([{"x": float(i)} for i in range(64)],
                         num_blocks=2)
    ds = ds.map_batches(lambda b: {"x": np.asarray(b["x"]) * 1.0})
    ds = ds.materialize()
    batch = next(ds.iter_batches(batch_size=32))
    with _pytest.raises(ValueError):
        batch["x"] *= 2  # read-only guard
    # and the stored blocks are intact on re-read
    again = next(ds.iter_batches(batch_size=32))
    np.testing.assert_array_equal(np.asarray(again["x"]),
                                  np.arange(32.0))


def test_executor_pauses_on_store_pressure(local_cluster, monkeypatch):
    """VERDICT r4 weak #6: the streaming executor reads the shm arena's
    REAL occupancy — near-full stores pause submission (drain-only)
    instead of piling blocks into a store about to spill."""
    from ray_tpu.data import streaming_executor as se
    from ray_tpu.data.executor import MapSpec

    pressure = {"used": 95, "cap": 100}
    monkeypatch.setattr(se, "_store_usage",
                        lambda: (pressure["used"], pressure["cap"]))
    source = [rt.put([{"x": i}]) for i in range(4)]
    topo = se.StreamingTopology(
        [MapSpec("map", lambda r: {"x": r["x"] + 1})], iter(source),
        se.ExecutionOptions(max_in_flight=4))
    # pressured round: unpressured would fill the whole window (4);
    # under pressure only the single progress-guarantee task moves
    topo._step()
    assert topo.stats()[0].submitted == 1
    assert topo.stats()[0].paused_on_store_pressure > 0
    # with one task in flight, further pressured rounds drain only
    topo._step()
    assert topo.stats()[0].submitted <= 2
    # pressure clears -> the pipeline completes normally
    pressure["used"] = 10
    out = [rt.get(r) for r in topo.run()]
    assert sorted(b[0]["x"] for b in out) == [1, 2, 3, 4]
    assert topo.stats()[0].submitted == 4


def test_executor_auto_budget_from_store_capacity(monkeypatch,
                                                  local_cluster):
    from ray_tpu.data import streaming_executor as se
    from ray_tpu.data.executor import MapSpec

    monkeypatch.setattr(se, "_store_usage", lambda: (0, 80 << 20))
    topo = se.StreamingTopology(
        [MapSpec("map", lambda r: r), MapSpec("map", lambda r: r)],
        iter([]), se.ExecutionOptions())
    # capacity/ (4 * 2 ops) = 10MB, below the 64MB static default
    assert all(op.budget_bytes == 10 << 20 for op in topo.ops)


def test_grouped_aggregate_streams_rows():
    """ADVICE fix regression (memory shape): the aggregate fold must
    consume a partition row-by-row — for a columnar block the transient
    per-row dicts die immediately instead of accumulating into per-group
    lists. With 200k single-group rows the old materializing path held
    ~200k dicts (tens of MB); the streaming fold's peak must stay an
    order of magnitude below that."""
    import tracemalloc

    from ray_tpu.data.aggregate import Sum
    from ray_tpu.data.block import NumpyBlock
    from ray_tpu.data.grouped import _fold_partition

    n = 200_000
    part = NumpyBlock({"k": np.zeros(n, np.int64),
                       "v": np.arange(n, dtype=np.int64)})
    tracemalloc.start()
    out = _fold_partition(part, "k", (Sum("v"),), {})
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert out == [{"k": 0, "sum(v)": n * (n - 1) // 2}]
    # 200k materialized row-dicts cost >30MB; streaming stays way under
    assert peak < 10 << 20, f"fold peak {peak / 1e6:.1f}MB — rows piling?"


def test_grouped_aggregate_mixed_surfaces(local_cluster):
    """Plugin AggregateFns and keyword (col, reducer) aggs compose on
    one pass through the streaming fold."""
    from ray_tpu.data.aggregate import Mean

    rows = [{"k": i % 2, "v": float(i)} for i in range(10)]
    ds = rd.from_items(rows, num_blocks=2)
    out = {r["k"]: r for r in ds.groupby("k").aggregate(
        Mean("v"), vmax=("v", max)).take_all()}
    assert out[0]["mean(v)"] == 4.0 and out[0]["vmax"] == 8.0
    assert out[1]["mean(v)"] == 5.0 and out[1]["vmax"] == 9.0
