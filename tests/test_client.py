"""Remote-driver client tests (ref analog: python/ray/util/client tests):
the client proxy executes tasks/actors/objects for a process with no
local node manager."""

import json
import subprocess
import sys
import time

import pytest

import ray_tpu as rt
from ray_tpu import client as rt_client


@pytest.fixture
def proxy(local_cluster):
    from ray_tpu.core.runtime import get_runtime_context

    ctx = get_runtime_context()
    addr = f"{ctx.gcs_address.host}:{ctx.gcs_address.port}"
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "from ray_tpu.client.server import main; "
         f"main({addr!r}, port=0)"],
        stdout=subprocess.PIPE, text=True)
    line = proc.stdout.readline()
    assert line, "client proxy failed to start"
    port = json.loads(line)["client_port"]
    try:
        yield f"127.0.0.1:{port}"
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_client_tasks_objects_actors(proxy):
    ctx = rt_client.connect(proxy)
    try:
        @ctx.remote
        def double(x):
            return x * 2

        ref = double.remote(21)
        assert ctx.get(ref) == 42

        # put/get + ref as task arg crosses the proxy boundary
        big = list(range(1000))
        data_ref = ctx.put(big)

        @ctx.remote
        def total(xs):
            return sum(xs)

        assert ctx.get(total.remote(data_ref)) == sum(big)

        # wait
        refs = [double.remote(i) for i in range(4)]
        ready, rest = ctx.wait(refs, num_returns=4, timeout=60)
        assert len(ready) == 4 and not rest

        # actors
        @ctx.remote
        class Counter:
            def __init__(self, start):
                self.n = start

            def incr(self, k=1):
                self.n += k
                return self.n

        c = Counter.remote(10)
        assert ctx.get(c.incr.remote()) == 11
        assert ctx.get(c.incr.remote(5)) == 16
        ctx.kill(c)

        # options pass through
        @ctx.remote(num_cpus=1)
        def one():
            return 1

        assert ctx.get(one.remote()) == 1
    finally:
        ctx.disconnect()


def test_client_nested_refs_and_timeout(proxy):
    ctx = rt_client.connect(proxy)
    try:
        @ctx.remote
        def total(xs):
            # reference semantics: only TOP-LEVEL args auto-resolve;
            # nested refs arrive as refs and the task gets them
            import ray_tpu as rt

            return sum(rt.get(xs["a"])) + rt.get(xs["b"][0])

        a = ctx.put([1, 2, 3])
        b = ctx.put(10)
        assert ctx.get(total.remote({"a": a, "b": (b,)})) == 16

        @ctx.remote
        def slow():
            import time

            time.sleep(30)

        import pytest as _pytest
        import time as _time

        t0 = _time.monotonic()
        with _pytest.raises(TimeoutError):
            ctx.get(slow.remote(), timeout=1.0)
        assert _time.monotonic() - t0 < 10  # honored promptly
    finally:
        ctx.disconnect()
