"""HA-grade GCS backing store (VERDICT r5 missing #6; ref analog:
src/ray/gcs/store_client/redis_store_client.h:107): snapshots live in
an EXTERNAL store process, so a head restarted anywhere — not just on
the box holding the old snapshot file — rebuilds its tables."""

import asyncio

import pytest

from ray_tpu._internal.ids import NodeID
from ray_tpu.core.common import Address, NodeInfo


class _Conn:
    on_close: list = []

    async def close(self):
        pass


@pytest.fixture
def store(tmp_path):
    """A SnapshotStoreServer running on a private event loop thread
    (stands in for the store process on another machine)."""
    from ray_tpu._internal.rpc import EventLoopThread
    from ray_tpu.core.persistence import SnapshotStoreServer

    io = EventLoopThread(name="test-store")
    server = SnapshotStoreServer(str(tmp_path / "store-data"))
    port = io.run(server.start("127.0.0.1", 0), 30)
    yield f"rayt://127.0.0.1:{port}", server, tmp_path
    io.run(server.stop(), 10)
    io.stop()


def test_backend_roundtrip(store):
    from ray_tpu.core.persistence import make_backend

    uri, _, _ = store
    b = make_backend(uri)
    assert b.get("snapshot") is None
    b.put("snapshot", b"state-v1")
    assert b.get("snapshot") == b"state-v1"
    b.put_if_absent("blobs/abc", b"blob-bytes")
    assert b.exists("blobs/abc")
    assert b.get("blobs/abc") == b"blob-bytes"
    b.close()


def test_head_restarts_anywhere_against_external_store(store):
    """GCS #1 writes tables to the store; GCS #2 (a fresh object — 'a
    new machine') reloads nodes, KV, and jobs from it."""
    from ray_tpu.core.gcs import GcsServer

    uri, _, _ = store

    async def first_head():
        gcs = GcsServer(persist_path=uri)
        nid = NodeID.random()
        await gcs.rpc_register_node(_Conn(), NodeInfo(
            node_id=nid, address=Address("127.0.0.1", 21001),
            resources_total={"CPU": 8.0}))
        gcs.rpc_kv_put(None, ("ns", "key", b"value", False))
        # big value -> content-addressed blob in the external store
        gcs.rpc_kv_put(None, ("ns", "big", b"x" * 600_000, False))
        gcs.rpc_register_job(None, (None, {"name": "j1"}))
        gcs.mark_dirty()
        gcs._write_snapshot()
        gcs._backend.close()
        return nid

    nid = asyncio.new_event_loop().run_until_complete(first_head())

    # a brand-new head process, pointed at the same store URI
    gcs2 = GcsServer(persist_path=uri)
    try:
        assert nid in gcs2.nodes
        assert gcs2.nodes[nid].resources_total == {"CPU": 8.0}
        assert gcs2.kv["ns"]["key"] == b"value"
        assert gcs2.kv["ns"]["big"] == b"x" * 600_000
        assert len(gcs2.jobs) == 1
        # restored nodes seed the resource-sync log (delta consumers see
        # them) — same invariant as the file backend
        view = gcs2.rpc_get_cluster_resources_delta(None, 0)
        entries = (view["full"] if view["full"] is not None
                   else view["changed"])
        assert nid.hex() in entries
    finally:
        gcs2._backend.close()


def test_file_backend_layout_unchanged(tmp_path):
    """The file backend keeps the pre-backend on-disk layout, so old
    snapshots keep loading."""
    from ray_tpu.core.persistence import FileSnapshotBackend

    base = str(tmp_path / "snap.pkl")
    b = FileSnapshotBackend(base)
    b.put("snapshot", b"data")
    b.put("blobs/deadbeef", b"blob")
    assert (tmp_path / "snap.pkl").read_bytes() == b"data"
    assert (tmp_path / "snap.pkl.blobs" / "deadbeef").read_bytes() == b"blob"
