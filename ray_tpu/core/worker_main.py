"""Worker process entrypoint (ref analog:
python/ray/_private/workers/default_worker.py + the C++ task execution loop
entered from _raylet.pyx:3038). Spawned by the node manager; registers back
and then serves push_task / create_actor / push_actor_task until killed.

Deliberately does NOT import jax at startup — workers boot in ~100ms and
only pay the jax import when a task actually uses it.
"""

from __future__ import annotations

import os
import signal
import sys
import threading


def main():
    from ray_tpu._internal.ids import JobID, NodeID
    from ray_tpu.core.common import Address
    from ray_tpu.core.core_worker import CoreWorker

    node_id = NodeID.from_hex(os.environ["RAYT_NODE_ID"])
    nm_host, nm_port = os.environ["RAYT_NODE_ADDR"].split(":")
    gcs_host, gcs_port = os.environ["RAYT_GCS_ADDR"].split(":")
    job_id = JobID.from_hex(os.environ.get("RAYT_JOB_ID", "00000000"))

    cw = CoreWorker(
        mode="worker", job_id=job_id,
        gcs_address=Address(gcs_host, int(gcs_port)),
        node_address=Address(nm_host, int(nm_port)),
        node_id=node_id)
    cw.connect_cluster()
    # Booted with -S for ~100ms startup; replay sitecustomize (PJRT/TPU
    # plugin registration) off the critical path so jax tasks still work.
    from ray_tpu._internal.spawn import import_site_background

    import_site_background()

    stop = threading.Event()

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    # Orphan watchdog: if the node manager connection drops (raylet died,
    # possibly SIGKILLed), exit instead of lingering forever (ref analog:
    # workers die when their raylet does).
    if cw.node_conn is not None:
        cw.node_conn.on_close.append(lambda _c: stop.set())
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    os._exit(0)


if __name__ == "__main__":
    main()
