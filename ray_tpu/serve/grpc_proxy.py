"""gRPC ingress proxy (ref analog: python/ray/serve/_private/proxy.py
gRPC data plane + grpc_util/: the reference serves user-defined proto
services; this ingress exposes a generic byte-level service so callers
don't need generated stubs).

Service (full method names):
  /rayt.serve.Serve/Predict        unary-unary
  /rayt.serve.Serve/PredictStream  unary-stream

Request bytes: JSON {"app": <name>, "payload": <json value>,
"model_id": <optional>}; response bytes: JSON value per result (one per
stream message for PredictStream). Runs inside an async actor next to
the HTTP proxy, sharing the same DeploymentHandle routing path.
"""

from __future__ import annotations

import asyncio
import json
from typing import Any

_SERVICE = "rayt.serve.Serve"


class GrpcProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._handles: dict[str, Any] = {}
        self._ingress: dict[str, str] = {}
        self._server = None

    # ------------------------------------------------------------- control
    def register_app(self, app_name: str, ingress_deployment: str) -> bool:
        self._ingress[app_name] = ingress_deployment
        self._handles.pop(app_name, None)
        return True

    def unregister_app(self, app_name: str) -> bool:
        self._ingress.pop(app_name, None)
        self._handles.pop(app_name, None)
        return True

    async def start(self) -> int:
        import grpc

        proxy = self

        class _Generic(grpc.GenericRpcHandler):
            def service(self, details):
                if details.method == f"/{_SERVICE}/Predict":
                    return grpc.unary_unary_rpc_method_handler(
                        proxy._predict)
                if details.method == f"/{_SERVICE}/PredictStream":
                    return grpc.unary_stream_rpc_method_handler(
                        proxy._predict_stream)
                return None

        self._server = grpc.server(
            __import__("concurrent.futures", fromlist=["f"])
            .ThreadPoolExecutor(max_workers=8),
            options=[("grpc.so_reuseport", 0)])
        self._server.add_generic_rpc_handlers((_Generic(),))
        self.port = self._server.add_insecure_port(
            f"{self.host}:{self.port}")
        self._server.start()
        return self.port

    async def stop(self):
        if self._server is not None:
            self._server.stop(grace=1.0)

    # --------------------------------------------------------------- data
    def _resolve(self, request_bytes: bytes):
        import grpc

        req = json.loads(request_bytes)
        app_name = req.get("app")
        ingress = self._ingress.get(app_name)
        if ingress is None:
            raise _Abort(grpc.StatusCode.NOT_FOUND,
                         f"no app {app_name!r}")
        handle = self._handles.get(app_name)
        if handle is None:
            from ray_tpu.serve.handle import DeploymentHandle

            handle = DeploymentHandle(ingress, app_name)
            self._handles[app_name] = handle
        model_id = req.get("model_id") or ""
        if model_id:
            handle = handle.options(multiplexed_model_id=model_id)
        return handle, req.get("payload")

    def _predict(self, request_bytes: bytes, context) -> bytes:
        try:
            handle, payload = self._resolve(request_bytes)
            result = handle.remote(payload).result(timeout=300)
            return json.dumps(result, default=str).encode()
        except _Abort as e:
            context.abort(e.code, e.detail)
        except Exception as e:
            import grpc

            context.abort(grpc.StatusCode.INTERNAL, repr(e))

    def _predict_stream(self, request_bytes: bytes, context):
        try:
            handle, payload = self._resolve(request_bytes)
            for item in handle.options(stream=True).remote(payload):
                yield json.dumps(item, default=str).encode()
        except _Abort as e:
            context.abort(e.code, e.detail)
        except Exception as e:
            import grpc

            context.abort(grpc.StatusCode.INTERNAL, repr(e))


class _Abort(Exception):
    def __init__(self, code, detail):
        self.code = code
        self.detail = detail
