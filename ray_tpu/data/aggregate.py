"""Aggregate plugin API (ref analog: python/ray/data/aggregate.py
AggregateFn + the built-ins Count/Sum/Min/Max/Mean/Std).

An AggregateFn is a distributive reducer: per-block tasks fold rows into
a small accumulator (`init` + `accumulate_row`), accumulators `merge`
pairwise, and `finalize` produces the result — so a global aggregation
moves only O(blocks) accumulators to the driver, never rows, and a
grouped aggregation folds each key's rows inside its hash partition.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional


class AggregateFn:
    def __init__(self, init: Callable[[], Any],
                 accumulate_row: Callable[[Any, dict], Any],
                 merge: Callable[[Any, Any], Any],
                 finalize: Optional[Callable[[Any], Any]] = None,
                 name: str = "agg"):
        self.init = init
        self.accumulate_row = accumulate_row
        self.merge = merge
        self.finalize = finalize or (lambda a: a)
        self.name = name


def _col(row: dict, on: Optional[str]):
    return row if on is None else row[on]


class Count(AggregateFn):
    def __init__(self, name: str = "count()"):
        super().__init__(lambda: 0, lambda a, r: a + 1,
                         lambda a, b: a + b, name=name)


class Sum(AggregateFn):
    def __init__(self, on: str, name: Optional[str] = None):
        super().__init__(lambda: 0,
                         lambda a, r: a + _col(r, on),
                         lambda a, b: a + b,
                         name=name or f"sum({on})")


class Min(AggregateFn):
    def __init__(self, on: str, name: Optional[str] = None):
        super().__init__(lambda: None,
                         lambda a, r: _col(r, on) if a is None
                         else min(a, _col(r, on)),
                         lambda a, b: b if a is None
                         else (a if b is None else min(a, b)),
                         name=name or f"min({on})")


class Max(AggregateFn):
    def __init__(self, on: str, name: Optional[str] = None):
        super().__init__(lambda: None,
                         lambda a, r: _col(r, on) if a is None
                         else max(a, _col(r, on)),
                         lambda a, b: b if a is None
                         else (a if b is None else max(a, b)),
                         name=name or f"max({on})")


class Mean(AggregateFn):
    def __init__(self, on: str, name: Optional[str] = None):
        super().__init__(lambda: (0.0, 0),
                         lambda a, r: (a[0] + _col(r, on), a[1] + 1),
                         lambda a, b: (a[0] + b[0], a[1] + b[1]),
                         lambda a: a[0] / a[1] if a[1] else float("nan"),
                         name=name or f"mean({on})")


class Std(AggregateFn):
    """Sample standard deviation via parallel Welford/Chan merge (the
    numerically stable pairwise form the reference uses)."""

    def __init__(self, on: str, ddof: int = 1, name: Optional[str] = None):
        def acc(a, r):
            n, mean, m2 = a
            x = _col(r, on)
            n += 1
            d = x - mean
            mean += d / n
            m2 += d * (x - mean)
            return (n, mean, m2)

        def merge(a, b):
            na, ma, m2a = a
            nb, mb, m2b = b
            if na == 0:
                return b
            if nb == 0:
                return a
            n = na + nb
            d = mb - ma
            return (n, ma + d * nb / n,
                    m2a + m2b + d * d * na * nb / n)

        def fin(a):
            n, _, m2 = a
            if n - ddof <= 0:
                return float("nan")
            return math.sqrt(m2 / (n - ddof))

        super().__init__(lambda: (0, 0.0, 0.0), acc, merge, fin,
                         name=name or f"std({on})")
