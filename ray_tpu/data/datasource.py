"""Datasources: file reads fan out as tasks, one block per file/shard (ref
analog: python/ray/data/datasource/ + read_api.py)."""

from __future__ import annotations

import glob as globlib
import os
from typing import Optional

import ray_tpu as rt


def _expand(paths) -> list[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                f for f in globlib.glob(os.path.join(p, "**"), recursive=True)
                if os.path.isfile(f)))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(globlib.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files match {paths!r}")
    return out


def read_text(paths, *, drop_empty_lines: bool = True):
    from ray_tpu.data.dataset import Dataset

    def read_file(path: str):
        with open(path) as f:
            lines = f.read().splitlines()
        if drop_empty_lines:
            lines = [ln for ln in lines if ln]
        return [{"text": ln} for ln in lines]

    task = rt.remote(num_cpus=1)(read_file)
    return Dataset([task.remote(p) for p in _expand(paths)])


def read_csv(paths):
    from ray_tpu.data.dataset import Dataset

    def read_file(path: str):
        from pyarrow import csv as pa_csv

        return pa_csv.read_csv(path)  # arrow block (columnar)

    task = rt.remote(num_cpus=1)(read_file)
    return Dataset([task.remote(p) for p in _expand(paths)])


def read_parquet(paths, *, columns: Optional[list[str]] = None):
    from ray_tpu.data.dataset import Dataset

    def read_file(path: str, columns):
        import pyarrow.parquet as pq

        # arrow table IS the block: stays columnar through the pipeline,
        # zero-copy into numpy batches for train ingest
        return pq.read_table(path, columns=columns)

    task = rt.remote(num_cpus=1)(read_file)
    return Dataset([task.remote(p, columns) for p in _expand(paths)])


def read_json(paths):
    from ray_tpu.data.dataset import Dataset

    def read_file(path: str):
        import json

        with open(path) as f:
            first = f.read(1)
            f.seek(0)
            if first == "[":
                return json.load(f)
            return [json.loads(ln) for ln in f if ln.strip()]

    task = rt.remote(num_cpus=1)(read_file)
    return Dataset([task.remote(p) for p in _expand(paths)])


def write_parquet(dataset, path: str) -> None:
    import pyarrow as pa
    import pyarrow.parquet as pq

    from ray_tpu.data.block import is_arrow_block

    os.makedirs(path, exist_ok=True)
    for i, ref in enumerate(dataset._iter_block_refs()):
        block = rt.get(ref)
        if is_arrow_block(block):
            if block.num_rows == 0:
                continue
            table = block
        elif block:
            table = pa.Table.from_pylist(block)
        else:
            continue
        pq.write_table(table,
                       os.path.join(path, f"part-{i:05d}.parquet"))


def read_npz(paths):
    """One columnar NumpyBlock per .npz file: the multi-dim-column
    format (token matrices, image stacks) Arrow files can't carry.
    Producer side: ray_tpu.rl.offline.write_offline_dataset or plain
    np.savez of equal-length arrays."""
    from ray_tpu.data.block import NumpyBlock
    from ray_tpu.data.dataset import Dataset

    def read_file(path: str):
        import numpy as np

        with np.load(path) as z:
            return NumpyBlock({k: z[k] for k in z.files})

    task = rt.remote(num_cpus=1)(read_file)
    return Dataset([task.remote(p) for p in _expand(paths)])
