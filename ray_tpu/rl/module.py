"""RLModule — the jax policy/value network (ref analog:
rllib/core/rl_module/rl_module.py `RLModule`; torch modules there, pure
jax pytrees here so the learner jits end-to-end and shards over the
mesh)."""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class MLPModuleConfig:
    observation_size: int
    num_actions: int
    hidden: tuple = (64, 64)


def init_params(cfg: MLPModuleConfig, key: jax.Array) -> dict:
    """Shared torso + policy and value heads."""
    dims = (cfg.observation_size,) + tuple(cfg.hidden)
    keys = jax.random.split(key, len(dims) + 1)
    torso = [
        {"w": (jax.random.normal(k, (a, b))
               * math.sqrt(2.0 / a)).astype(jnp.float32),
         "b": jnp.zeros((b,), jnp.float32)}
        for k, a, b in zip(keys, dims[:-1], dims[1:])
    ]
    h = dims[-1]
    return {
        "torso": torso,
        "pi": {"w": (jax.random.normal(keys[-2], (h, cfg.num_actions))
                     * 0.01).astype(jnp.float32),
               "b": jnp.zeros((cfg.num_actions,), jnp.float32)},
        "vf": {"w": (jax.random.normal(keys[-1], (h, 1))
                     * 1.0 / math.sqrt(h)).astype(jnp.float32),
               "b": jnp.zeros((1,), jnp.float32)},
    }


def forward(params: dict, obs: jax.Array) -> tuple[jax.Array, jax.Array]:
    """-> (action logits [B, A], value [B])"""
    x = obs
    for layer in params["torso"]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    logits = x @ params["pi"]["w"] + params["pi"]["b"]
    value = (x @ params["vf"]["w"] + params["vf"]["b"])[:, 0]
    return logits, value


def sample_actions(params: dict, obs: np.ndarray, key: jax.Array):
    """Host-side sampling helper for env runners (CPU jax)."""
    logits, value = forward(params, jnp.asarray(obs))
    action = jax.random.categorical(key, logits, axis=-1)
    logp = jax.nn.log_softmax(logits)[jnp.arange(logits.shape[0]), action]
    return (np.asarray(action), np.asarray(logp), np.asarray(value))
