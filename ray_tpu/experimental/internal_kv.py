"""Internal KV helpers over the GCS (ref analog:
python/ray/experimental/internal_kv.py — the `_internal_kv_*` functions
libraries build rendezvous/metadata on)."""

from __future__ import annotations

from typing import Optional


def _client():
    from ray_tpu.api import _core_worker

    return _core_worker()


def _internal_kv_initialized() -> bool:
    try:
        return _client() is not None
    except Exception:
        return False


def _internal_kv_put(key: bytes | str, value: bytes | str, *,
                     overwrite: bool = True,
                     namespace: str = "kv") -> bool:
    """Returns True iff the key was NEWLY added (reference semantics:
    False means it already existed)."""
    cw = _client()
    key = key.decode() if isinstance(key, bytes) else key
    value = value.encode() if isinstance(value, str) else value
    added = cw.io.run(cw.gcs.kv_put(key, value, namespace=namespace,
                                    overwrite=overwrite))
    return bool(added)


def _internal_kv_get(key: bytes | str, *,
                     namespace: str = "kv") -> Optional[bytes]:
    cw = _client()
    key = key.decode() if isinstance(key, bytes) else key
    return cw.io.run(cw.gcs.kv_get(key, namespace=namespace))


def _internal_kv_exists(key: bytes | str, *, namespace: str = "kv") -> bool:
    return _internal_kv_get(key, namespace=namespace) is not None


def _internal_kv_del(key: bytes | str, *, namespace: str = "kv") -> bool:
    cw = _client()
    key = key.decode() if isinstance(key, bytes) else key
    return bool(cw.io.run(cw.gcs.kv_del(key, namespace=namespace)))


def _internal_kv_list(prefix: bytes | str = "", *,
                      namespace: str = "kv") -> list[bytes]:
    cw = _client()
    prefix = prefix.decode() if isinstance(prefix, bytes) else prefix
    keys = cw.io.run(cw.gcs.kv_keys(prefix, namespace=namespace))
    return [k.encode() for k in keys]
