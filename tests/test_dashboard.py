"""Dashboard head: Prometheus metrics export + job submission API (ref
analogs: dashboard/modules/job tests, metrics_agent Prometheus export)."""

import json
import time
import urllib.request

import pytest

import ray_tpu as rt
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def dash_cluster():
    cluster = Cluster(head_resources={"CPU": 4.0}, dashboard_port=0)
    cluster.connect()
    assert cluster.dashboard_port and cluster.dashboard_port > 0
    try:
        yield cluster
    finally:
        cluster.shutdown()


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as r:
        return r.read().decode()


def test_metrics_prometheus_export(dash_cluster):
    from ray_tpu.util.metrics import Counter, Gauge

    c = Counter("test_requests_total", tag_keys=("route",))
    c.inc(3.0, tags={"route": "a"})
    c.inc(2.0, tags={"route": "a"})
    g = Gauge("test_queue_depth")
    g.set(7.0)
    time.sleep(0.5)  # async publish to GCS

    body = _get(dash_cluster.dashboard_port, "/metrics")
    assert "# TYPE test_requests_total counter" in body
    assert 'test_requests_total{route="a"} 5.0' in body
    assert "test_queue_depth 7.0" in body


def test_state_endpoints(dash_cluster):
    @rt.remote(num_cpus=0)
    class Marker:
        def ping(self):
            return 1

    m = Marker.remote()
    rt.get(m.ping.remote(), timeout=30)

    nodes = json.loads(_get(dash_cluster.dashboard_port, "/api/nodes"))
    assert any(n["alive"] for n in nodes)
    actors = json.loads(_get(dash_cluster.dashboard_port, "/api/actors"))
    assert any(a["class_name"] == "Marker" for a in actors)
    status = json.loads(
        _get(dash_cluster.dashboard_port, "/api/cluster_status"))
    assert status["num_nodes"] >= 1


def test_job_submission_lifecycle(dash_cluster, tmp_path):
    script = tmp_path / "job_script.py"
    script.write_text(
        "import os\n"
        "import ray_tpu as rt\n"
        "rt.init(address=os.environ['RAYT_ADDRESS'])\n"
        "@rt.remote\n"
        "def f(x):\n"
        "    return x * 2\n"
        "print('job result:', rt.get(f.remote(21)))\n"
        "rt.shutdown()\n")
    port = dash_cluster.dashboard_port
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/jobs",
        data=json.dumps(
            {"entrypoint": f"python {script}",
             "env": {"PYTHONPATH": "/root/repo"}}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        sub_id = json.loads(r.read())["submission_id"]

    deadline = time.monotonic() + 90
    status = None
    while time.monotonic() < deadline:
        status = json.loads(_get(port, f"/api/jobs/{sub_id}"))
        if status["status"] in ("SUCCEEDED", "FAILED"):
            break
        time.sleep(0.5)
    logs = _get(port, f"/api/jobs/{sub_id}/logs")
    assert status["status"] == "SUCCEEDED", (status, logs)
    assert "job result: 42" in logs


def _post(port, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read().decode())


def test_job_submit_with_runtime_env(dash_cluster, tmp_path):
    """Submitted jobs run through the runtime-env machinery (VERDICT r3
    #10): working_dir becomes the driver cwd + import root, env_vars
    apply, and logs stream incrementally via the offset endpoint."""
    wd = tmp_path / "jobwd"
    wd.mkdir()
    (wd / "jobmod.py").write_text("MAGIC = 'wd-import-ok'\n")
    port = dash_cluster.dashboard_port
    out = _post(port, "/api/jobs", {
        "entrypoint": ("python -c \"import os, jobmod; "
                       "print(jobmod.MAGIC, os.environ['JOBVAR'], "
                       "os.path.basename(os.getcwd()))\""),
        "runtime_env": {"working_dir": str(wd),
                        "env_vars": {"JOBVAR": "v-42"}},
    })
    sub_id = out["submission_id"]
    deadline = time.monotonic() + 60
    status = None
    while time.monotonic() < deadline:
        status = json.loads(_get(port, f"/api/jobs/{sub_id}"))
        if status["status"] in ("SUCCEEDED", "FAILED"):
            break
        time.sleep(0.3)
    logs = _get(port, f"/api/jobs/{sub_id}/logs")
    assert status["status"] == "SUCCEEDED", logs
    assert "wd-import-ok v-42 jobwd" in logs
    # incremental tail endpoint (follow-mode streaming)
    tail = json.loads(_get(port, f"/api/jobs/{sub_id}/logs?offset=0"))
    assert "wd-import-ok" in tail["data"]
    assert tail["offset"] > 0 and tail["running"] is False
    rest = json.loads(_get(port,
                           f"/api/jobs/{sub_id}/logs?offset={tail['offset']}"))
    assert rest["data"] == ""


def test_index_page_serves_static_html(dash_cluster):
    """`/` serves the operator page (ref: dashboard web client, scoped):
    static HTML wired to the JSON endpoints it polls."""
    html = _get(dash_cluster.dashboard_port, "/")
    assert html.lstrip().startswith("<!DOCTYPE html>")
    for endpoint in ("/api/nodes", "/api/actors", "/api/jobs"):
        assert endpoint in html
