"""Remote-driver client (ref analog: python/ray/util/client/ — the
"ray://" proxy API). A process on ANY host connects to the cluster's
client proxy and gets the task/actor/object API without a local node
manager or shared-memory store; the proxy executes operations as the
owning driver.

    from ray_tpu import client

    ctx = client.connect("head-host:10001")

    @ctx.remote
    def f(x):
        return x * 2

    ctx.get(f.remote(21))  # 42

The client is dependency-light: it needs only the RPC framing and
cloudpickle — no jax, no cluster runtime — so thin CLI boxes and
notebooks can drive TPU clusters.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from ray_tpu.client.server import _ClientRefMarker


class ClientObjectRef:
    def __init__(self, ctx: "ClientContext", ref_id: str):
        self._ctx = ctx
        self._id = ref_id

    def __repr__(self):
        return f"ClientObjectRef({self._id[:12]})"

    def __del__(self):
        try:
            self._ctx._release(self._id)
        except Exception:
            pass


class ClientRemoteFunction:
    def __init__(self, ctx: "ClientContext", fn, options: dict):
        self._ctx = ctx
        self._fn = fn
        self._options = options

    def options(self, **opts) -> "ClientRemoteFunction":
        return ClientRemoteFunction(self._ctx, self._fn,
                                    {**self._options, **opts})

    def remote(self, *args, **kwargs) -> ClientObjectRef:
        import cloudpickle

        rid = self._ctx._call("client_task", (
            cloudpickle.dumps(self._fn),
            self._ctx._encode_args(args),
            self._ctx._encode_args(kwargs),
            self._options))
        return ClientObjectRef(self._ctx, rid)


class ClientActorMethod:
    def __init__(self, ctx, actor_id: str, name: str):
        self._ctx = ctx
        self._actor_id = actor_id
        self._name = name

    def remote(self, *args, **kwargs) -> ClientObjectRef:
        rid = self._ctx._call("client_actor_call", (
            self._actor_id, self._name,
            self._ctx._encode_args(args),
            self._ctx._encode_args(kwargs)))
        return ClientObjectRef(self._ctx, rid)


class ClientActorHandle:
    def __init__(self, ctx, actor_id: str):
        self._ctx = ctx
        self._actor_id = actor_id

    def __getattr__(self, name: str) -> ClientActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ClientActorMethod(self._ctx, self._actor_id, name)


class ClientActorClass:
    def __init__(self, ctx, cls, options: dict):
        self._ctx = ctx
        self._cls = cls
        self._options = options

    def options(self, **opts) -> "ClientActorClass":
        return ClientActorClass(self._ctx, self._cls,
                                {**self._options, **opts})

    def remote(self, *args, **kwargs) -> ClientActorHandle:
        import cloudpickle

        aid = self._ctx._call("client_actor_create", (
            cloudpickle.dumps(self._cls),
            self._ctx._encode_args(args),
            self._ctx._encode_args(kwargs),
            self._options))
        return ClientActorHandle(self._ctx, aid)


class ClientContext:
    """The remote-driver API surface (mirrors the top-level rt API)."""

    def __init__(self, host: str, port: int):
        from ray_tpu._internal.rpc import connect

        self._io = _LoopThread()
        self._conn = self._io.run(connect(host, port))
        assert self._call("client_ping") is True

    # ---------------------------------------------------------- plumbing
    def _call(self, method: str, arg: Any = None, timeout: float = 300.0):
        return self._io.run(self._conn.call(method, arg, timeout=timeout))

    def _encode_args(self, args):
        # recursive: refs nested in containers must become markers too —
        # pickling a ClientObjectRef would drag the context's event-loop
        # thread into the payload
        def enc(a):
            if isinstance(a, ClientObjectRef):
                return _ClientRefMarker(a._id)
            if isinstance(a, dict):
                return {k: enc(v) for k, v in a.items()}
            if isinstance(a, (list, tuple)):
                out = [enc(v) for v in a]
                return tuple(out) if isinstance(a, tuple) else out
            return a

        if isinstance(args, dict):
            return {k: enc(v) for k, v in args.items()}
        return [enc(a) for a in args]

    def _release(self, ref_id: str):
        if not self._io.closed:
            self._io.run_nowait(
                self._conn.call("client_release", [ref_id], timeout=30))

    # --------------------------------------------------------------- api
    def remote(self, *args, **kwargs):
        def wrap(target, options):
            if isinstance(target, type):
                return ClientActorClass(self, target, options)
            return ClientRemoteFunction(self, target, options)

        if len(args) == 1 and not kwargs and callable(args[0]):
            return wrap(args[0], {})
        return lambda target: wrap(target, kwargs)

    def put(self, value: Any) -> ClientObjectRef:
        import cloudpickle

        rid = self._call("client_put", cloudpickle.dumps(value))
        return ClientObjectRef(self, rid)

    def get(self, refs, timeout: Optional[float] = None):
        import cloudpickle

        single = isinstance(refs, ClientObjectRef)
        ids = [refs._id] if single else [r._id for r in refs]
        # indefinite waits poll in BOUNDED wire calls: one long-lived RPC
        # would trip the transport timeout (and strand a proxy executor
        # thread) on any task slower than the wire budget
        ready = self._poll_until(ids, len(ids), timeout)
        if len(ready) < len(ids):
            raise TimeoutError(
                f"get timed out after {timeout}s "
                f"({len(ready)}/{len(ids)} ready)")
        blobs = self._call("client_get", (ids, 30.0), timeout=60)
        values = [cloudpickle.loads(b) for b in blobs]
        return values[0] if single else values

    def _poll_until(self, ids, num_returns: int,
                    timeout: Optional[float]):
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            step = 25.0 if deadline is None else max(
                0.0, min(25.0, deadline - _time.monotonic()))
            ready, _ = self._call("client_wait", (ids, num_returns, step),
                                  timeout=step + 35)
            if len(ready) >= num_returns:
                return ready
            if deadline is not None and _time.monotonic() >= deadline:
                return ready

    def wait(self, refs, *, num_returns: int = 1,
             timeout: Optional[float] = None):
        by_id = {r._id: r for r in refs}
        ready = self._poll_until([r._id for r in refs], num_returns,
                                 timeout)
        ready_set = set(ready)
        return ([by_id[i] for i in ready],
                [r for r in refs if r._id not in ready_set])

    def kill(self, actor: ClientActorHandle):
        return self._call("client_actor_kill", actor._actor_id)

    def disconnect(self):
        self._io.close()


class _LoopThread:
    """Private asyncio loop on a daemon thread for the sync client API."""

    def __init__(self):
        import asyncio

        self._loop = asyncio.new_event_loop()
        self.closed = False
        self._thread = threading.Thread(
            target=self._loop.run_forever, name="rayt-client-io",
            daemon=True)
        self._thread.start()

    def run(self, coro):
        import asyncio

        return asyncio.run_coroutine_threadsafe(coro, self._loop).result()

    def run_nowait(self, coro):
        import asyncio

        asyncio.run_coroutine_threadsafe(coro, self._loop)

    def close(self):
        self.closed = True
        self._loop.call_soon_threadsafe(self._loop.stop)


def connect(address: str) -> ClientContext:
    """Connect to a cluster's client proxy ("host:port" or
    "rayt://host:port")."""
    address = address.replace("rayt://", "")
    host, _, port = address.partition(":")
    return ClientContext(host, int(port or 10001))
