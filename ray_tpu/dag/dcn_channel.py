"""Cross-node DCN ring channels for compiled DAGs.

Ref analog: the reference's compiled-graph cross-node channels
(python/ray/experimental/channel/ — a shm ring on the reader's node fed
by the object transport). Here the channel is a peer-to-peer stream over
the EXISTING RPC plane: every worker (and the driver) already runs an
``RpcServer`` (core_worker.py `_async_connect`), so the consumer side
registers a sink under a token on its server and the producer dials it
once at attach time — a persistent connection, no per-tick control
plane.

Per-tick cost mirrors the shm ring's contract at DCN distance:

* items travel as NOTIFY frames; payloads the producer pre-serializes on
  its tick thread ride the PR-4 scatter-gather framing verbatim
  (``rpc.Serialized`` — each pickle-5 buffer reaches the transport as
  its own buffer, one join in the transport), and the consumer
  deserializes over the received contiguous buffer, so large numpy
  payloads alias the receive buffer instead of bouncing through an
  extra copy (bytes are immutable and refcounted — no pin rule needed
  on this side).
* flow control is credit-based, mirroring the ring's ``n_slots``: the
  producer starts with ``n_slots`` credits, each write consumes one,
  and the consumer returns a credit as each item is read — so at most
  ``n_slots`` ticks buffer between the stages, the same pipelining
  window a shm ring gives (GPipe-style microbatch overlap), and a slow
  consumer backpressures the producer instead of ballooning memory.
* close is symmetric: either side closing surfaces ``ChannelClosed`` on
  the peer's next read/write, including while blocked on a full (no
  credits) or empty (no items) channel — same semantics the shm ring's
  ``closed`` header byte provides.

Wire methods (all on the consumer's existing RpcServer / connection):
``dcn_open`` (handshake REQUEST, returns the credit window) and the
per-token ``dcn.item.<t>`` / ``dcn.credit.<t>`` / ``dcn.close.<t>``
NOTIFY frames.
"""

from __future__ import annotations

import asyncio
import threading
import time
from collections import deque
from dataclasses import dataclass

from ray_tpu._internal.rpc import RpcError, Serialized, connect
from ray_tpu._internal.serialization import serialize, serialized_size
from ray_tpu.dag.channel import ChannelClosed, ChannelStats


@dataclass(frozen=True)
class DcnChannelSpec:
    """Serializable descriptor shipped inside DAG schedules. The holder
    whose process registered ``token`` attaches as the consumer; every
    other attacher dials (host, port) and becomes the producer."""
    token: str
    host: str
    port: int
    n_slots: int
    slot_size: int   # advisory (compile-time buffer_size_bytes)


# process-global endpoint registry: token -> _DcnSink (consumer side)
_registry_lock = threading.Lock()
_sinks: dict[str, "_DcnSink"] = {}


def _core_worker():
    from ray_tpu.core.object_ref import get_core_worker

    cw = get_core_worker()
    if cw is None:
        from ray_tpu.api import _core_worker as api_cw

        cw = api_cw()
    if cw is None:
        raise RuntimeError("DCN channels need an initialized ray_tpu "
                           "worker or driver (rt.init first)")
    return cw


def _rpc_dcn_open(conn, token: str) -> int:
    """Handshake handler on the consumer's RpcServer: bind the producer's
    connection to the token's sink and grant the initial credit window."""
    with _registry_lock:
        sink = _sinks.get(token)
    if sink is None:
        raise RpcError(f"unknown dcn channel {token!r}")
    sink.bind(conn)
    return sink.n_slots


def ensure_dcn_service(cw) -> None:
    """Idempotently register the handshake handler on this process's
    existing RpcServer (the wire path workers already serve leases and
    object transfer on)."""
    if "dcn_open" not in cw.server.handlers:
        cw.server.add_handler("dcn_open", _rpc_dcn_open)


class _DcnSink:
    """Consumer-side endpoint: receives items on the IO loop, hands them
    to the (blocking) DAG loop thread, returns credits as items drain."""

    def __init__(self, token: str, n_slots: int, loop):
        self.token = token
        self.n_slots = n_slots
        self._loop = loop
        self._items: deque = deque()
        self._cv = threading.Condition()
        self._closed = False
        self._conn = None
        self.stats = ChannelStats()

    # ------------------------------------------------ IO-loop callbacks
    def bind(self, conn):
        self._conn = conn
        conn.on_notify(f"dcn.item.{self.token}", self._on_item)
        conn.on_notify(f"dcn.close.{self.token}", self._on_close)
        conn.on_close.append(lambda _c: self._on_close())

    def _on_item(self, value):
        with self._cv:
            self._items.append(value)
            self._cv.notify_all()

    def _on_close(self, _arg=None):
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    # ------------------------------------------- consumer-thread side
    def read(self, timeout: float | None):
        deadline = None if timeout is None else time.monotonic() + timeout
        st = self.stats
        with self._cv:
            while not self._items:
                if self._closed:
                    st.end_read_block()
                    raise ChannelClosed()
                if st.read_blocked_since is None:
                    st.read_blocked_since = time.monotonic()
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    st.end_read_block()
                    raise TimeoutError("dcn channel read timed out")
                self._cv.wait(timeout=(remaining if remaining is not None
                                       else 1.0))
            st.end_read_block()
            value = self._items.popleft()
        st.reads += 1
        self._grant_credit(1)
        return value

    def _grant_credit(self, n: int):
        conn = self._conn
        if conn is None or conn.closed:
            return
        try:
            asyncio.run_coroutine_threadsafe(
                conn.notify(f"dcn.credit.{self.token}", n), self._loop)
        except RuntimeError:
            pass  # loop shut down mid-teardown

    def close(self):
        with _registry_lock:
            _sinks.pop(self.token, None)
        conn = self._conn
        if conn is not None and not conn.closed:
            try:
                asyncio.run_coroutine_threadsafe(conn.close(), self._loop)
            except RuntimeError:
                pass
        self._on_close()


class DcnConsumerChannel:
    """Read side of a DCN channel (the endpoint owner)."""

    def __init__(self, sink: _DcnSink, spec: DcnChannelSpec):
        self._sink = sink
        self.spec = spec
        self._closed = False

    def read(self, timeout: float | None = None):
        return self._sink.read(timeout)

    def write(self, value, timeout: float | None = None):
        raise RuntimeError("consumer side of a DCN channel cannot write")

    # ---------------------------------------------------- observability
    @property
    def stats(self) -> ChannelStats:
        return self._sink.stats

    def occupancy(self) -> int:
        return len(self._sink._items)

    def cursor_state(self) -> tuple[int, int]:
        """(items consumed, items received) — the DCN twin of the shm
        ring's (read cursor, write seq) for the _get_tick timeout error."""
        st = self._sink.stats
        return st.reads, st.reads + len(self._sink._items)

    def snapshot(self) -> dict:
        snap = self._sink.stats.snapshot()
        snap["occupancy"] = self.occupancy()
        snap["pinned_slots"] = 0
        snap["n_slots"] = self.spec.n_slots
        return snap

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._sink.close()


class DcnProducerChannel:
    """Write side: dials the consumer's RpcServer once, then streams
    NOTIFY frames under the credit window."""

    def __init__(self, spec: DcnChannelSpec, cw=None):
        cw = cw or _core_worker()
        self.spec = spec
        self._io = cw.io
        self._credits = threading.Semaphore(0)
        # mirror of the semaphore for snapshots; += / -= are LOAD/ADD/
        # STORE sequences hit from two threads (tick thread vs IO-loop
        # credit grants), so the mirror mutates under its own lock — a
        # lost update would skew the credits/occupancy diagnostics
        # permanently, not transiently
        self._credit_avail = 0
        self._credit_lock = threading.Lock()
        self._closed = threading.Event()
        self._item_method = f"dcn.item.{spec.token}"
        self.stats = ChannelStats()
        self._conn = self._io.run(self._open(spec), timeout=60.0)

    async def _open(self, spec: DcnChannelSpec):
        conn = await connect(spec.host, spec.port)
        conn.on_notify(f"dcn.credit.{spec.token}", self._on_credit)
        conn.on_close.append(lambda _c: self._closed.set())
        window = await conn.call("dcn_open", spec.token, timeout=30.0)
        for _ in range(int(window)):
            self._credits.release()
        with self._credit_lock:
            self._credit_avail += int(window)
        return conn

    def _on_credit(self, n):
        for _ in range(int(n)):
            self._credits.release()
        with self._credit_lock:
            self._credit_avail += int(n)

    def write(self, value, timeout: float | None = None):
        self.write_chunks(serialize(value), timeout=timeout)

    def write_chunks(self, chunks: list, total: int | None = None,
                     timeout: float | None = None):
        """Send one pre-serialized item. Fire-and-forget onto the IO
        loop (FIFO per thread); the credit window paces the producer, so
        at most n_slots items are ever in flight past the consumer's
        reads. The chunk buffers are handed to the transport
        asynchronously — treat written values as frozen."""
        deadline = None if timeout is None else time.monotonic() + timeout
        st = self.stats
        while not self._credits.acquire(timeout=0.2):
            if self._closed.is_set():
                st.end_write_block()
                raise ChannelClosed()
            if st.write_blocked_since is None:
                st.write_blocked_since = time.monotonic()
            if deadline is not None and time.monotonic() > deadline:
                st.end_write_block()
                raise TimeoutError(
                    "dcn channel write timed out (no credits: consumer "
                    "is >n_slots ticks behind)")
        st.end_write_block()
        with self._credit_lock:
            self._credit_avail -= 1
        conn = self._conn
        if conn is None or self._closed.is_set():
            raise ChannelClosed()
        payload = Serialized(chunks)
        try:
            fut = asyncio.run_coroutine_threadsafe(
                conn.notify(self._item_method, payload),
                self._io.loop)
            # fire-and-forget: a send on a concurrently-dying connection
            # surfaces via on_close -> ChannelClosed on the NEXT write;
            # consume the future's exception so it never logs unobserved
            fut.add_done_callback(lambda f: f.exception())
        except RuntimeError:
            self._closed.set()
            raise ChannelClosed()
        # count AFTER the frame reached the transport: the ChannelClosed
        # path above must not report a phantom tick to the dag manager
        st.writes += 1
        st.bytes_written += (serialized_size(chunks)
                             if total is None else total)

    def read(self, timeout: float | None = None):
        raise RuntimeError("producer side of a DCN channel cannot read")

    # ---------------------------------------------------- observability
    def occupancy(self) -> int:
        """In-flight items past the consumer's reads = window consumed."""
        return max(0, self.spec.n_slots - self._credit_avail)

    def cursor_state(self) -> tuple[int, int]:
        return self.stats.writes, self.stats.writes

    def snapshot(self) -> dict:
        snap = self.stats.snapshot()
        snap["occupancy"] = self.occupancy()
        snap["pinned_slots"] = 0
        snap["n_slots"] = self.spec.n_slots
        snap["credits"] = self._credit_avail
        return snap

    def close(self):
        conn = self._conn
        if conn is None:
            return  # idempotent
        self._conn = None

        async def _shut():
            try:
                if not conn.closed:
                    await conn.notify(f"dcn.close.{self.spec.token}")
                    await conn.close()
            except Exception:
                pass

        try:
            self._io.run(_shut(), timeout=10.0)
        except Exception:
            pass
        self._closed.set()


def create_endpoint(token: str, n_slots: int, slot_size: int,
                    cw=None) -> DcnConsumerChannel:
    """Create the consumer-side endpoint in THIS process, listening on
    the process's existing RpcServer."""
    cw = cw or _core_worker()
    ensure_dcn_service(cw)
    sink = _DcnSink(token, n_slots, cw.io.loop)
    with _registry_lock:
        _sinks[token] = sink
    addr = cw.worker_info.address
    spec = DcnChannelSpec(token=token, host=addr.host, port=addr.port,
                          n_slots=n_slots, slot_size=slot_size)
    return DcnConsumerChannel(sink, spec)


def attach_channel(spec):
    """Attach any channel flavor from its serializable spec: device
    specs wrap their inner transport in the jax.Array framing; the
    process that registered a DCN token gets the consumer side, any
    other process the producer side; shm specs attach as before."""
    from ray_tpu.dag.device_channel import DeviceChannelSpec, attach_device

    if isinstance(spec, DeviceChannelSpec):
        return attach_device(spec)
    if isinstance(spec, DcnChannelSpec):
        with _registry_lock:
            sink = _sinks.get(spec.token)
        if sink is not None:
            return DcnConsumerChannel(sink, spec)
        return DcnProducerChannel(spec)
    from ray_tpu.dag.channel import ShmChannel

    return ShmChannel.attach(spec)


def _dcn_create_endpoints(self, reqs: list[tuple[str, int, int]]) -> list:
    """Runs on a consumer ACTOR via ``__rayt_apply__`` at compile time:
    create one endpoint per (token, n_slots, slot_size) request on this
    worker's RpcServer and return the dialable specs."""
    return [create_endpoint(token, n_slots, slot_size).spec
            for (token, n_slots, slot_size) in reqs]
