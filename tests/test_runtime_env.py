"""Runtime env materialization (ref analog:
python/ray/_private/runtime_env/plugin.py + packaging.py; tests mirror
tests/test_runtime_env_env_vars.py / test_runtime_env_working_dir.py)."""

import os
import textwrap

import pytest

import ray_tpu as rt


def test_env_vars_visible_in_task(local_cluster):
    @rt.remote(runtime_env={"env_vars": {"RAYT_TEST_FLAG": "hello42"}})
    def read_env():
        return os.environ.get("RAYT_TEST_FLAG")

    assert rt.get(read_env.remote(), timeout=60) == "hello42"


def test_env_vars_visible_in_actor(local_cluster):
    @rt.remote(runtime_env={"env_vars": {"ACTOR_FLAG": "on"}})
    class A:
        def read(self):
            return os.environ.get("ACTOR_FLAG")

    a = A.remote()
    assert rt.get(a.read.remote(), timeout=60) == "on"


def test_py_modules_shipped(local_cluster, tmp_path):
    pkg = tmp_path / "shipped_pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("MAGIC = 1234\n")
    (pkg / "helper.py").write_text(textwrap.dedent("""
        def triple(x):
            return 3 * x
    """))

    @rt.remote(runtime_env={"py_modules": [str(pkg)]})
    def use_module():
        import shipped_pkg
        from shipped_pkg.helper import triple

        return shipped_pkg.MAGIC, triple(7)

    assert rt.get(use_module.remote(), timeout=60) == (1234, 21)


def test_working_dir_shipped(local_cluster, tmp_path):
    wd = tmp_path / "wdir"
    wd.mkdir()
    (wd / "data.txt").write_text("payload!")

    @rt.remote(runtime_env={"working_dir": str(wd)})
    def read_file():
        with open("data.txt") as f:
            return f.read()

    assert rt.get(read_file.remote(), timeout=60) == "payload!"


def test_unsupported_key_raises(local_cluster):
    @rt.remote(runtime_env={"container": {"image": "x"}})
    def f():
        return 1

    with pytest.raises(ValueError, match="unsupported runtime_env"):
        f.remote()


def test_bad_env_vars_type_raises(local_cluster):
    @rt.remote(runtime_env={"env_vars": {"A": 1}})
    def f():
        return 1

    with pytest.raises(TypeError):
        f.remote()


def _build_wheel(dest_dir, name="testpkg_rayt", version="1.0"):
    """Minimal local wheel so `pip install --no-index` works offline."""
    import base64
    import hashlib
    import zipfile

    dist = f"{name}-{version}.dist-info"
    code = f'VERSION = "{version}"\n'
    metadata = (f"Metadata-Version: 2.1\nName: {name}\n"
                f"Version: {version}\n")
    wheel_meta = ("Wheel-Version: 1.0\nGenerator: rayt-test\n"
                  "Root-Is-Purelib: true\nTag: py3-none-any\n")

    def rec(path, data):
        digest = base64.urlsafe_b64encode(
            hashlib.sha256(data.encode()).digest()).rstrip(b"=").decode()
        return f"{path},sha256={digest},{len(data)}"

    record = "\n".join([
        rec(f"{name}/__init__.py", code),
        rec(f"{dist}/METADATA", metadata),
        rec(f"{dist}/WHEEL", wheel_meta),
        f"{dist}/RECORD,,",
    ]) + "\n"
    path = os.path.join(dest_dir, f"{name}-{version}-py3-none-any.whl")
    with zipfile.ZipFile(path, "w") as zf:
        zf.writestr(f"{name}/__init__.py", code)
        zf.writestr(f"{dist}/METADATA", metadata)
        zf.writestr(f"{dist}/WHEEL", wheel_meta)
        zf.writestr(f"{dist}/RECORD", record)
    return path


def test_pip_env_installs_wheel_visible_only_in_task(local_cluster,
                                                     tmp_path):
    """The pip key builds a cached venv; the package imports inside the
    task and is absent outside (ref: _private/runtime_env/pip.py)."""
    _build_wheel(str(tmp_path))
    renv = {"pip": {"packages": ["testpkg-rayt"],
                    "pip_install_options": [
                        "--no-index", "--find-links", str(tmp_path)]}}

    @rt.remote(runtime_env=renv)
    def use_pkg():
        import testpkg_rayt

        return testpkg_rayt.VERSION

    assert rt.get(use_pkg.remote(), timeout=120) == "1.0"

    # not visible outside the runtime env
    @rt.remote
    def without_env():
        try:
            import testpkg_rayt  # noqa: F401

            return "visible"
        except ImportError:
            return "absent"

    assert rt.get(without_env.remote(), timeout=60) == "absent"

    # second use hits the cached venv (marker exists, still works)
    import time as _t

    t0 = _t.monotonic()
    assert rt.get(use_pkg.remote(), timeout=60) == "1.0"
    assert _t.monotonic() - t0 < 30.0


def test_runtime_env_plugin_api(local_cluster):
    """Custom runtime_env keys via the plugin API (ref:
    _private/runtime_env/plugin.py): driver-side package() ships payloads,
    worker-side materialize() applies them before the task runs."""
    import os

    import ray_tpu as rt
    from ray_tpu._internal.runtime_env import (RuntimeEnvPlugin,
                                               register_runtime_env_plugin)

    class StampPlugin(RuntimeEnvPlugin):
        def package(self, value, kv_put):
            kv_put("stamp_payload", f"packaged:{value}".encode())
            return "stamp_payload"

        def materialize(self, spec_value, kv_get):
            os.environ["STAMPED"] = kv_get(spec_value).decode()

    register_runtime_env_plugin("stamp", StampPlugin())

    @rt.remote(runtime_env={"stamp": "xyz"})
    def read():
        import os

        return os.environ.get("STAMPED")

    assert rt.get(read.remote(), timeout=90) == "packaged:xyz"


# ------------------------------------------------------ conda (r5, ref conda.py)
@pytest.fixture
def stub_conda(tmp_path, monkeypatch):
    """A fake conda binary: `env create -p P -f F` makes a prefix with a
    marker module in site-packages; `run -n NAME python -c ...` prints a
    prepared named-env prefix."""
    import stat
    import sys as _sys

    named_prefix = tmp_path / "named-env"
    ver = f"python{_sys.version_info[0]}.{_sys.version_info[1]}"
    (named_prefix / "lib" / ver / "site-packages").mkdir(parents=True)
    (named_prefix / "lib" / ver / "site-packages"
     / "named_env_marker.py").write_text("WHO = 'named'\n")

    stub = tmp_path / "conda"
    stub.write_text(f"""#!/bin/bash
if [ "$1" = "env" ] && [ "$2" = "create" ]; then
  while [ $# -gt 0 ]; do
    if [ "$1" = "-p" ]; then PREFIX="$2"; fi
    shift
  done
  mkdir -p "$PREFIX/lib/{ver}/site-packages"
  echo "WHO = 'spec'" > "$PREFIX/lib/{ver}/site-packages/spec_env_marker.py"
  exit 0
fi
if [ "$1" = "run" ]; then
  echo "{named_prefix}"
  exit 0
fi
exit 1
""")
    stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("RAYT_CONDA_EXE", str(stub))
    yield


def test_conda_spec_env_builds_and_splices(stub_conda, monkeypatch,
                                           tmp_path):
    import sys as _sys

    from ray_tpu._internal import runtime_env as renv_mod

    monkeypatch.setattr(renv_mod, "_CONDA_ROOT",
                        str(tmp_path / "conda-cache"))
    spec = renv_mod.package(
        {"conda": {"dependencies": ["numpy", {"pip": ["x", "y"]}]}},
        kv_put=lambda *a: None)
    # hash is order-insensitive
    spec2 = renv_mod.package(
        {"conda": {"dependencies": [{"pip": ["y", "x"]}, "numpy"]}},
        kv_put=lambda *a: None)
    assert spec["conda"]["hash"] == spec2["conda"]["hash"]

    saved = list(_sys.path)
    try:
        renv_mod.materialize(spec, kv_get=lambda k: None)
        import named_env_marker  # noqa: F401  (should NOT resolve)
    except ImportError:
        pass
    finally:
        import spec_env_marker

        assert spec_env_marker.WHO == "spec"
        _sys.modules.pop("spec_env_marker", None)
        _sys.path[:] = saved


def test_conda_named_env_splices(stub_conda, monkeypatch):
    import sys as _sys

    from ray_tpu._internal import runtime_env as renv_mod

    spec = renv_mod.package({"conda": "my-named-env"},
                            kv_put=lambda *a: None)
    saved = list(_sys.path)
    try:
        renv_mod.materialize(spec, kv_get=lambda k: None)
        import named_env_marker

        assert named_env_marker.WHO == "named"
    finally:
        _sys.modules.pop("named_env_marker", None)
        _sys.path[:] = saved


def test_conda_requires_binary(monkeypatch):
    from ray_tpu._internal import runtime_env as renv_mod

    monkeypatch.delenv("RAYT_CONDA_EXE", raising=False)
    monkeypatch.setattr("shutil.which", lambda _: None)
    with pytest.raises(RuntimeError, match="conda binary"):
        renv_mod.ensure_conda_env({"name": "whatever"})


def test_conda_and_pip_mutually_exclusive():
    from ray_tpu._internal import runtime_env as renv_mod

    with pytest.raises(ValueError, match="mutually exclusive"):
        renv_mod.validate({"conda": "env", "pip": ["numpy"]})


# ------------------------------------------- container jobs (r5, ref image_uri.py)
def test_job_container_wraps_entrypoint(tmp_path, monkeypatch):
    from ray_tpu.dashboard.head import JobManager

    runtime = tmp_path / "podman"
    runtime.write_text("#!/bin/bash\necho CONTAINER-RAN \"$@\"\n")
    runtime.chmod(0o755)
    monkeypatch.setenv("RAYT_CONTAINER_RUNTIME", str(runtime))

    jm = JobManager("127.0.0.1:0", log_dir=str(tmp_path / "logs"))
    sub = jm.submit("echo hello-from-job",
                    runtime_env={"container": {"image": "my/image:1"}})
    for _ in range(100):
        st = jm.status(sub)
        if st["status"] != "RUNNING":
            break
        import time as _t
        _t.sleep(0.05)
    assert st["status"] == "SUCCEEDED", st
    logs = jm.logs(sub)
    assert "CONTAINER-RAN" in logs
    assert "my/image:1" in logs
    assert "--network=host" in logs
    jm.shutdown()


def test_job_container_requires_runtime(tmp_path, monkeypatch):
    from ray_tpu.dashboard.head import JobManager

    monkeypatch.delenv("RAYT_CONTAINER_RUNTIME", raising=False)
    monkeypatch.setattr("shutil.which", lambda _: None)
    jm = JobManager("127.0.0.1:0", log_dir=str(tmp_path / "logs"))
    with pytest.raises(RuntimeError, match="podman or docker"):
        jm.submit("echo x",
                  runtime_env={"container": {"image": "img"}})
    jm.shutdown()
