"""joblib parallel backend over cluster tasks (ref analog:
python/ray/util/joblib/ — `register_ray()` +
ray_backend.py's RayBackend). Lets scikit-learn-style
`with joblib.parallel_backend("rayt"): ...` fan grid searches out over
the cluster unchanged.
"""

from __future__ import annotations


def register_rayt() -> None:
    """Register the "rayt" joblib backend (call once per process)."""
    from joblib import register_parallel_backend

    register_parallel_backend("rayt", _make_backend())


def _make_backend():
    from joblib._parallel_backends import ThreadingBackend

    class RaytBackend(ThreadingBackend):
        """Batches of joblib work items run as cluster tasks.

        Subclasses ThreadingBackend so joblib's bookkeeping (callbacks,
        batching, nesting) stays local; only apply_async's batch payload
        crosses the cluster. The same shape the reference uses (its
        backend rides the multiprocessing-Pool shim)."""

        supports_timeout = True

        def configure(self, n_jobs=1, parallel=None, **kwargs):
            import ray_tpu as rt

            if not rt.is_initialized():
                rt.init()
            if n_jobs == -1:
                n_jobs = max(1, int(rt.cluster_resources().get("CPU", 1)))
            return super().configure(n_jobs=n_jobs, parallel=parallel,
                                     **kwargs)

        def apply_async(self, func, callback=None):
            import ray_tpu as rt
            from ray_tpu._internal.serialization import ship_code_by_value

            ship_code_by_value(func)
            task = rt.remote(num_cpus=1)(_run_joblib_batch)
            ref = task.remote(func)

            class _FutureLike:
                def get(self, timeout=None):
                    return rt.get(ref, timeout=timeout)

            out = _FutureLike()
            if callback is not None:
                import threading

                def _wait():
                    try:
                        result = rt.get(ref)
                    except Exception:
                        return
                    callback(result)

                threading.Thread(target=_wait, daemon=True).start()
            return out

    return RaytBackend


def _run_joblib_batch(batch):
    """Executes one joblib BatchedCalls payload inside a worker."""
    return batch()
