"""PPO algorithm (ref analogs: rllib/algorithms/ppo/ppo.py:363,
training_step:389; dataflow per SURVEY.md §3.6: EnvRunner actors sample →
GAE → LearnerGroup update → weights broadcast back via the object
store)."""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import cloudpickle
import numpy as np

import ray_tpu as rt
from ray_tpu.rl.actor_manager import FaultTolerantActorManager
from ray_tpu.rl.env import make_vector_env, require_discrete
from ray_tpu.rl.env_runner import EnvRunner
from ray_tpu.rl.impala import _sample_fragment_nbytes, _tree_leaves
from ray_tpu.rl.learner import JaxLearner, PPOLearnerConfig, compute_gae
from ray_tpu.rl.module import MLPModuleConfig


@dataclasses.dataclass
class PPOConfig:
    env: str = "CartPole-v1"
    num_env_runners: int = 2
    num_envs_per_runner: int = 8
    rollout_fragment_length: int = 64
    num_learners: int = 1
    hidden: tuple = (64, 64)
    lr: float = 3e-4
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    num_epochs: int = 4
    minibatch_size: int = 256
    seed: int = 0
    # steady-state sampling plane: compile the runner fleet onto a
    # channel DAG (dag/channel_exec.py) — weights broadcast over the
    # input edge, fragments stream back over output rings; one iteration
    # submits `sample_waves` pipelined ticks (the waves overlap through
    # the rings, so 2 waves cost ~1.2x one wave's wall time and double
    # the on-policy batch per update). False restores per-call sampling.
    use_compiled_dag: bool = True
    sample_waves: int = 2
    # device edges: wave-0's weight broadcast rides a DEVICE input edge
    # (dag/device_channel.py — jax.Array leaves as raw shard bytes,
    # rebuilt on each runner's devices; never a host pickle of the
    # buffers). False restores host framing on the input edges.
    use_device_edges: bool = True

    def learner_config(self) -> PPOLearnerConfig:
        return PPOLearnerConfig(
            lr=self.lr, gamma=self.gamma, gae_lambda=self.gae_lambda,
            clip_eps=self.clip_eps, vf_coeff=self.vf_coeff,
            entropy_coeff=self.entropy_coeff, num_epochs=self.num_epochs,
            minibatch_size=self.minibatch_size)

    def build(self) -> "PPO":
        return PPO(self)


class PPO:
    """Algorithm driver (ref: Algorithm.train()); iteration =
    sample → update → broadcast."""

    def __init__(self, config: PPOConfig):
        from ray_tpu.rl.module import CNNModuleConfig

        self.config = config
        probe = make_vector_env(config.env, 1, config.seed)
        require_discrete(probe, "PPO")
        obs_shape = getattr(probe, "observation_shape", None)
        if obs_shape is not None:
            self.module_cfg = CNNModuleConfig(
                obs_shape=tuple(obs_shape), num_actions=probe.num_actions)
        else:
            self.module_cfg = MLPModuleConfig(
                observation_size=probe.observation_size,
                num_actions=probe.num_actions, hidden=tuple(config.hidden))
        module_blob = cloudpickle.dumps(self.module_cfg)
        learner_blob = cloudpickle.dumps(self.config.learner_config())

        runner_cls = rt.remote(num_cpus=1, max_restarts=-1)(EnvRunner)
        # runner spec retained for DAG recovery's respawn path (a dead
        # runner with no restarts left is replaced from here)
        self._runner_cls = runner_cls
        self._module_blob = module_blob
        self._spawned_runners = config.num_env_runners
        # placement-plane consult: soft co-location of the runner fleet
        # (see rl/actor_manager.gang_placement_options)
        from ray_tpu.rl.actor_manager import gang_placement_options

        gang_opts = gang_placement_options(config.num_env_runners)
        self._runners = FaultTolerantActorManager([
            runner_cls.options(**gang_opts[i]).remote(
                config.env, config.num_envs_per_runner,
                config.seed + i, module_blob)
            for i in range(config.num_env_runners)])

        n_learn = config.num_learners
        group = f"ppo-learners-{id(self):x}" if n_learn > 1 else None
        learner_cls = rt.remote(num_cpus=1)(JaxLearner)
        self._learners = [
            learner_cls.remote(module_blob, learner_blob, config.seed,
                               group, n_learn, rank)
            for rank in range(n_learn)]
        self._iteration = 0
        self._recent_returns: list[float] = []
        self._weights = rt.get(self._learners[0].get_weights.remote(),
                               timeout=120)
        # compiled-DAG sampling plane (see PPOConfig.use_compiled_dag)
        self._dag = None
        if config.use_compiled_dag:
            self._build_dag()

    def _build_dag(self):
        """Recovery-wrapped compiled sampling plane: a dead runner
        mid-wave triggers teardown → restart/respawn → recompile →
        resume (see dag/recovery.py) instead of failing the iteration."""
        from ray_tpu.dag.recovery import RecoverableDag

        self._dag = RecoverableDag(
            self._compile_dag, recover_cb=self._recover_runners,
            name="ppo")

    def _compile_dag(self, epoch: int = 0, recovered_from: str = ""):
        from ray_tpu.dag import InputNode, MultiOutputNode

        cfg = self.config
        runners = self._runners.healthy_actors()
        with InputNode() as inp:
            outs = [r.sample_dag.bind(inp, cfg.rollout_fragment_length)
                    for r in runners]
        node = MultiOutputNode(outs) if len(outs) > 1 else outs[0]
        self._dag_multi = len(outs) > 1
        sample_nbytes = 2 * _sample_fragment_nbytes(
            self.module_cfg, cfg.rollout_fragment_length,
            cfg.num_envs_per_runner) + (1 << 16)
        weights_nbytes = 2 * sum(
            int(np.asarray(w).nbytes) for w in _tree_leaves(self._weights)
        ) + (1 << 16)
        return node.experimental_compile(
            buffer_size_bytes=max(sample_nbytes, weights_nbytes, 1 << 20),
            max_inflight=max(2, cfg.sample_waves + 1),
            device_input=cfg.use_device_edges,
            epoch=epoch, recovered_from=recovered_from)

    def _recover_runners(self, failed: dict):
        """RecoverableDag recover_cb (same policy as IMPALA's): wait for
        GCS restarts, respawn replacements for runners that stay dead,
        and push the driver's current weights so a restarted runner does
        not sample from its init params until wave 0 replays."""
        from ray_tpu._internal.config import get_config
        from ray_tpu.dag.recovery import DagRecoveryError, wait_actor_alive

        cfg = self.config
        by_hex = {a._actor_id.hex(): a for a in self._runners._actors}
        fatal = [h for h in failed if h not in by_hex]
        if fatal:
            raise DagRecoveryError(
                f"non-runner DAG peers died ({fatal}); PPO's sampling "
                "ring only spans env runners")
        timeout = get_config().dag_recovery_restart_timeout_s
        for hexid in failed:
            runner = by_hex[hexid]
            if wait_actor_alive(runner, timeout) != "ALIVE":
                replacement = self._runner_cls.remote(
                    cfg.env, cfg.num_envs_per_runner,
                    cfg.seed + self._spawned_runners, self._module_blob)
                self._spawned_runners += 1
                self._runners.replace(runner, replacement)
        self._runners.probe_unhealthy(timeout=timeout)
        weights_ref = rt.put(self._weights)
        self._runners.foreach(
            lambda a: a.set_weights.remote(weights_ref))

    # ------------------------------------------------------------------ train
    def train(self) -> dict:
        cfg = self.config
        t0 = time.perf_counter()
        if self._dag is not None:
            # compiled-DAG sampling: wave 0 carries this iteration's
            # weights over the input edge; later waves pipeline through
            # the rings with the same weights (still on-policy — no
            # update happens between waves)
            from ray_tpu.util import builtin_metrics as _bm

            w0 = self._weights
            if cfg.use_device_edges:
                # device input edges ship raw shard bytes: mark the
                # HOST weight leaves for the framing directly —
                # device_put-then-pack would round-trip every leaf
                # H2D+D2H on an accelerator-backed driver for nothing
                from ray_tpu.dag.device_channel import wrap_host_arrays

                w0, _ = wrap_host_arrays(w0)
            refs = [self._dag.execute(w0 if k == 0 else None)
                    for k in range(max(1, cfg.sample_waves))]
            # PPO stays on-policy: staleness is bounded by the wave
            # count (all waves sample the weights broadcast on wave 0)
            _bm.rl_dag_staleness.set(len(refs), tags={"algo": "ppo"})
            _bm.rl_dag_weight_broadcasts.inc(tags={"algo": "ppo"})
            samples = []
            for ref in refs:
                vals = ref.get(timeout=600)
                samples.extend(vals if self._dag_multi else [vals])
        else:
            weights_ref = rt.put(self._weights)
            self._runners.foreach(
                lambda a: a.set_weights.remote(weights_ref))
            samples = self._runners.foreach(
                lambda a: a.sample.remote(cfg.rollout_fragment_length))
        if not samples:
            self._runners.probe_unhealthy()
            raise RuntimeError("all env runners unhealthy")
        batch, ep_returns, steps = self._build_batch(samples)
        self._recent_returns.extend(ep_returns)
        self._recent_returns = self._recent_returns[-100:]

        shards = self._split_batch(batch, len(self._learners))
        aux = rt.get([lr.update.remote(s)
                      for lr, s in zip(self._learners, shards)],
                     timeout=600)[0]
        self._weights = rt.get(self._learners[0].get_weights.remote(),
                               timeout=120)
        self._runners.probe_unhealthy()
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "episode_return_mean": (float(np.mean(self._recent_returns))
                                    if self._recent_returns else 0.0),
            "num_env_steps_sampled": steps,
            "time_this_iter_s": time.perf_counter() - t0,
            **{f"learner/{k}": v for k, v in aux.items()},
        }

    def _build_batch(self, samples: list[dict]):
        from ray_tpu.rl.learner import build_ppo_batch

        cfg = self.config
        return build_ppo_batch(samples, cfg.gamma, cfg.gae_lambda)

    @staticmethod
    def _split_batch(batch: dict, n: int) -> list[dict]:
        if n == 1:
            return [batch]
        return [{k: v[i::n] for k, v in batch.items()} for i in range(n)]

    # ------------------------------------------------------- checkpointable
    def save_to_path(self, path: str) -> str:
        import os
        import pickle

        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "algorithm_state.pkl"), "wb") as f:
            pickle.dump({"weights": self._weights,
                         "iteration": self._iteration,
                         "config": self.config}, f)
        return path

    def restore_from_path(self, path: str) -> None:
        import os
        import pickle

        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self._weights = state["weights"]
        self._iteration = state["iteration"]
        rt.get([lr.set_weights.remote(self._weights)
                for lr in self._learners], timeout=120)

    def stop(self):
        if self._dag is not None:
            try:
                self._dag.teardown()
            except Exception:
                pass
            self._dag = None
        for a in self._runners._actors + self._learners:
            try:
                rt.kill(a)
            except Exception:
                pass
