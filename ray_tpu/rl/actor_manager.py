"""FaultTolerantActorManager (ref analog:
rllib/utils/actor_manager.py:198): async RPC fan-out over a fleet with
per-actor health tracking — failed calls mark the actor unhealthy and are
dropped from results; a later successful probe restores it."""

from __future__ import annotations

import logging
from typing import Any, Callable, Optional

import ray_tpu as rt

logger = logging.getLogger("ray_tpu.rl")


def gang_placement_options(n: int, resources: Optional[dict] = None,
                           strategy: str = "SLICE_PACK") -> list[dict]:
    """Best-effort soft co-location of an n-actor fleet through the GCS
    placement plane: asks `place_gang` (advisory — nothing reserved)
    where the gang fits whole, and returns one actor-options dict per
    member carrying a SOFT NodeAffinity to its advised node. When the
    plane can't fit the gang (or isn't reachable), returns empty dicts
    and scheduling falls back to the per-lease local policies — fleets
    must boot even on clusters that can't co-locate them."""
    opts: list[dict] = [{} for _ in range(n)]
    try:
        nodes = rt.place_gang(
            [dict(resources or {"CPU": 1.0}) for _ in range(n)],
            strategy)
    except Exception:
        logger.debug("gang placement advise failed", exc_info=True)
        return opts
    if not nodes or len(nodes) != n:
        return opts
    from ray_tpu._internal.ids import NodeID
    from ray_tpu.core.common import NodeAffinitySchedulingStrategy

    for i, h in enumerate(nodes):
        opts[i] = {"scheduling_strategy": NodeAffinitySchedulingStrategy(
            NodeID(bytes.fromhex(h)), soft=True)}
    return opts


class FaultTolerantActorManager:
    def __init__(self, actors: list, *, probe_method: str = "ping"):
        self._actors = list(actors)
        self._healthy = [True] * len(actors)
        self._probe_method = probe_method

    @property
    def num_healthy(self) -> int:
        return sum(self._healthy)

    def healthy_actors(self) -> list:
        return [a for a, h in zip(self._actors, self._healthy) if h]

    def foreach(self, fn: Callable, *, timeout: float = 120.0,
                healthy_only: bool = True) -> list:
        """fn(actor) -> ObjectRef; returns results from actors that
        succeeded (failures mark the actor unhealthy)."""
        targets = [(i, a) for i, (a, h) in enumerate(
            zip(self._actors, self._healthy)) if h or not healthy_only]
        refs = [(i, fn(a)) for i, a in targets]
        out = []
        for i, ref in refs:
            try:
                out.append(rt.get(ref, timeout=timeout))
                self._healthy[i] = True
            except Exception as e:
                logger.warning("actor %d failed: %r", i, e)
                self._healthy[i] = False
        return out

    def replace(self, old, new) -> None:
        """Swap a permanently-dead actor for a freshly spawned
        replacement (DAG recovery's respawn path); the replacement
        starts healthy and keeps the fleet size stable."""
        for i, a in enumerate(self._actors):
            if a is old:
                self._actors[i] = new
                self._healthy[i] = True
                return
        raise ValueError("actor is not managed by this manager")

    def probe_unhealthy(self, timeout: float = 10.0) -> int:
        """Try to restore unhealthy actors (restarted actors respond
        again); returns how many are healthy now."""
        for i, (a, h) in enumerate(zip(self._actors, self._healthy)):
            if h:
                continue
            try:
                rt.get(getattr(a, self._probe_method).remote(),
                       timeout=timeout)
                self._healthy[i] = True
            except Exception:
                pass
        return self.num_healthy
