"""Deployment descriptors + application graphs (ref analogs:
python/ray/serve/deployment.py:64 `Deployment`, api.py `@serve.deployment`,
handle-based composition).

`@serve.deployment class D: ...` then `D.bind(args)` builds an
Application node; bound nodes passed as init args become
DeploymentHandles inside the replica (model-composition DAG).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional


@dataclasses.dataclass
class AutoscalingConfig:
    """Closed-loop replica autoscaling policy (ref analog:
    serve/config.py AutoscalingConfig + autoscaling_state.py).

    The controller combines three live signals each reconcile tick:
    ongoing requests reported by replicas (+ router queue depth from the
    metrics store) against ``target_ongoing_requests``, per-deployment
    QPS from the metrics store against ``target_qps_per_replica`` (when
    set), and p99 request latency against ``latency_target_s`` (when
    set; adds one replica per decision while violated). The largest
    demand wins, clamped to [min_replicas, max_replicas], then the
    up/down delays apply hysteresis: the desired direction must hold
    continuously for the delay before replicas actually move."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 2.0
    downscale_delay_s: float = 10.0
    # metrics-store-driven signals (None disables the signal)
    target_qps_per_replica: Optional[float] = None
    latency_target_s: Optional[float] = None
    metrics_window_s: float = 30.0


class Deployment:
    def __init__(self, func_or_class: Any, name: str,
                 num_replicas: int | str = 1,
                 ray_actor_options: Optional[dict] = None,
                 autoscaling_config: Optional[AutoscalingConfig | dict] = None,
                 max_ongoing_requests: int = 16,
                 user_config: Any = None,
                 health_check_period_s: float = 10.0,
                 health_check_timeout_s: float = 5.0,
                 health_check_failure_threshold: int = 2,
                 drain_timeout_s: float = 30.0):
        self.func_or_class = func_or_class
        self.name = name
        if isinstance(autoscaling_config, dict):
            autoscaling_config = AutoscalingConfig(**autoscaling_config)
        if num_replicas == "auto":
            autoscaling_config = autoscaling_config or AutoscalingConfig()
            num_replicas = autoscaling_config.min_replicas
        self.num_replicas = int(num_replicas)
        self.ray_actor_options = ray_actor_options or {}
        self.autoscaling_config = autoscaling_config
        self.max_ongoing_requests = max_ongoing_requests
        self.user_config = user_config
        self.health_check_period_s = health_check_period_s
        self.health_check_timeout_s = health_check_timeout_s
        self.health_check_failure_threshold = health_check_failure_threshold
        self.drain_timeout_s = drain_timeout_s

    def options(self, **kwargs) -> "Deployment":
        merged = dict(
            name=self.name, num_replicas=self.num_replicas,
            ray_actor_options=self.ray_actor_options,
            autoscaling_config=self.autoscaling_config,
            max_ongoing_requests=self.max_ongoing_requests,
            user_config=self.user_config,
            health_check_period_s=self.health_check_period_s,
            health_check_timeout_s=self.health_check_timeout_s,
            health_check_failure_threshold=self.health_check_failure_threshold,
            drain_timeout_s=self.drain_timeout_s)
        merged.update(kwargs)
        return Deployment(self.func_or_class, **merged)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def __call__(self, *a, **kw):
        raise TypeError(
            f"Deployment {self.name!r} cannot be called directly; deploy it "
            "with serve.run(D.bind(...)) and call the handle")


class Application:
    """A bound deployment node; init args may reference other bound nodes
    (composition)."""

    def __init__(self, deployment: Deployment, args: tuple, kwargs: dict):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs

    def walk(self) -> list["Application"]:
        """All nodes reachable from this one (dependencies first)."""
        seen: dict[int, Application] = {}

        def visit(node: "Application"):
            if id(node) in seen:
                return
            for a in list(node.args) + list(node.kwargs.values()):
                if isinstance(a, Application):
                    visit(a)
            seen[id(node)] = node

        visit(self)
        return list(seen.values())


def deployment(func_or_class: Any = None, *, name: Optional[str] = None,
               num_replicas: int | str = 1,
               ray_actor_options: Optional[dict] = None,
               autoscaling_config: Optional[AutoscalingConfig | dict] = None,
               max_ongoing_requests: int = 16,
               user_config: Any = None,
               health_check_period_s: float = 10.0,
               health_check_timeout_s: float = 5.0,
               health_check_failure_threshold: int = 2,
               drain_timeout_s: float = 30.0):
    """@serve.deployment decorator (ref: serve/api.py)."""

    def wrap(target):
        return Deployment(
            target, name or target.__name__, num_replicas,
            ray_actor_options, autoscaling_config, max_ongoing_requests,
            user_config, health_check_period_s, health_check_timeout_s,
            health_check_failure_threshold, drain_timeout_s)

    if func_or_class is not None:
        return wrap(func_or_class)
    return wrap
