"""Model zoo: TPU-first implementations (the reference delegates models to
torch; here the model layer is co-designed with sharding, see
models/llama.py docstring)."""

from ray_tpu.models import llama  # noqa: F401
from ray_tpu.models.mlp import MLPConfig, mlp_forward, mlp_init, mlp_loss  # noqa: F401
