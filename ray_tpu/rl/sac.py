"""SAC — soft actor-critic for continuous control (ref analogs:
rllib/algorithms/sac/sac.py + sac_learner.py: twin Q critics, squashed
Gaussian actor, automatic entropy-temperature tuning; the learner math is
an independent jitted JAX implementation, Haarnoja et al. 2018).

Dataflow mirrors DQN's off-policy loop: SACRunner actors step continuous
envs sampling from the tanh-Gaussian policy -> transitions into a
ReplayBuffer actor -> driver samples minibatches -> one jitted update
does critic + actor + alpha steps and the polyak target move -> weights
broadcast back to runners.
"""

from __future__ import annotations

import dataclasses
import time

import cloudpickle
import numpy as np

import ray_tpu as rt
from ray_tpu.rl.actor_manager import FaultTolerantActorManager
from ray_tpu.rl.env import make_vector_env
from ray_tpu.rl.module import ContinuousModuleConfig
from ray_tpu.rl.replay import ReplayBuffer, ReplayRolloutMixin


class SACRunner(ReplayRolloutMixin):
    """Rollout actor sampling from the squashed-Gaussian policy."""

    def __init__(self, env_name: str, num_envs: int, seed: int,
                 module_cfg_blob: bytes):
        from ray_tpu._internal.spawn import wait_site_ready

        wait_site_ready()
        import jax

        jax.config.update("jax_platforms", "cpu")
        self.env = make_vector_env(env_name, num_envs, seed)
        self.module_cfg = cloudpickle.loads(module_cfg_blob)
        self._key = jax.random.PRNGKey(seed)
        self._obs = self.env.reset(seed)
        self._actor = None
        self._ep_return = np.zeros(num_envs, np.float32)
        self._completed: list[float] = []

    def set_weights(self, actor_params) -> bool:
        self._actor = actor_params
        return True

    def sample(self, num_steps: int, random_actions: bool = False) -> dict:
        """[T*N] flat transition arrays + completed episode returns.

        random_actions drives uniform exploration before learning starts
        (the reference's `num_steps_sampled_before_learning_starts`)."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.rl import module as rlm

        cfg = self.module_cfg
        N, A, high = self.env.num_envs, cfg.action_size, cfg.action_high

        def select(obs):
            self._key, k = jax.random.split(self._key)
            if random_actions or self._actor is None:
                return np.asarray(jax.random.uniform(
                    k, (N, A), minval=-high, maxval=high), np.float32)
            mean, log_std = rlm.actor_forward(self._actor, jnp.asarray(obs))
            a, _ = rlm.sample_squashed(mean, log_std, k, high)
            return np.asarray(a, np.float32)

        return self._rollout(num_steps, select)

    def ping(self) -> bool:
        return True


@dataclasses.dataclass
class SACConfig:
    env: str = "Pendulum-v1"
    num_env_runners: int = 2
    num_envs_per_runner: int = 8
    rollout_fragment_length: int = 32
    hidden: tuple = (64, 64)
    actor_lr: float = 3e-4
    critic_lr: float = 3e-4
    alpha_lr: float = 3e-4
    gamma: float = 0.99
    tau: float = 0.005                  # polyak rate for target critics
    initial_alpha: float = 1.0
    target_entropy: float | None = None  # default: -action_size
    buffer_capacity: int = 100_000
    learning_starts: int = 1_000
    train_batch_size: int = 128
    updates_per_iteration: int = 16
    seed: int = 0

    def build(self) -> "SAC":
        return SAC(self)


class SAC:
    def __init__(self, config: SACConfig):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rl import module as rlm

        self.config = config
        probe = make_vector_env(config.env, 1, config.seed)
        if not probe.continuous:
            raise ValueError(
                f"SAC needs a continuous-action env; {config.env!r} is "
                "discrete (use DQN/PPO, or give the env `continuous=True` "
                "with `action_size`/`action_high`)")
        self.module_cfg = ContinuousModuleConfig(
            observation_size=probe.observation_size,
            action_size=probe.action_size,
            action_high=float(probe.action_high), hidden=config.hidden)
        self.params = rlm.init_continuous_params(
            self.module_cfg, jax.random.PRNGKey(config.seed))
        self.target_q = jax.tree.map(
            lambda x: x, {"q1": self.params["q1"], "q2": self.params["q2"]})
        self.log_alpha = jnp.asarray(
            np.log(config.initial_alpha), jnp.float32)
        target_entropy = (config.target_entropy
                          if config.target_entropy is not None
                          else -float(self.module_cfg.action_size))

        self._actor_opt = optax.adam(config.actor_lr)
        self._critic_opt = optax.adam(config.critic_lr)
        self._alpha_opt = optax.adam(config.alpha_lr)
        self._opt_state = {
            "actor": self._actor_opt.init(self.params["actor"]),
            "critic": self._critic_opt.init(
                {"q1": self.params["q1"], "q2": self.params["q2"]}),
            "alpha": self._alpha_opt.init(self.log_alpha),
        }
        gamma, tau = config.gamma, config.tau
        high = self.module_cfg.action_high

        def critic_loss(q_params, params, target_q, log_alpha, batch, key):
            mean, log_std = rlm.actor_forward(params["actor"],
                                              batch["next_obs"])
            next_a, next_logp = rlm.sample_squashed(mean, log_std, key, high)
            tq1 = rlm.q_forward(target_q["q1"], batch["next_obs"], next_a)
            tq2 = rlm.q_forward(target_q["q2"], batch["next_obs"], next_a)
            alpha = jnp.exp(log_alpha)
            soft_q = jnp.minimum(tq1, tq2) - alpha * next_logp
            target = batch["rewards"] + gamma * soft_q * (
                1.0 - batch["dones"].astype(jnp.float32))
            target = jax.lax.stop_gradient(target)
            q1 = rlm.q_forward(q_params["q1"], batch["obs"], batch["actions"])
            q2 = rlm.q_forward(q_params["q2"], batch["obs"], batch["actions"])
            return (((q1 - target) ** 2).mean()
                    + ((q2 - target) ** 2).mean())

        def actor_loss(actor_params, params, log_alpha, batch, key):
            mean, log_std = rlm.actor_forward(actor_params, batch["obs"])
            a, logp = rlm.sample_squashed(mean, log_std, key, high)
            q1 = rlm.q_forward(params["q1"], batch["obs"], a)
            q2 = rlm.q_forward(params["q2"], batch["obs"], a)
            alpha = jax.lax.stop_gradient(jnp.exp(log_alpha))
            return (alpha * logp - jnp.minimum(q1, q2)).mean(), logp

        def update(params, target_q, log_alpha, opt_state, batch, key):
            kc, ka = jax.random.split(key)
            q_params = {"q1": params["q1"], "q2": params["q2"]}
            closs, cgrads = jax.value_and_grad(critic_loss)(
                q_params, params, target_q, log_alpha, batch, kc)
            cupd, opt_c = self._critic_opt.update(
                cgrads, opt_state["critic"], q_params)
            q_params = optax.apply_updates(q_params, cupd)
            params = {**params, **q_params}

            (aloss, logp), agrads = jax.value_and_grad(
                actor_loss, has_aux=True)(
                params["actor"], params, log_alpha, batch, ka)
            aupd, opt_a = self._actor_opt.update(
                agrads, opt_state["actor"], params["actor"])
            params = {**params,
                      "actor": optax.apply_updates(params["actor"], aupd)}

            # alpha step: loss(log_alpha) = E[-log_alpha*(logp + H_target)]
            # so grad = -(logp + H_target).mean(); entropy below target
            # (logp + H_target > 0) pushes log_alpha UP -> more exploration
            entropy_gap = jax.lax.stop_gradient(logp) + target_entropy
            alpha_grad = -entropy_gap.mean()
            alupd, opt_al = self._alpha_opt.update(
                alpha_grad, opt_state["alpha"], log_alpha)
            log_alpha = optax.apply_updates(log_alpha, alupd)

            target_q = jax.tree.map(
                lambda t, s: (1.0 - tau) * t + tau * s, target_q, q_params)
            opt_state = {"actor": opt_a, "critic": opt_c, "alpha": opt_al}
            stats = {"critic_loss": closs, "actor_loss": aloss,
                     "alpha": jnp.exp(log_alpha), "entropy": -logp.mean()}
            return params, target_q, log_alpha, opt_state, stats

        self._update = jax.jit(update)
        self._key = jax.random.PRNGKey(config.seed + 1)

        blob = cloudpickle.dumps(self.module_cfg)
        runner_cls = rt.remote(num_cpus=1)(SACRunner)
        self._runners = FaultTolerantActorManager([
            runner_cls.remote(config.env, config.num_envs_per_runner,
                              config.seed + 1 + i, blob)
            for i in range(config.num_env_runners)])
        self._buffer = rt.remote(num_cpus=0)(ReplayBuffer).remote(
            config.buffer_capacity, config.seed)
        self._broadcast_weights()
        self._iteration = 0
        self._env_steps = 0
        self._updates = 0
        self._last_returns: list[float] = []

    # ------------------------------------------------------------------ api
    def _broadcast_weights(self):
        ref = rt.put(self.params["actor"])
        self._runners.foreach(lambda a: a.set_weights.remote(ref))

    def train(self) -> dict:
        import jax
        import jax.numpy as jnp

        c = self.config
        t0 = time.monotonic()
        warmup = self._env_steps < c.learning_starts
        samples = self._runners.foreach(
            lambda a: a.sample.remote(c.rollout_fragment_length, warmup))
        returns = []
        for s in samples:
            self._env_steps += s["steps"]
            returns.extend(s["episode_returns"])
            rt.get(self._buffer.add.remote(s["transitions"]), timeout=60)
        stats = None
        if self._env_steps >= c.learning_starts:
            for _ in range(c.updates_per_iteration):
                batch = rt.get(
                    self._buffer.sample.remote(c.train_batch_size),
                    timeout=60)
                if batch is None:
                    break
                batch = {k: jnp.asarray(v) for k, v in batch.items()}
                self._key, k = jax.random.split(self._key)
                (self.params, self.target_q, self.log_alpha,
                 self._opt_state, stats) = self._update(
                    self.params, self.target_q, self.log_alpha,
                    self._opt_state, batch, k)
                self._updates += 1
            self._broadcast_weights()
        self._iteration += 1
        self._last_returns = (self._last_returns + returns)[-100:]
        mean_ret = (float(np.mean(self._last_returns))
                    if self._last_returns else None)
        out = {
            "training_iteration": self._iteration,
            "env_steps": self._env_steps,
            "num_updates": self._updates,
            "episode_return_mean": mean_ret,
            "time_s": time.monotonic() - t0,
        }
        if stats is not None:
            out.update({k: float(v) for k, v in stats.items()})
        return out

    def policy_mean(self, obs: np.ndarray) -> np.ndarray:
        """Deterministic (mean) action for evaluation."""
        import jax.numpy as jnp

        from ray_tpu.rl import module as rlm

        mean, _ = rlm.actor_forward(self.params["actor"], jnp.asarray(obs))
        return np.asarray(jnp.tanh(mean) * self.module_cfg.action_high)

    def stop(self):
        for a in [self._buffer] + list(self._runners._actors):
            try:
                rt.kill(a)
            except Exception:
                pass
