"""HTTP ingress proxy (ref analog: python/ray/serve/_private/proxy.py:1135
— uvicorn in the reference; aiohttp here).

Routes: POST/GET /<app_name> (body JSON becomes the request payload) →
app ingress handle → JSON response. Runs as an async actor; blocking
ObjectRef gets ride a DEDICATED thread executor (sized by
``RAYT_SERVE_PROXY_THREADS``) so the event loop keeps accepting — and
shedding — connections even when every worker thread is parked on a
result.

Admission control (see serve/admission.py): each request first passes
the per-app admission window sized from the routing table (replicas x
max_ongoing_requests x headroom). The capacity read is CACHED (~1s) and
refreshed off the request path on a small auxiliary executor, so the
accept/shed decision itself never needs a thread from the (possibly
saturated) request executor: shed requests answer 503 + ``Retry-After``
straight from the event loop — no executor thread, no replica traffic —
keeping a flat, fast rejection path under exactly the overload the
window exists for. Status mapping: 503 for overload/backpressure/
timeout (reasons ``shed`` / ``queue_full`` / ``timeout`` /
``no_replicas`` in the JSON body and the X-Rayt-Reason header), 500
ONLY for an exception raised by the replica's user code. Streaming
requests route BEFORE the SSE response is prepared, so an overloaded
stream sheds with a real 503 too (mid-stream failures degrade to an
``event: error`` frame — the 200 is already on the wire).
"""

from __future__ import annotations

import asyncio
import json
import os
import time
from typing import Any

from ray_tpu.serve.admission import (AdmissionWindow, count_admitted,
                                     count_shed, is_overload_error,
                                     request_timeout_s, retry_after_s)

PROXY_THREADS_ENV = "RAYT_SERVE_PROXY_THREADS"

# routing-table capacity cache TTL: admission windows follow replica
# scaling within this bound without an RPC per request
CAPACITY_TTL_S = 1.0


class ProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000,
                 request_timeout_s: float | None = None,
                 admission_headroom: float | None = None):
        self.host = host
        self.port = port
        self._handles: dict[str, Any] = {}
        self._ingress: dict[str, str] = {}
        self._runner = None
        self._executor = None       # admitted-request result waits
        self._aux_executor = None   # capacity refreshes (never starved
        # by admitted requests parking on results)
        self._timeout_override = request_timeout_s
        self._admission = AdmissionWindow(admission_headroom)
        self._capacity: dict[str, tuple[int, int, float]] = {}
        self._cap_refreshing: set[str] = set()

    async def start(self) -> int:
        from concurrent.futures import ThreadPoolExecutor

        from aiohttp import web

        self._executor = ThreadPoolExecutor(
            max_workers=int(os.environ.get(PROXY_THREADS_ENV, "128")),
            thread_name_prefix="serve-proxy")
        self._aux_executor = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="serve-proxy-cap")
        app = web.Application()
        app.router.add_route("*", "/-/routes", self._routes_endpoint)
        app.router.add_route("*", "/-/healthz", self._healthz)
        app.router.add_route("*", "/-/admission", self._admission_endpoint)
        app.router.add_route("*", "/{app_name}", self._dispatch)
        app.router.add_route("*", "/{app_name}/{tail:.*}", self._dispatch)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in site._server.sockets:
            self.port = s.getsockname()[1]
            break
        return self.port

    def register_app(self, app_name: str, ingress_deployment: str) -> bool:
        self._ingress[app_name] = ingress_deployment
        self._handles.pop(app_name, None)
        self._capacity.pop(app_name, None)
        return True

    def unregister_app(self, app_name: str) -> bool:
        self._ingress.pop(app_name, None)
        self._handles.pop(app_name, None)
        self._capacity.pop(app_name, None)
        return True

    async def _healthz(self, request):
        from aiohttp import web

        return web.Response(text="ok")

    async def _routes_endpoint(self, request):
        from aiohttp import web

        return web.json_response(dict(self._ingress))

    async def _admission_endpoint(self, request):
        from aiohttp import web

        return web.json_response(self._admission.snapshot())

    def _request_timeout(self) -> float:
        if self._timeout_override is not None:
            return float(self._timeout_override)
        return request_timeout_s()

    def _unavailable(self, app_name: str, reason: str, detail: str):
        """503 + Retry-After: overload/backpressure/timeout semantics —
        the client should back off and retry, nothing is broken."""
        from aiohttp import web

        retry = retry_after_s()
        count_shed(app_name, "http", reason)
        return web.json_response(
            {"error": detail, "reason": reason, "retry_after_s": retry},
            status=503,
            headers={"Retry-After": str(retry),
                     "X-Rayt-Reason": reason})

    async def _app_capacity(self, app_name: str, handle,
                            loop) -> tuple[int, int]:
        """(replicas, max_ongoing) from the ~1s cache. Only the COLD
        read (first request for an app) waits on an RPC — and on the
        aux executor, not the request executor, so a saturated proxy
        still sheds instantly. Stale entries refresh in the background
        while the current value keeps serving decisions."""
        cap = self._capacity.get(app_name)
        now = time.monotonic()
        if cap is None:
            try:
                replicas, max_ongoing = await loop.run_in_executor(
                    self._aux_executor, handle.capacity)
            except Exception:
                replicas, max_ongoing = 1, 16  # table warming up
            self._capacity[app_name] = (replicas, max_ongoing,
                                        time.monotonic())
            return replicas, max_ongoing
        replicas, max_ongoing, ts = cap
        if now - ts > CAPACITY_TTL_S and \
                app_name not in self._cap_refreshing:
            self._cap_refreshing.add(app_name)

            def _refresh():
                try:
                    r, m = handle.capacity()
                    self._capacity[app_name] = (r, m, time.monotonic())
                except Exception:
                    self._capacity[app_name] = (replicas, max_ongoing,
                                                time.monotonic())
                finally:
                    self._cap_refreshing.discard(app_name)

            self._aux_executor.submit(_refresh)
        return replicas, max_ongoing

    async def _dispatch(self, request):
        from aiohttp import web

        app_name = request.match_info["app_name"]
        ingress = self._ingress.get(app_name)
        if ingress is None:
            return web.json_response(
                {"error": f"no app {app_name!r}"}, status=404)
        handle = self._handles.get(app_name)
        if handle is None:
            from ray_tpu.serve.handle import DeploymentHandle

            handle = DeploymentHandle(ingress, app_name)
            self._handles[app_name] = handle
        if request.can_read_body:
            try:
                payload = await request.json()
            except json.JSONDecodeError:
                payload = (await request.read()).decode()
        else:
            payload = dict(request.query)
        # streaming: ?stream=1 or Accept: text/event-stream gets an SSE
        # response fed by the replica's generator (ref: serve response
        # streaming through the proxy)
        wants_stream = (request.query.get("stream") == "1"
                        or "text/event-stream" in
                        request.headers.get("Accept", ""))
        loop = asyncio.get_running_loop()
        # ---- admission: window sized from the (cached) routing-table
        # capacity; accept/shed is sync + fast on the event loop
        replicas, max_ongoing = await self._app_capacity(app_name, handle,
                                                         loop)
        if not self._admission.try_acquire(app_name, replicas, max_ongoing):
            return self._unavailable(
                app_name, "shed",
                f"admission window full for app {app_name!r} (window="
                f"{self._admission.window_for(replicas, max_ongoing)})")
        count_admitted(app_name, "http")
        # model multiplexing (ref: serve proxy forwards the model-id
        # header); the router's capacity-gate park is bounded by the
        # request timeout — a request that can't find a replica slot in
        # time is SHED (503 queue_full), never left queueing to timeout
        from ray_tpu.serve.admission import queue_timeout_s

        model_id = request.headers.get("serve_multiplexed_model_id", "")
        handle = handle.options(
            multiplexed_model_id=model_id or None,
            queue_timeout_s=min(queue_timeout_s(),
                                self._request_timeout()))
        try:
            if wants_stream:
                return await self._dispatch_stream(request, handle,
                                                   app_name, payload)
            return await self._dispatch_unary(handle, app_name, payload,
                                              loop)
        finally:
            self._admission.release(app_name)

    def _error_response(self, app_name: str, e: Exception):
        """Map a routing/replica failure onto the 503/500 split."""
        from aiohttp import web
        from ray_tpu.core.common import GetTimeoutError

        if isinstance(e, GetTimeoutError):
            return self._unavailable(
                app_name, "timeout",
                f"request exceeded {self._request_timeout():.0f}s "
                "(RAYT_SERVE_REQUEST_TIMEOUT_S)")
        if is_overload_error(e):
            return self._unavailable(app_name, "queue_full", repr(e))
        if isinstance(e, RuntimeError) and "no replicas" in str(e):
            return self._unavailable(app_name, "no_replicas", repr(e))
        # a replica-raised user exception: a real 500
        return web.json_response({"error": repr(e)}, status=500)

    async def _dispatch_unary(self, handle, app_name, payload, loop):
        from aiohttp import web

        timeout = self._request_timeout()
        try:
            response = await loop.run_in_executor(
                self._executor,
                lambda: handle.remote(payload).result(timeout=timeout))
        except Exception as e:
            return self._error_response(app_name, e)
        if isinstance(response, (dict, list, str, int, float, bool,
                                 type(None))):
            return web.json_response({"result": response})
        return web.Response(body=str(response).encode())

    async def _dispatch_stream(self, request, handle, app_name, payload):
        from aiohttp import web

        loop = asyncio.get_running_loop()
        if isinstance(payload, dict):
            payload.pop("stream", None)
        # route BEFORE preparing the SSE response: an overloaded /
        # replica-less stream must shed with a real 503, not a 200
        # carrying an error frame
        try:
            gen = await loop.run_in_executor(
                self._executor,
                lambda: handle.options(stream=True).remote(payload))
        except Exception as e:
            return self._error_response(app_name, e)
        resp = web.StreamResponse(
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache"})
        await resp.prepare(request)
        try:
            async for item in gen:
                await resp.write(
                    f"data: {json.dumps(item, default=str)}\n\n".encode())
        except (ConnectionResetError, ConnectionError):
            pass  # client went away; gen.close() stops the replica
        except Exception as e:
            # mid-stream failure: the 200 is already on the wire — an
            # error frame is the only channel left
            try:
                await resp.write(
                    f"event: error\ndata: "
                    f"{json.dumps(repr(e))}\n\n".encode())
            except Exception:
                pass
        finally:
            gen.close()
        try:
            await resp.write_eof()
        except Exception:
            pass
        return resp
