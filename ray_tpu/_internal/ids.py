"""Unique identifiers for jobs, tasks, actors, objects, nodes, workers.

Mirrors the semantics of the reference's ID scheme (ref: src/ray/common/id.h
and src/ray/design_docs/id_specification.md) with a simplified, uniform
layout: every ID is raw bytes with a typed wrapper. ObjectIDs embed the
TaskID that produced them plus a return-index, so ownership and lineage can
be derived from the ID itself.

Layout (bytes):
  JobID    = 4 random bytes
  ActorID  = 8 random bytes  + JobID            (12)
  TaskID   = 8 random bytes  + ActorID-or-zeros (20)
  ObjectID = TaskID + 4-byte big-endian index   (24)
  NodeID   = 16 random bytes
  WorkerID = 16 random bytes
  PlacementGroupID = 12 random bytes
"""

from __future__ import annotations

import os
import random

# ID randomness: unique, NOT cryptographic (matches the reference — ids
# only need collision-resistance). os.urandom is a getrandom(2) syscall
# per id, which dominated TaskID minting on the submit hot path
# (~90us/id on the CI host); a process-local PRNG seeded from urandom
# keeps 64-bit+ uniqueness at ~1us/id. Fork-safety: reseed on first use
# in a child (getpid check) so forked workers never replay the parent's
# stream and collide with its ids.
_rng: random.Random | None = None
_rng_pid = 0


def _rand_bytes(n: int) -> bytes:
    global _rng, _rng_pid
    pid = os.getpid()
    if _rng is None or _rng_pid != pid:
        _rng = random.Random(os.urandom(16) + pid.to_bytes(4, "little"))
        _rng_pid = pid
    return _rng.randbytes(n)


JOB_ID_LEN = 4
ACTOR_ID_LEN = 12
TASK_ID_LEN = 20
OBJECT_ID_LEN = 24
NODE_ID_LEN = 16
WORKER_ID_LEN = 16
PLACEMENT_GROUP_ID_LEN = 12


class BaseID:
    LEN = 16
    __slots__ = ("_bytes", "_hex", "_h")

    def __init__(self, b: bytes):
        if not isinstance(b, bytes) or len(b) != self.LEN:
            raise ValueError(
                f"{type(self).__name__} requires {self.LEN} bytes, got {b!r}")
        self._bytes = b

    @classmethod
    def random(cls) -> "BaseID":
        return cls(_rand_bytes(cls.LEN))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(b"\x00" * cls.LEN)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.LEN

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        # cached: ids are hex()'d on every lifecycle event / metrics tag
        # of every task — a lazy slot beats re-encoding each time
        try:
            return self._hex
        except AttributeError:
            h = self._hex = self._bytes.hex()
            return h

    @classmethod
    def from_hex(cls, h: str) -> "BaseID":
        return cls(bytes.fromhex(h))

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other._bytes == self._bytes

    def __hash__(self) -> int:
        # cached: ids key every hot dict (pending tasks, object meta,
        # reference counts); the tuple build per lookup adds up
        try:
            return self._h
        except AttributeError:
            h = self._h = hash((type(self).__name__, self._bytes))
            return h

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self._bytes.hex()[:16]})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    LEN = JOB_ID_LEN


class NodeID(BaseID):
    LEN = NODE_ID_LEN


class WorkerID(BaseID):
    LEN = WORKER_ID_LEN


class PlacementGroupID(BaseID):
    LEN = PLACEMENT_GROUP_ID_LEN


class ActorID(BaseID):
    LEN = ACTOR_ID_LEN

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(_rand_bytes(8) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[8:])


class TaskID(BaseID):
    LEN = TASK_ID_LEN

    @classmethod
    def for_normal_task(cls, job_id: JobID) -> "TaskID":
        return cls(_rand_bytes(8) + b"\x00" * 8 + job_id.binary())

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(_rand_bytes(8) + actor_id.binary())

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[8:])

    def has_actor(self) -> bool:
        return self._bytes[8:16] != b"\x00" * 8

    def job_id(self) -> JobID:
        return JobID(self._bytes[16:])


class ObjectID(BaseID):
    LEN = OBJECT_ID_LEN

    @classmethod
    def for_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + index.to_bytes(4, "big"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        # Puts use the high bit of the index to avoid colliding with returns.
        return cls(task_id.binary() + (0x80000000 | put_index).to_bytes(4, "big"))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:TASK_ID_LEN])

    def index(self) -> int:
        return int.from_bytes(self._bytes[TASK_ID_LEN:], "big")

    def job_id(self) -> JobID:
        return self.task_id().job_id()
