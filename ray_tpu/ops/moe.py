"""Mixture-of-Experts layer: top-k router + capacity-bounded dispatch +
grouped expert FFN (GShard/Switch formulation).

The reference framework has NO expert parallelism (SURVEY.md §2.4 —
verified absent); this is TPU-native core-op territory. Design follows
the GShard/Mesh-TF einsum recipe rather than a scatter/gather kernel:

* routing produces a dispatch one-hot [tokens, E, C] and combine weights;
* expert inputs form via one einsum, the expert FFN is a single grouped
  matmul ("ecd,edh->ech") over a leading expert dim, outputs combine via
  another einsum;
* under GSPMD the expert dim carries the `expert` mesh axis, so XLA
  lowers the dispatch/combine einsums to all_to_all over ICI and the
  grouped matmul to per-device expert shards — no hand-written
  collectives, static shapes throughout (capacity bounds make it
  jit-compatible; overflow tokens are dropped, the standard trade).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    # aux load-balancing loss weight (Switch Transformer eq. 4)
    aux_loss_weight: float = 0.01


def init_moe_params(key: jax.Array, dim: int, hidden_dim: int,
                    cfg: MoEConfig, dtype=jnp.float32) -> dict:
    """Router + per-expert SwiGLU FFN weights (stacked on a leading
    expert axis, the EP analog of the stacked-layers scan trick)."""
    import math

    k1, k2, k3, k4, k5 = jax.random.split(key, 5)
    e, d, h = cfg.num_experts, dim, hidden_dim

    def dense(rng, shape, fan_in):
        return (jax.random.normal(rng, shape, jnp.float32)
                / math.sqrt(fan_in)).astype(dtype)

    return {
        "router": dense(k1, (d, e), d),
        "w_gate": dense(k2, (e, d, h), d),
        "w_up": dense(k3, (e, d, h), d),
        "w_down": dense(k4, (e, h, d), h),
    }


def moe_logical_axes() -> dict:
    return {
        "router": ("embed", "expert_logits"),
        "w_gate": ("expert", "embed", "mlp"),
        "w_up": ("expert", "embed", "mlp"),
        "w_down": ("expert", "mlp", "embed"),
    }


def _route(router_logits: jax.Array, cfg: MoEConfig, capacity: int):
    """router_logits [T, E] -> (dispatch [T, E, C] bool-ish f32,
    combine [T, E, C] f32, aux_loss scalar).

    Top-k routing with per-expert capacity: the c-th token routed to an
    expert takes slot c; tokens beyond capacity are dropped (their
    combine weight is 0 and the residual path carries them).
    """
    T, E = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)

    # top-k expert choices per token
    top_probs, top_idx = jax.lax.top_k(probs, cfg.top_k)     # [T, k]
    # renormalize chosen gates so they sum to 1 (Mixtral convention)
    top_probs = top_probs / jnp.maximum(
        top_probs.sum(-1, keepdims=True), 1e-9)

    # aux load-balancing loss: mean prob per expert x fraction routed
    onehot_topk = jax.nn.one_hot(top_idx, E, dtype=jnp.float32)  # [T,k,E]
    routed_frac = onehot_topk.sum(axis=(0, 1)) / (T * cfg.top_k)
    mean_prob = probs.mean(axis=0)
    aux_loss = E * jnp.sum(routed_frac * mean_prob)

    dispatch = jnp.zeros((T, E, capacity), jnp.float32)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    # position of each (token, choice) within its expert's queue:
    # cumulative count of earlier assignments to the same expert
    for k in range(cfg.top_k):
        onehot = onehot_topk[:, k, :]                          # [T, E]
        if k == 0:
            prior = jnp.zeros((T, E), jnp.float32)
        else:
            prior = onehot_topk[:, :k, :].sum(axis=1)
        # earlier tokens' assignments (all k slots) + this token's
        # earlier-k assignments
        pos_within = (jnp.cumsum(onehot_topk.sum(axis=1), axis=0)
                      - onehot_topk.sum(axis=1)) + prior       # [T, E]
        slot = (pos_within * onehot).sum(-1).astype(jnp.int32)  # [T]
        keep = (pos_within * onehot).sum(-1) < capacity
        slot_oh = jax.nn.one_hot(jnp.where(keep, slot, capacity),
                                 capacity + 1,
                                 dtype=jnp.float32)[:, :capacity]  # [T, C]
        d_k = onehot[:, :, None] * slot_oh[:, None, :]          # [T, E, C]
        dispatch = dispatch + d_k
        combine = combine + d_k * top_probs[:, k][:, None, None]
    return dispatch, combine, aux_loss


def moe_ffn(params: dict, x: jax.Array, cfg: MoEConfig,
            activation=jax.nn.silu) -> tuple[jax.Array, jax.Array]:
    """x: [b, s, d] -> (out [b, s, d], aux_loss scalar).

    Static-shape capacity dispatch; the grouped matmuls keep a leading
    [E] dim that GSPMD shards over the `expert` mesh axis. Routing is
    per batch row ("group" in GShard terms) so the one-hot dispatch
    tensor is [b, s, E, C] with C ~ s/E — bounded, not O((b*s)^2/E).
    """
    b, s, d = x.shape
    E = cfg.num_experts
    capacity = max(1, int(cfg.capacity_factor * cfg.top_k * s / E))
    router_logits = jnp.einsum(
        "gsd,de->gse", x.astype(jnp.float32),
        params["router"].astype(jnp.float32))
    dispatch, combine, aux_loss = jax.vmap(
        lambda lg: _route(lg, cfg, capacity))(router_logits)
    aux_loss = aux_loss.mean()

    dt = x.dtype
    # dispatch: [g, s, E, C] x [g, s, d] -> expert inputs [E, g, C, d]
    expert_in = jnp.einsum("gsec,gsd->egcd", dispatch.astype(dt), x)
    # grouped SwiGLU FFN over the leading expert dim
    gate = activation(jnp.einsum(
        "egcd,edh->egch", expert_in, params["w_gate"].astype(dt)))
    up = jnp.einsum("egcd,edh->egch", expert_in, params["w_up"].astype(dt))
    expert_out = jnp.einsum(
        "egch,ehd->egcd", gate * up, params["w_down"].astype(dt))
    # combine: [g, s, E, C] x [E, g, C, d] -> [g, s, d]
    out = jnp.einsum("gsec,egcd->gsd", combine.astype(dt), expert_out)
    return out, aux_loss * cfg.aux_loss_weight
