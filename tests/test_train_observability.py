"""Train-plane observability (ISSUE 17): per-step waterfalls, XLA
compile & device-memory accounting, and `rayt train status`.

Covers: the GcsTrainManager contract (per-run step store with
oldest-first eviction from the chattiest run + dropped accounting,
purge on job finish, hex-prefix get, filtered list/summarize, compile
and retrace events, device-memory gauges, blocked-phase stall
attribution with transition-only cluster events), the StepRecorder
unit behavior (waterfall tiling by construction, wrap_jit
compile/retrace detection, host-RSS memory fallback), the async
checkpoint split (``ckpt_block_s`` staging returns while the commit
runs in the background), and the E2E acceptance path — a
corpus_pretrain_loop run on the 8-virtual-device CPU mesh whose
retained step records tile step wall within 10%, record at least one
compile event and non-zero device memory, all reachable via state_api
and the `rayt train status` / `rayt list steps` renderers — plus the
pause-ingest stall drill (``ingest_starved`` flag + cluster event).
"""

import json
import os
import time

import numpy as np
import pytest

from ray_tpu.core.gcs_train_manager import (CH_TRAIN, GcsTrainManager,
                                            TRAIN_STAGES)


# --------------------------------------------- GcsTrainManager contract
def _mgr(**kw):
    return GcsTrainManager(**kw)


def _step(run_id, rank=0, step=0, wall=0.010, *, data_wait=None,
          h2d=None, stepc=None, ckpt=None, **extra):
    stages = {"data_wait_s": 0.2 * wall if data_wait is None else data_wait,
              "h2d_s": 0.1 * wall if h2d is None else h2d,
              "step_s": 0.7 * wall if stepc is None else stepc,
              "ckpt_block_s": 0.0 if ckpt is None else ckpt}
    rec = {"kind": "step", "run_id": run_id, "experiment": "exp",
           "rank": rank, "step": step, "wall_s": wall,
           "stages": stages, "ts": 1.0 + step}
    rec.update(extra)
    return rec


def _run(run_id, state="RUNNING", **extra):
    rec = {"kind": "run", "run_id": run_id, "experiment": "exp",
           "job_id": "j" * 8, "world_size": 2, "state": state,
           "ts": 1.0}
    rec.update(extra)
    return rec


def test_manager_step_ingest_and_worker_rollups():
    m = _mgr()
    m.ingest(_run("r1"))
    m.ingest([_step("r1", step=i, tokens=128, loss=1.0 / (i + 1))
              for i in range(3)])
    run = m.get("r1")
    assert run is not None
    assert run["experiment"] == "exp" and run["world_size"] == 2
    w = run["workers"][0]
    assert w["steps_total"] == 3 and w["last_step"] == 2
    assert w["tokens_total"] == 3 * 128
    assert len(w["history"]) == 3
    # history points carry the full waterfall for the sparkline
    assert set(TRAIN_STAGES) <= set(w["history"][0])
    assert m.num_steps() == 3 and m.num_runs() == 1
    # loss/tokens ride the retained record
    out = m.list_steps(run_id="r1")
    assert out["total"] == 3
    assert out["steps"][0]["step"] == 2  # newest first
    assert out["steps"][0]["loss"] == pytest.approx(1.0 / 3)


def test_manager_get_by_hex_prefix():
    m = _mgr()
    m.ingest(_step("deadbeef" * 4))
    assert m.get("deadbeef")["run_id"] == "deadbeef" * 4
    assert m.get("no-such") is None
    # list_steps resolves the prefix too
    assert m.list_steps(run_id="deadbeef")["total"] == 1


def test_manager_eviction_biggest_run_oldest_first():
    m = _mgr(max_steps=4)
    for i in range(5):
        m.ingest(_step("big", step=i))
    m.ingest(_step("small", step=0))
    # the chatty run gave up its OLDEST steps; the small run's record
    # survives even though it arrived last
    ids = {s["step"] for s in m.list_steps(run_id="big",
                                           limit=0)["steps"]}
    assert 0 not in ids and 4 in ids
    assert m.list_steps(run_id="small")["total"] == 1
    assert m.dropped_counts()["big"] == 2
    assert "small" not in m.dropped_counts()
    out = m.list_steps(run_id="big")
    assert out["dropped"]["big"] == 2
    # rollups keep counting what the store evicted
    assert m.get("big")["workers"][0]["steps_total"] == 5
    assert m.get("big")["dropped_steps"] == 2


def test_manager_list_filters_and_slow_order():
    m = _mgr()
    m.ingest(_run("r1"))
    m.ingest(_run("r2", experiment="other"))
    m.ingest([_step("r1", rank=0, step=0, wall=0.010),
              _step("r1", rank=1, step=0, wall=0.050),
              _step("r2", rank=0, step=0, wall=0.002)])
    out = m.list_runs(experiment="exp")
    assert out["total"] == 1 and out["runs"][0]["run_id"] == "r1"
    assert m.list_runs(state="FINISHED")["total"] == 0
    assert m.list_runs(limit=1)["truncated"] == 1
    # rank filter
    assert m.list_steps(run_id="r1", rank=1)["total"] == 1
    # slow ordering spans runs, by wall desc
    steps = m.list_steps(slow=True)["steps"]
    assert [s["wall_s"] for s in steps] == sorted(
        (s["wall_s"] for s in steps), reverse=True)
    assert m.list_steps(min_wall_s=0.04)["total"] == 1


def test_manager_summarize_rolls():
    m = _mgr()
    m.ingest(_run("r1"))
    for i in range(10):
        m.ingest(_step("r1", step=i, wall=0.010 * (i + 1)))
    summ = m.summarize(run_id="r1")
    e = summ["runs"]["r1"]
    assert e["steps"] == 10 and e["last_step"] == 9
    assert e["wall"]["n"] == 10
    assert e["wall"]["p50"] == pytest.approx(0.060, abs=0.011)
    assert e["wall"]["p99"] == pytest.approx(0.100, abs=1e-9)
    assert e["stages"]["step_s"]["mean"] == pytest.approx(
        0.7 * e["wall"]["mean"], rel=1e-6)
    assert summ["total_steps"] == 10 and summ["steps_total"] == 10


def test_manager_purge_on_job_finish():
    m = _mgr()
    m.ingest(_run("gone", job_id="jobdead"))
    m.ingest(_step("gone"))
    m.ingest(_run("kept", job_id="jobalive"))
    m.ingest(_step("kept"))
    # a stalled worker on the purged run must not leak the O(1) count
    m.ingest({"kind": "phase", "run_id": "gone", "rank": 0,
              "phase": "data_wait", "blocked_s": 99.0, "step": 1,
              "ts": 2.0})
    assert m.stalled_count() == 1
    m.on_job_finished("jobdead")
    assert m.get("gone") is None and m.get("kept") is not None
    assert m.num_steps() == 1 and m.stalled_count() == 0
    assert "gone" not in m.dropped_counts()


def test_manager_compile_retrace_events_and_metrics():
    events = []
    m = _mgr(event_cb=lambda *a: events.append(a))
    m.ingest(_run("r1"))
    m.drain_metric_records()
    m.ingest({"kind": "compile", "run_id": "r1", "rank": 0,
              "fn": "sgd_step", "event": "compile", "compile_s": 0.5,
              "shape": "(f32[8,32])", "prev_shape": "", "ts": 2.0})
    assert m.get("r1")["compile_count"] == 1
    assert not events  # first-trace compile is expected, no warning
    recs = m.drain_metric_records()
    assert any(r["name"] == "rayt_train_compiles_total"
               and r["tags"]["event"] == "compile" for r in recs)
    # a retrace is a perf bug: WARNING event with the shape delta
    m.ingest({"kind": "compile", "run_id": "r1", "rank": 0,
              "fn": "sgd_step", "event": "retrace", "compile_s": 0.4,
              "shape": "(f32[4,32])", "prev_shape": "(f32[8,32])",
              "ts": 3.0})
    assert m.get("r1")["retrace_count"] == 1
    kind, msg, sev, job, data = events[-1]
    assert kind == "train_retrace" and sev == "WARNING"
    assert "(f32[8,32]) -> (f32[4,32])" in msg
    assert data["fn"] == "sgd_step"


def test_manager_memory_gauges():
    m = _mgr()
    m.drain_metric_records()
    m.ingest({"kind": "memory", "run_id": "r1", "rank": 0,
              "node_id": "n" * 8,
              "devices": [{"device": "tpu:0", "bytes_in_use": 1000,
                           "peak_bytes": 2000},
                          {"device": "tpu:1", "bytes_in_use": 500,
                           "peak_bytes": 700}],
              "ts": 2.0})
    recs = m.drain_metric_records()
    used = {r["tags"]["device"]: r["value"] for r in recs
            if r["name"] == "rayt_device_memory_used_bytes"}
    peak = {r["tags"]["device"]: r["value"] for r in recs
            if r["name"] == "rayt_device_memory_peak_bytes"}
    assert used == {"tpu:0": 1000, "tpu:1": 500}
    assert peak == {"tpu:0": 2000, "tpu:1": 700}
    assert all(r["tags"]["node"] == "n" * 8 for r in recs
               if r["name"].startswith("rayt_device_memory"))
    # summarize folds the per-device totals
    m.ingest(_step("r1"))
    e = m.summarize(run_id="r1")["runs"]["r1"]
    assert e["memory_used_bytes"] == 1500
    assert e["memory_peak_bytes"] == 2700


def test_manager_stall_attribution_and_transitions():
    events = []
    m = _mgr(stall_grace_s=5.0,
             event_cb=lambda *a: events.append(a))

    def phase(phase, blocked, step=7):
        return {"kind": "phase", "run_id": "r1", "rank": 0,
                "phase": phase, "blocked_s": blocked, "step": step,
                "ts": 10.0}

    m.ingest(_run("r1"))
    # under the grace window: ignored
    m.ingest(phase("data_wait", 1.0))
    assert m.stalled_count() == 0 and not events
    # past grace: stalled, attributed ingest_starved, WARNING event
    m.ingest(phase("data_wait", 6.0))
    assert m.stalled_count() == 1
    kind, msg, sev, job, data = events[-1]
    assert kind == "train_stall" and sev == "WARNING"
    assert data["attribution"] == "ingest_starved"
    assert "ingest_starved" in msg and "data_wait" in msg
    # same attribution heartbeat: quiet refresh, no event spam
    n = len(events)
    m.ingest(phase("data_wait", 8.0))
    assert len(events) == n and m.stalled_count() == 1
    stall = m.get("r1")["workers"][0]["stall"]
    assert stall["blocked_s"] == 8.0
    # attribution change fires a new WARNING
    m.ingest(phase("ckpt_block", 6.0))
    assert events[-1][0] == "train_stall"
    assert events[-1][4]["attribution"] == "checkpoint_blocked"
    assert m.stalled_count() == 1  # still ONE stalled worker
    # compute-side block attributes to the collective barrier
    m.ingest(phase("step", 6.0))
    assert events[-1][4]["attribution"] == "collective_barrier"
    # a fresh step record clears the flag with an INFO transition
    m.ingest(_step("r1", step=8))
    assert m.stalled_count() == 0
    kind, msg, sev, job, data = events[-1]
    assert kind == "train_stall_cleared" and sev == "INFO"
    # summarize surfaces stalled workers while flagged
    m.ingest(phase("data_wait", 6.0))
    e = m.summarize(run_id="r1")["runs"]["r1"]
    assert e["stalled_workers"][0]["attribution"] == "ingest_starved"


def test_manager_starved_workers_by_dp_rank():
    m = _mgr()
    m.ingest(_run("r1"))
    for i in range(4):  # rank 1 spends half its wall waiting on ingest
        m.ingest(_step("r1", rank=0, step=i, wall=0.010,
                       data_wait=0.0005))
        m.ingest(_step("r1", rank=1, step=i, wall=0.010,
                       data_wait=0.005))
    e = m.summarize(run_id="r1")["runs"]["r1"]
    assert list(e["starved_workers"]) == [1]
    assert e["starved_workers"][1]["share"] == pytest.approx(0.5)


def test_manager_derives_histograms_before_eviction():
    """Prometheus series must be unskewed by retention: an evicted step
    record still contributed its waterfall observations."""
    m = _mgr(max_steps=2)
    m.drain_metric_records()
    for i in range(5):
        m.ingest(_step("r1", step=i))
    recs = m.drain_metric_records()
    per_name = {}
    for r in recs:
        per_name[r["name"]] = per_name.get(r["name"], 0) + 1
    for stage in TRAIN_STAGES:
        assert per_name.get(f"rayt_train_{stage}") == 5, per_name
    assert all(r["kind"] == "histogram" and r.get("bounds")
               for r in recs)
    assert m.num_steps() == 2  # store bounded, series complete


def test_manager_malformed_records_do_not_poison_batch():
    m = _mgr()
    m.ingest([{"kind": "step"}, None, {"no": "kind"},
              {"kind": "step", "run_id": "ok", "rank": "x"},
              _step("ok", step=1)])
    assert m.list_steps(run_id="ok")["total"] == 1


# ------------------------------------------------- StepRecorder (unit)
class _FakeCW:
    gcs = object()

    def _spawn_from_thread(self, coro):
        coro.close()


def _recorder(experiment="unit"):
    from ray_tpu.train.telemetry import StepRecorder

    rec = StepRecorder("a" * 32, experiment, rank=0, node_id="n" * 8)
    fake = _FakeCW()
    rec._pub._core_worker = lambda: fake
    return rec


def _drain(rec):
    with rec._pub._lock:
        out, rec._pub._buf = rec._pub._buf, []
    return out


def test_recorder_waterfall_tiles_wall():
    rec = _recorder()
    rec.end_step(0)  # open the wall clock
    _drain(rec)
    with rec.phase("data_wait"):
        time.sleep(0.02)
    with rec.phase("h2d"):
        time.sleep(0.005)
    with rec.phase("step"):
        time.sleep(0.03)
    rec.add_stage("ckpt_block", 0.001)
    rec.end_step(1, tokens=64, loss=0.5)
    (r,) = _drain(rec)
    assert r["kind"] == "step" and r["step"] == 1
    assert r["tokens"] == 64 and r["loss"] == 0.5
    ssum = sum(r["stages"].values())
    assert set(r["stages"]) == {f"{k}_s" for k in
                                ("data_wait", "h2d", "step",
                                 "ckpt_block")}
    # tiling by construction: stages nest inside the wall, covering it
    # up to loop overhead (sub-ms here)
    assert ssum <= r["wall_s"] + 1e-3
    assert r["wall_s"] - ssum < 0.1 * r["wall_s"] + 5e-3
    # the accumulators reset per step
    rec.end_step(2)
    (r2,) = _drain(rec)
    assert sum(r2["stages"].values()) == 0.0


def test_recorder_wrap_jit_compile_and_retrace():
    import jax.numpy as jnp

    rec = _recorder()

    def f(x):
        return x * 2

    wrapped = rec.wrap_jit(f, "f")
    assert float(wrapped(jnp.ones((4,)))[0]) == 2.0
    (r,) = [x for x in _drain(rec) if x["kind"] == "compile"]
    assert r["event"] == "compile" and r["fn"] == "f"
    assert r["compile_s"] >= 0 and "4" in r["shape"]
    # same signature: no event
    wrapped(jnp.ones((4,)))
    assert not [x for x in _drain(rec) if x["kind"] == "compile"]
    # new shape: retrace with the delta
    wrapped(jnp.ones((8,)))
    (r2,) = [x for x in _drain(rec) if x["kind"] == "compile"]
    assert r2["event"] == "retrace"
    assert r2["prev_shape"] == r["shape"] and "8" in r2["shape"]


def test_recorder_flush_extras_heartbeat_and_memory(monkeypatch):
    monkeypatch.setenv("RAYT_TRAIN_STALL_GRACE_S", "0.05")
    from ray_tpu._internal import config as cfg_mod

    old = cfg_mod._config
    cfg_mod.set_config(cfg_mod.load_config())
    try:
        rec = _recorder()
        rec.begin_phase("data_wait")
        recs, keep = rec._flush_extras()
        assert keep  # a phase is open: the chain must stay alive
        assert not [r for r in recs if r["kind"] == "phase"]  # in grace
        time.sleep(0.08)
        recs, keep = rec._flush_extras()
        hb = [r for r in recs if r["kind"] == "phase"]
        assert keep and hb and hb[0]["phase"] == "data_wait"
        assert hb[0]["blocked_s"] >= 0.05
        rec.end_phase()
        recs, keep = rec._flush_extras()
        assert not keep and not [r for r in recs
                                 if r["kind"] == "phase"]
    finally:
        cfg_mod._config = old
    # the first cycle carried a memory snapshot (CPU backend: host RSS
    # fallback keeps the gauges non-zero)
    from ray_tpu.train.telemetry import device_memory_snapshot

    devs = device_memory_snapshot()
    assert devs and all(d["bytes_in_use"] > 0 and d["peak_bytes"] > 0
                        for d in devs)


# -------------------------------------------- async checkpoint overlap
def test_async_save_overlaps_next_step(monkeypatch, tmp_path):
    """The staging slice (``ckpt_block_s``) returns while the commit
    runs in the background; ``wait()`` joins it and the checkpoint
    round-trips. Forces the fallback path (deterministic commit gate);
    the orbax path is covered by the round-trip test below."""
    import pickle as _pickle
    import threading

    from ray_tpu.train import checkpoint as ckpt_mod

    gate = threading.Event()
    real_dump = _pickle.dump

    def slow_dump(obj, f, **kw):
        assert gate.wait(timeout=30), "commit gate never released"
        return real_dump(obj, f, **kw)

    monkeypatch.setitem(__import__("sys").modules, "orbax.checkpoint",
                        None)  # force the pickle fallback
    monkeypatch.setattr(ckpt_mod.pickle, "dump", slow_dump)
    state = {"w": np.arange(1000, dtype=np.float32)}
    h = ckpt_mod.save_pytree_async(state, str(tmp_path / "ck"))
    # staging returned while the commit is parked on the gate: the next
    # step can run here
    assert not h.done and h.block_s >= 0.0
    next_step = float(np.sum(state["w"]))  # "the next step"
    gate.set()
    commit_s = h.wait()
    assert h.done and commit_s >= 0.0
    assert h.wait() == commit_s  # idempotent join
    monkeypatch.setattr(ckpt_mod.pickle, "dump", real_dump)
    loaded = ckpt_mod.load_pytree(str(tmp_path / "ck"))
    assert np.array_equal(loaded["w"], state["w"])
    assert next_step == pytest.approx(float(np.sum(loaded["w"])))


def test_async_save_roundtrip_default_path(tmp_path):
    """Whatever backend is importable (orbax async or the thread
    fallback), the async save handle commits a loadable checkpoint."""
    from ray_tpu.train.checkpoint import load_pytree, save_pytree_async

    state = {"a": np.arange(16, dtype=np.int32),
             "b": {"c": np.ones((2, 3), dtype=np.float32)}}
    h = save_pytree_async(state, str(tmp_path / "ck"))
    assert h.wait() >= 0.0 and h.done
    out = load_pytree(str(tmp_path / "ck"))
    assert np.array_equal(np.asarray(out["a"]), state["a"])
    assert np.array_equal(np.asarray(out["b"]["c"]), state["b"]["c"])


# ----------------------------------------------- E2E: train run -> GCS
def _make_corpus(root, *, shards=4, docs=40, seed=1):
    corpus = os.path.join(root, "corpus")
    os.makedirs(corpus, exist_ok=True)
    rng = np.random.default_rng(seed)
    for s in range(shards):
        with open(os.path.join(corpus, f"s{s:03d}.jsonl"), "w") as f:
            for _ in range(docs):
                toks = rng.integers(1, 100,
                                    rng.integers(5, 60)).tolist()
                f.write(json.dumps({"tokens": toks}) + "\n")
    return corpus


@pytest.fixture
def obs_cluster(monkeypatch):
    """Cluster with a fast train flush cadence so short CPU runs land
    their memory snapshots and step batches before worker teardown."""
    monkeypatch.setenv("RAYT_TRAIN_FLUSH_INTERVAL_S", "0.2")
    monkeypatch.setenv("RAYT_TRAIN_STALL_GRACE_S", "0.6")
    from ray_tpu._internal import config as cfg_mod

    old = cfg_mod._config
    cfg_mod.set_config(cfg_mod.load_config())
    import ray_tpu

    ray_tpu.init(num_cpus=4, resources={"TPU": 8})
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()
        cfg_mod._config = old


def _fit(corpus, root, name, *, steps=12):
    from ray_tpu.train import IngestSpec, JaxTrainer
    from ray_tpu.train.config import (FailureConfig, RunConfig,
                                      ScalingConfig)
    from ray_tpu.train.recipes import corpus_pretrain_loop

    spec = IngestSpec(paths=corpus, seq_len=32, batch_blocks=4,
                      eos_id=0, epochs=8)
    # big-enough embedding table that a step is ~2ms of real compute on
    # CPU — at the default toy size (~66us/step) fixed per-step
    # bookkeeping would dominate the tiling-residual assertion
    cfg = {"steps": steps, "checkpoint_every": 4, "vocab_size": 8192,
           "dim": 256}
    trainer = JaxTrainer(
        corpus_pretrain_loop, train_loop_config=cfg,
        scaling_config=ScalingConfig(num_workers=1, ingest=spec),
        run_config=RunConfig(
            name=f"obs-{name}",
            storage_path=os.path.join(root, "res"),
            failure_config=FailureConfig(max_failures=0)))
    return trainer.fit()


def _wait(fn, timeout=20.0, desc="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(0.25)
    raise AssertionError(f"{desc} never became true")


@pytest.mark.timeout(170)
def test_e2e_run_waterfall_compile_memory_and_cli(obs_cluster,
                                                  tmp_path, capsys):
    """ISSUE acceptance: a corpus_pretrain_loop run on the CPU mesh
    yields per-step GCS records whose stages tile step wall within 10%,
    at least one compile event, and non-zero device-memory gauges — all
    reachable via state_api, `rayt train status` and `rayt list
    steps`."""
    from ray_tpu import state_api

    root = str(tmp_path)
    res = _fit(_make_corpus(root), root, "wf")
    assert res.error is None and res.metrics["step"] == 12

    # the FINISHED lifecycle record is flushed from the driver-side
    # publisher on a timer, so it can trail the workers' step records
    runs = _wait(lambda: [
        r for r in state_api.list_train_runs()
        if r["experiment"] == "obs-wf"
        and r["workers"].get(0, {}).get("steps_total", 0) >= 12
        and r["state"] == "FINISHED"],
        desc="FINISHED train run with 12 steps in the GCS")
    run = runs[0]
    rid = run["run_id"]
    assert run["world_size"] == 1

    # waterfall tiling: stages sum to the step wall within 10% (+2ms
    # epsilon for sub-ms CPU steps); checkpoint-boundary steps pay
    # untracked report bookkeeping, so judge the non-checkpoint ones
    steps = state_api.list_train_steps(run_id=rid, limit=0)
    assert steps and len(steps) >= 10
    residual_shares = []
    for s in steps:
        ssum = sum(s["stages"].values())
        assert ssum <= s["wall_s"] + 2e-3, s
        if s["step"] > 1 and s["step"] % 4 != 0:
            residual_shares.append(
                (s["wall_s"] - ssum) / max(s["wall_s"], 1e-9))
    residual_shares.sort()
    assert residual_shares[len(residual_shares) // 2] <= 0.10, \
        residual_shares
    # every step spent real time in compute and the waterfall keys are
    # the canonical four
    assert all(set(s["stages"]) == set(TRAIN_STAGES) for s in steps)
    assert any(s["stages"]["step_s"] > 0 for s in steps)
    assert any(s["stages"]["data_wait_s"] > 0 for s in steps)

    # at least one compile event (the sgd_step first trace), retained
    # on the run and counted in the summary
    assert run["compile_count"] >= 1
    assert any(c["fn"] == "sgd_step" and c["event"] == "compile"
               for c in run["compiles"])
    summ = state_api.summarize_train_runs(run_id=rid)
    e = summ["runs"][rid]
    assert e["compile_count"] >= 1
    assert e["wall"]["n"] >= 10 and e["stages"]["step_s"]["p50"] > 0

    # device-memory gauges non-zero (host-RSS fallback on CPU)
    mem = run["workers"][0].get("memory")
    assert mem and mem["devices"], "memory snapshot never landed"
    assert all(d["bytes_in_use"] > 0 for d in mem["devices"])
    assert e["memory_used_bytes"] > 0 and e["memory_peak_bytes"] > 0
    from ray_tpu.core.object_ref import get_core_worker

    cw = get_core_worker()
    snap = _wait(lambda: [
        m for m in cw.io.run(cw.gcs.conn.call("metrics_snapshot"))
        if m.get("name") == "rayt_device_memory_used_bytes"
        and m.get("value", 0) > 0],
        desc="rayt_device_memory_used_bytes gauge")
    assert snap[0]["tags"].get("device")
    # the step histograms flowed too
    names = {m.get("name")
             for m in cw.io.run(cw.gcs.conn.call("metrics_snapshot"))}
    for stage in TRAIN_STAGES:
        assert f"rayt_train_{stage}" in names, names

    # hex-prefix get + state filter
    assert state_api.get_train_run(rid[:8])["run_id"] == rid
    assert any(r["run_id"] == rid
               for r in state_api.list_train_runs(state="FINISHED"))

    # the CLI renderers (the `rayt train status` / `rayt list steps`
    # bodies) print the waterfall from the same surfaces
    from ray_tpu.scripts.cli import _print_steps, _print_train_waterfall

    _print_train_waterfall(summ)
    text = capsys.readouterr().out
    assert "obs-wf" in text and "data_wait" in text, text
    assert "compiles=" in text and "p99" in text
    assert "steps recorded" in text
    _print_steps(state_api.list_train_steps(run_id=rid, slow=True,
                                            detail=True))
    text = capsys.readouterr().out
    assert "data_wait" in text and "> step" in text, text
    assert "matched" in text


@pytest.mark.timeout(120)
def test_pause_ingest_stall_drill(obs_cluster):
    """ISSUE acceptance: a worker parked in the ingest dequeue past the
    grace window is flagged ``ingest_starved`` — with the matching
    WARNING cluster event — and the flag clears (INFO event) when the
    step resumes. Driven by a real StepRecorder heartbeat, not by
    hand-fed phase records."""
    from ray_tpu import state_api
    from ray_tpu.train.telemetry import (StepRecorder, mint_run_id,
                                         publish_record)

    run_id = mint_run_id()
    publish_record({"kind": "run", "run_id": run_id,
                    "experiment": "drill", "job_id": "",
                    "world_size": 1, "state": "RUNNING",
                    "ts": time.time()})
    rec = StepRecorder(run_id, "drill", rank=0)
    rec.end_step(0)
    rec.begin_phase("data_wait")  # ...and the ingest queue goes quiet

    def stalled():
        summ = state_api.summarize_train_runs(run_id=run_id)
        e = (summ["runs"] or {}).get(run_id)
        sw = (e or {}).get("stalled_workers") or {}
        return sw if 0 in sw else None

    sw = _wait(stalled, timeout=30, desc="ingest_starved stall flag")
    assert sw[0]["attribution"] == "ingest_starved"
    assert sw[0]["phase"] == "data_wait"
    ev = _wait(lambda: [
        e for e in state_api.list_cluster_events(source="train",
                                                 limit=0)
        if e["kind"] == "train_stall"
        and e.get("data", {}).get("run_id") == run_id],
        desc="train_stall cluster event")
    assert ev[0]["severity"] == "WARNING"
    assert ev[0]["data"]["attribution"] == "ingest_starved"
    assert "ingest_starved" in ev[0]["message"]

    # the batch arrives: the step closes and the flag clears
    rec.end_phase()
    rec.end_step(1)
    rec._pub.flush_now()
    _wait(lambda: not stalled(), timeout=30, desc="stall clear")
    _wait(lambda: [
        e for e in state_api.list_cluster_events(source="train",
                                                 limit=0)
        if e["kind"] == "train_stall_cleared"
        and e.get("data", {}).get("run_id") == run_id],
        desc="train_stall_cleared event")
    rec.close()


@pytest.mark.timeout(120)
def test_rl_learner_emits_step_waterfall(obs_cluster):
    """RL parity satellite: the IMPALA learner's update loop emits the
    same train_state records (experiment ``rl:impala``) showing the
    data-wait vs update split."""
    import cloudpickle

    from ray_tpu import state_api
    from ray_tpu.rl.impala import IMPALAConfig, IMPALALearner
    from ray_tpu.rl.module import MLPModuleConfig

    cfg_obj = IMPALAConfig(env="CartPole-v1")
    module_cfg = MLPModuleConfig(observation_size=4, num_actions=2,
                                 hidden=(16,))
    learner = IMPALALearner(cloudpickle.dumps(module_cfg),
                            cloudpickle.dumps(cfg_obj))
    assert learner._recorder is not None

    T, B = 8, 4
    rng = np.random.default_rng(0)

    def batch():
        return {
            "obs": rng.normal(size=(T, B, 4)).astype(np.float32),
            "last_obs": rng.normal(size=(B, 4)).astype(np.float32),
            "actions": rng.integers(0, 2, (T, B)).astype(np.int32),
            "logp": np.full((T, B), -0.6931, np.float32),
            "rewards": np.ones((T, B), np.float32),
            "dones": np.zeros((T, B), np.float32),
            "trunc_values": np.zeros((T, B), np.float32),
        }

    for _ in range(3):
        out = learner.update(batch())
        assert np.isfinite(out["loss"])
    learner._recorder.end_phase()  # close the trailing data_wait
    learner._recorder._pub.flush_now()

    rid = learner._run_id
    steps = _wait(lambda: state_api.list_train_steps(run_id=rid,
                                                     limit=0),
                  desc="RL learner step records")
    assert len(steps) == 3
    # the update split is honest: compute time recorded every step,
    # data-wait recorded once the inter-update gap is measured
    assert all(s["stages"]["step_s"] > 0 for s in steps)
    assert any(s["stages"]["data_wait_s"] > 0
               for s in steps if s["step"] > 1)
    runs = state_api.list_train_runs(experiment="rl:impala")
    assert any(r["run_id"] == rid for r in runs)
    # the first trace of the jitted v-trace update was recorded
    assert state_api.get_train_run(rid)["compile_count"] >= 1
