"""Object spilling + memory-monitor policy (ref analogs:
src/ray/raylet/local_object_manager.h:41 spill-to-disk,
common/memory_monitor.h + worker_killing_policy_retriable_fifo.cc)."""

import time

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu._internal.config import get_config


@pytest.fixture
def tiny_store_cluster():
    """Cluster whose head advertises a 2 MiB object store with a 50%
    spill watermark — a few 512 KiB objects force spilling."""
    cfg = get_config()
    saved = (cfg.object_store_memory, cfg.object_spilling_threshold)
    cfg.object_store_memory = 2 << 20
    cfg.object_spilling_threshold = 0.5
    import ray_tpu.cluster_utils as cu

    cluster = cu.Cluster(head_resources={"CPU": 4.0})
    cluster.connect()
    try:
        yield cluster
    finally:
        cluster.shutdown()
        cfg.object_store_memory, cfg.object_spilling_threshold = saved


def _node_stats(cluster):
    import ray_tpu.core.runtime as rtc

    cw = rtc.get_runtime_context().core_worker
    return cw.io.run(cw.node_conn.call("node_stats"))


def test_objects_spill_and_restore(tiny_store_cluster):
    cluster = tiny_store_cluster
    refs = [rt.put(np.full(512 * 1024, i, dtype=np.uint8))
            for i in range(6)]  # 3 MiB total >> 1 MiB watermark
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if _node_stats(cluster)["num_spilled"] > 0:
            break
        time.sleep(0.2)
    stats = _node_stats(cluster)
    assert stats["num_spilled"] > 0, stats
    # every object still reads back correctly (spilled ones restore)
    for i, ref in enumerate(refs):
        arr = rt.get(ref, timeout=60)
        assert int(arr[0]) == i and arr.shape == (512 * 1024,)
    stats = _node_stats(cluster)
    assert stats["num_restored"] > 0, stats


def test_spilled_object_consumed_by_task(tiny_store_cluster):
    cluster = tiny_store_cluster
    refs = [rt.put(np.full(512 * 1024, i, dtype=np.uint8))
            for i in range(6)]
    time.sleep(1.0)  # let the spill loop work

    @rt.remote(num_cpus=1)
    def head_sum(arr):
        return int(arr[0]) + int(arr[-1])

    # tasks resolving spilled args trigger restore through the pull path
    results = rt.get([head_sum.remote(r) for r in refs], timeout=90)
    assert results == [2 * i for i in range(6)]


def test_kill_policy_prefers_retriable_task_workers():
    """Unit test of the OOM victim policy: newest busy task worker first,
    actors only as a last resort."""
    from ray_tpu.core.node_manager import NodeManager

    class W:
        def __init__(self, busy, actor, t):
            self.busy = busy
            self.actor_id = actor
            self.last_idle = t
            self.info = None

    nm = object.__new__(NodeManager)  # policy only; no ctor
    nm.workers = {i: w for i, w in enumerate([
        W(True, None, 1.0), W(True, None, 5.0), W(True, "actor", 9.0),
        W(False, None, 7.0)])}
    victim = NodeManager._pick_worker_to_kill(nm)
    assert victim.last_idle == 5.0  # newest busy NON-actor worker
    # only actors left -> pick the actor
    nm.workers = {0: W(True, "actor", 3.0), 1: W(True, "actor", 8.0)}
    victim = NodeManager._pick_worker_to_kill(nm)
    assert victim.last_idle == 8.0
