"""Task-event tracing: per-worker event buffer -> GCS task manager ->
Chrome trace export (ref analogs: src/ray/core_worker/task_event_buffer.cc,
gcs/gcs_server/gcs_task_manager.h task-event store, and the
`ray timeline` Chrome-trace exporter at scripts/scripts.py `timeline`).

Processes record per-task STATE TRANSITIONS (PENDING_ARGS -> SCHEDULED ->
DISPATCHED -> RUNNING -> FINISHED/FAILED, each timestamped, with attempt
number and a truncated error payload on failure) into a bounded local
ring; a periodic flush ships them to the GCS, whose task manager
coalesces the transitions of one task into a single record
(core/gcs_task_manager.py). `rayt timeline` renders the records as
nested Chrome trace-viewer slices — one outer slice per task lifetime,
inner slices per lifecycle phase — grouped by node (pid) and worker
(tid).
"""

from __future__ import annotations

import collections
import json
import threading
import time
from typing import Any

# local buffer bound: ring semantics — when full the OLDEST events are
# evicted (the flush loop drains every second, so hitting it means a
# flood; the timeline must show the flood's tail, not freeze at its
# start) and the drop is accounted in a meta event
_LOCAL_CAP = 4096

# Task lifecycle states, in transition order (ref: rpc::TaskStatus).
# FAILED outranks FINISHED (a task whose retry failed is FAILED), and
# CANCELLED outranks both: rt.cancel() wins even when it races the body
# to completion (core_worker cancel semantics), and a deliberate cancel
# must not count as a failure in summaries.
TASK_STATES = ("PENDING_ARGS", "SCHEDULED", "DISPATCHED", "RUNNING",
               "FINISHED", "FAILED", "CANCELLED")
TERMINAL_STATES = ("FINISHED", "FAILED", "CANCELLED")

# error payload truncation (a 100MB traceback must not transit the
# control plane; ref: RAY_task_events_max_error_message_length)
_ERR_MSG_CAP = 500
_ERR_TB_CAP = 2000


def truncate_error(exc_type: str, message: str, tb: str = "") -> dict:
    """Bounded error payload carried on a FAILED transition."""
    return {"type": exc_type[:200], "message": (message or "")[:_ERR_MSG_CAP],
            "traceback": (tb or "")[-_ERR_TB_CAP:]}


def make_transition(*, task_id: str, name: str, kind: str, state: str,
                    job_id: str = "", actor_id: str = "", attempt: int = 0,
                    worker: str = "", node: str = "",
                    error: dict | None = None,
                    resources: dict | None = None,
                    ts: float | None = None) -> dict:
    """The one wire schema for a lifecycle transition event — every
    emitter (worker buffer, node manager, GCS-side actor-creation flow)
    builds events here so the coalescer never sees divergent shapes.
    ``resources`` (the demand shape, carried on the submit-side
    PENDING_ARGS) is the join key `rayt why-pending` uses against the
    scheduling decision traces."""
    ev = {
        "type": "transition", "task_id": task_id, "name": name,
        "kind": kind, "state": state, "job_id": job_id,
        "actor_id": actor_id, "attempt": attempt,
        "worker": worker, "node": node,
        "ts_us": int((time.time() if ts is None else ts) * 1e6),
    }
    if error is not None:
        ev["error"] = error
    if resources is not None:
        ev["resources"] = resources
    return ev


class TaskEventBuffer:
    def __init__(self, worker_hex: str, node_hex: str,
                 enabled: bool | None = None):
        self.worker = worker_hex
        self.node = node_hex
        if enabled is None:
            from ray_tpu._internal.config import get_config

            enabled = get_config().task_events_enabled
        self.enabled = enabled
        self._events: collections.deque = collections.deque()
        self._dropped = 0
        self._lock = threading.Lock()

    def _append(self, ev: dict):
        with self._lock:
            self._events.append(ev)
            if len(self._events) > _LOCAL_CAP:
                # ring semantics: evict OLDEST so a flood's tail survives
                self._events.popleft()
                self._dropped += 1

    def record_transition(self, *, task_id: str, name: str, kind: str,
                          state: str, job_id: str = "", actor_id: str = "",
                          attempt: int = 0, error: dict | None = None,
                          resources: dict | None = None,
                          ts: float | None = None):
        """One lifecycle state transition (ref: TaskEventBuffer::
        RecordTaskStatusEvent). Near-free when task events are disabled —
        the hot submit path pays one attribute check. Enabled, it
        appends a COMPACT tuple; the wire dict materializes at drain
        time (the 1s flush), keeping the per-submit cost to a deque
        append (``resources`` rides as a dict REFERENCE, not a copy)."""
        if not self.enabled:
            return
        self._append(("t", task_id, name, kind, state, job_id, actor_id,
                      attempt, error, time.time() if ts is None else ts,
                      resources))

    def drain(self) -> list[dict]:
        with self._lock:
            raw = list(self._events)
            self._events.clear()
            out = [make_transition(
                task_id=e[1], name=e[2], kind=e[3], state=e[4],
                job_id=e[5], actor_id=e[6], attempt=e[7],
                worker=self.worker, node=self.node, error=e[8],
                ts=e[9], resources=e[10] if len(e) > 10 else None)
                if isinstance(e, tuple) else e
                for e in raw]
            if self._dropped:
                out.append({
                    "name": f"<dropped {self._dropped} events>",
                    "task_id": "", "kind": "meta", "worker": self.worker,
                    "node": self.node, "actor_id": "", "ok": True,
                    "dropped": self._dropped,
                    "ts_us": int(time.time() * 1e6), "dur_us": 0})
                self._dropped = 0
            return out


# ------------------------------------------------------ Chrome trace
# inner-slice labels: the phase a task is in AFTER entering state K
_PHASE_LABELS = {
    "PENDING_ARGS": "scheduling",   # waiting for a lease / placement
    "SCHEDULED": "dispatch",        # lease granted, pushing to worker
    "DISPATCHED": "startup",        # on the worker, not yet executing
    "RUNNING": "execution",
}


def _record_slices(rec: dict) -> list[dict]:
    """Render one coalesced task record as nested Chrome slices: an
    outer "X" spanning the whole lifecycle plus one inner slice per
    phase (Perfetto nests same-tid containment automatically)."""
    states: dict = rec.get("states") or {}
    order = [s for s in TASK_STATES if s in states]
    if not order:
        return []
    t0 = states[order[0]]
    t1 = max(states.values())
    pid = f"node:{(rec.get('node') or '?')[:8]}"
    tid = f"worker:{(rec.get('worker') or '?')[:8]}"
    err = rec.get("error") or {}
    args = {"task_id": rec.get("task_id", ""),
            "actor_id": rec.get("actor_id", ""),
            "job_id": rec.get("job_id", ""),
            "attempt": rec.get("attempt", 0),
            "state": rec.get("state", ""),
            "ok": rec.get("state") != "FAILED"}
    if err:
        args["error"] = f"{err.get('type', '')}: {err.get('message', '')}"
    out = [{
        "name": rec.get("name", "task"), "cat": rec.get("kind", "task"),
        "ph": "X", "ts": t0, "dur": max(1, t1 - t0),
        "pid": pid, "tid": tid, "args": args,
    }]
    if len(order) >= 3:  # enough structure for per-phase breakdown
        for a, b in zip(order, order[1:]):
            label = _PHASE_LABELS.get(a)
            if label is None:
                continue
            out.append({
                "name": f"{rec.get('name', 'task')} [{label}]",
                "cat": "phase", "ph": "X", "ts": states[a],
                "dur": max(1, states[b] - states[a]),
                "pid": pid, "tid": tid,
                "args": {"task_id": rec.get("task_id", "")},
            })
    return out


def to_chrome_trace(events: list[dict]) -> dict:
    """Chrome trace-viewer JSON (load via chrome://tracing / Perfetto).

    Accepts coalesced task records (GCS task manager, carry a "states"
    map -> nested lifecycle slices), otel spans (otel.py read_spans
    dicts, carry "start_ns" — per-tick DAG spans land here, grouped by
    pid and stitched by trace id), and legacy flat duration events
    (single "X" each); meta events are skipped.
    """
    trace_events: list[dict] = []
    for ev in events:
        if "states" in ev:
            trace_events.extend(_record_slices(ev))
            continue
        if "start_ns" in ev:
            trace_events.append({
                "name": ev.get("name", "span"),
                "cat": ev.get("kind", "span"),
                "ph": "X",
                "ts": ev["start_ns"] // 1000,
                "dur": max(1, (ev.get("end_ns", ev["start_ns"])
                               - ev["start_ns"]) // 1000),
                "pid": f"pid:{ev.get('pid', '?')}",
                "tid": f"trace:{(ev.get('trace_id') or '?')[:8]}",
                "args": {"trace_id": ev.get("trace_id", ""),
                         "span_id": ev.get("span_id", ""),
                         "parent_id": ev.get("parent_id"),
                         "ok": ev.get("status_ok", True),
                         **(ev.get("attributes") or {})},
            })
            continue
        if ev.get("kind") == "meta":
            continue
        trace_events.append({
            "name": ev["name"],
            "cat": ev.get("kind", "task"),
            "ph": "X",
            "ts": ev["ts_us"],
            "dur": max(1, ev.get("dur_us", 0)),
            "pid": f"node:{ev['node'][:8]}",
            "tid": f"worker:{ev['worker'][:8]}",
            "args": {"task_id": ev.get("task_id", ""),
                     "actor_id": ev.get("actor_id", ""),
                     "ok": ev.get("ok", True)},
        })
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def export_chrome_trace(events: list[dict], path: str) -> int:
    data = to_chrome_trace(events)
    with open(path, "w") as f:
        json.dump(data, f)
    return len(data["traceEvents"])
