"""LoRA adapters for the Llama family, TPU-first.

The reference has no first-class LoRA: fine-tuning arrives via
torch/DeepSpeed examples (ref: doc/source/train/examples/deepspeed/,
release/air_examples/dolly_v2_lightning_fsdp_finetuning/). Here LoRA is a
native model-layer feature because the adapter shardings, the frozen-base
gradient cut, and the remat policy must be co-designed with GSPMD
(BASELINE.json config #3: Llama-2-7B LoRA fine-tune at >=35% MFU).

Design:

* Adapters live in their OWN subtree ``{"layers": {"wq_a": [L, d, r],
  "wq_b": [L, r, out], ...}}`` — per-layer A/B stacked on the leading
  "layers" axis exactly like the base weights, so they ride the same
  ``lax.scan`` over blocks with zero extra traces.
* The forward applies the low-rank path ``x @ A @ B * (alpha / r)`` next
  to the frozen matmul — the [d, out] delta is NEVER materialized (a 7B
  delta would be ~6.5 GB bf16; the low-rank path is ~2*r/d of the base
  matmul FLOPs).
* Training differentiates ONLY w.r.t. the adapter subtree
  (``build_train_step(..., trainable_keys=("lora",))``): the backward
  never computes frozen-weight gradients, and optimizer moments exist
  only for adapters — the actual LoRA memory/FLOP win, not an
  optax-masked imitation of it.
* ``merge_lora`` folds adapters into base weights for serving.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp

from ray_tpu.models.llama import LlamaConfig

# target name -> (base param key, A logical in-axis, B logical out-axis)
_TARGET_AXES = {
    "wq": ("embed", "heads"),
    "wk": ("embed", "kv_heads"),
    "wv": ("embed", "kv_heads"),
    "wo": ("heads", "embed"),
    "w_gate": ("embed", "mlp"),
    "w_up": ("embed", "mlp"),
    "w_down": ("mlp", "embed"),
}

DEFAULT_TARGETS = ("wq", "wk", "wv", "wo")


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    rank: int = 8
    alpha: float = 16.0
    targets: tuple = DEFAULT_TARGETS

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


def _target_dims(cfg: LlamaConfig, name: str) -> tuple[int, int]:
    d, h = cfg.dim, cfg.hidden_dim
    dims = {
        "wq": (d, cfg.n_heads * cfg.head_dim),
        "wk": (d, cfg.n_kv_heads * cfg.head_dim),
        "wv": (d, cfg.n_kv_heads * cfg.head_dim),
        "wo": (cfg.n_heads * cfg.head_dim, d),
        "w_gate": (d, h),
        "w_up": (d, h),
        "w_down": (h, d),
    }
    return dims[name]


def init_lora_params(cfg: LlamaConfig, lora: LoraConfig,
                     key: jax.Array) -> dict:
    """A ~ N(0, 1/r) (Kaiming-style), B = 0 — the adapter starts as an
    exact no-op so step 0 matches the frozen base model bit-for-bit."""
    if lora.alpha != cfg.lora_alpha:
        # the forward pass and merge_lora read cfg.lora_alpha; a LoraConfig
        # with a different alpha would silently train at the wrong scale
        raise ValueError(
            f"LoraConfig.alpha={lora.alpha} != LlamaConfig.lora_alpha="
            f"{cfg.lora_alpha}; set them consistently (e.g. "
            f"config_for(name, lora_alpha=...))")
    if cfg.moe and any(t in ("w_gate", "w_up", "w_down")
                       for t in lora.targets):
        raise ValueError("LoRA on MoE expert FFNs is not supported; "
                         "use attention targets")
    L, r = cfg.n_layers, lora.rank
    pd = cfg.param_dtype
    layers: dict = {}
    keys = jax.random.split(key, len(lora.targets))
    for k, name in zip(keys, lora.targets):
        if name not in _TARGET_AXES:
            raise ValueError(f"unknown LoRA target {name!r}; "
                             f"have {sorted(_TARGET_AXES)}")
        d_in, d_out = _target_dims(cfg, name)
        layers[name + "_a"] = (
            jax.random.normal(k, (L, d_in, r), jnp.float32)
            * (1.0 / math.sqrt(r))).astype(pd)
        layers[name + "_b"] = jnp.zeros((L, r, d_out), pd)
    return {"layers": layers}


def lora_logical_axes(cfg: LlamaConfig, lora: LoraConfig) -> dict:
    """Adapter sharding mirrors the base weight it augments: A shards its
    input dim like the base in-axis (fsdp), B shards its output dim like
    the base out-axis (tensor) — so TP keeps the low-rank contraction
    local and only the tiny rank dim is replicated."""
    layers: dict = {}
    for name in lora.targets:
        in_ax, out_ax = _TARGET_AXES[name]
        layers[name + "_a"] = ("layers", in_ax, None)
        layers[name + "_b"] = ("layers", None, out_ax)
    return {"layers": layers}


def merge_lora(params: dict, cfg: LlamaConfig) -> dict:
    """Fold adapters into the base weights (for serving/decode paths that
    don't know about LoRA). Returns a NEW params dict without "lora".

    The scale comes from ``cfg.lora_alpha`` — the SAME source the forward
    pass uses — so merged weights always match the trained model. Targets
    are inferred from the adapter keys themselves.
    """
    if "lora" not in params:
        return params
    base_layers = dict(params["layers"])
    lora_layers = params["lora"]["layers"]
    targets = sorted({k[:-2] for k in lora_layers if k.endswith("_a")})
    for name in targets:
        a = lora_layers[name + "_a"].astype(jnp.float32)
        b = lora_layers[name + "_b"].astype(jnp.float32)
        scale = cfg.lora_alpha / a.shape[-1]
        delta = jnp.einsum("lir,lro->lio", a, b) * scale
        base_layers[name] = (base_layers[name].astype(jnp.float32)
                             + delta).astype(base_layers[name].dtype)
    out = {k: v for k, v in params.items() if k != "lora"}
    out["layers"] = base_layers
    return out
