"""RLModule — the jax policy/value network (ref analog:
rllib/core/rl_module/rl_module.py `RLModule`; torch modules there, pure
jax pytrees here so the learner jits end-to-end and shards over the
mesh).

Two architectures share one functional interface (`init_params` /
`forward` / `sample_actions`): an MLP for vector observations and an
IMPALA-style shallow CNN for image observations (ref analog: the conv
nets in rllib/core/rl_module + rllib/models/; Espeholt et al. 2018's
small tower). `forward` dispatches on the params structure, so env
runners and learners are architecture-agnostic.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class MLPModuleConfig:
    observation_size: int
    num_actions: int
    hidden: tuple = (64, 64)


@dataclasses.dataclass(frozen=True)
class CNNModuleConfig:
    """Image policy: conv tower -> dense -> pi/vf heads. obs [B, H, W, C]
    float32 (connectors normalize uint8 pixels upstream)."""
    obs_shape: tuple          # (H, W, C)
    num_actions: int
    # (out_channels, kernel, stride) per conv layer — default is the
    # classic small tower (fits Catch/MinAtar-scale; Atari uses the same
    # shape with larger strides)
    conv: tuple = ((16, 4, 2), (32, 3, 1))
    hidden: int = 128


def make_module_config(observation, num_actions: int, **kw):
    """Pick the architecture from the observation spec: images (H, W, C)
    get the CNN, flat vectors the MLP."""
    if isinstance(observation, tuple) and len(observation) == 3:
        return CNNModuleConfig(obs_shape=tuple(observation),
                               num_actions=num_actions, **kw)
    return MLPModuleConfig(observation_size=int(observation),
                           num_actions=num_actions, **kw)


@jax.tree_util.register_static
@dataclasses.dataclass(frozen=True)
class _ConvMeta:
    """Static (non-leaf) conv metadata riding inside the params pytree:
    tree.map / optimizers never see it, so grads and updates skip it."""
    stride: int


def _head_params(h: int, num_actions: int, k1, k2) -> dict:
    return {
        "pi": {"w": (jax.random.normal(k1, (h, num_actions))
                     * 0.01).astype(jnp.float32),
               "b": jnp.zeros((num_actions,), jnp.float32)},
        "vf": {"w": (jax.random.normal(k2, (h, 1))
                     * 1.0 / math.sqrt(h)).astype(jnp.float32),
               "b": jnp.zeros((1,), jnp.float32)},
    }


def init_params(cfg, key: jax.Array) -> dict:
    """Shared torso + policy and value heads (MLP or CNN by config)."""
    if isinstance(cfg, CNNModuleConfig):
        return _init_cnn(cfg, key)
    dims = (cfg.observation_size,) + tuple(cfg.hidden)
    keys = jax.random.split(key, len(dims) + 1)
    torso = [
        {"w": (jax.random.normal(k, (a, b))
               * math.sqrt(2.0 / a)).astype(jnp.float32),
         "b": jnp.zeros((b,), jnp.float32)}
        for k, a, b in zip(keys, dims[:-1], dims[1:])
    ]
    h = dims[-1]
    return {"torso": torso,
            **_head_params(h, cfg.num_actions, keys[-2], keys[-1])}


def _init_cnn(cfg: CNNModuleConfig, key: jax.Array) -> dict:
    H, W, C = cfg.obs_shape
    keys = iter(jax.random.split(key, len(cfg.conv) + 3))
    conv = []
    in_ch = C
    h, w = H, W
    for out_ch, k, s in cfg.conv:
        fan_in = k * k * in_ch
        conv.append({
            "w": (jax.random.normal(next(keys), (k, k, in_ch, out_ch))
                  * math.sqrt(2.0 / fan_in)).astype(jnp.float32),
            "b": jnp.zeros((out_ch,), jnp.float32),
            "meta": _ConvMeta(s),
        })
        h = -(-h // s)   # SAME padding output size
        w = -(-w // s)
        in_ch = out_ch
    flat = h * w * in_ch
    dense = {"w": (jax.random.normal(next(keys), (flat, cfg.hidden))
                   * math.sqrt(2.0 / flat)).astype(jnp.float32),
             "b": jnp.zeros((cfg.hidden,), jnp.float32)}
    return {"conv": conv, "dense": dense,
            **_head_params(cfg.hidden, cfg.num_actions,
                           next(keys), next(keys))}


def _cnn_torso(params: dict, obs: jax.Array) -> jax.Array:
    x = obs.astype(jnp.float32)
    for layer in params["conv"]:
        s = layer["meta"].stride
        x = jax.lax.conv_general_dilated(
            x, layer["w"], window_strides=(s, s), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        x = jax.nn.relu(x + layer["b"])
    x = x.reshape(x.shape[0], -1)
    return jax.nn.relu(x @ params["dense"]["w"] + params["dense"]["b"])


def forward(params: dict, obs: jax.Array) -> tuple[jax.Array, jax.Array]:
    """-> (action logits [B, A], value [B]). Dispatches on the params
    structure so callers stay architecture-agnostic."""
    if "conv" in params:
        x = _cnn_torso(params, obs)
    else:
        x = obs
        for layer in params["torso"]:
            x = jnp.tanh(x @ layer["w"] + layer["b"])
    logits = x @ params["pi"]["w"] + params["pi"]["b"]
    value = (x @ params["vf"]["w"] + params["vf"]["b"])[:, 0]
    return logits, value


def sample_actions(params: dict, obs: np.ndarray, key: jax.Array):
    """Host-side sampling helper for env runners (CPU jax)."""
    logits, value = forward(params, jnp.asarray(obs))
    action = jax.random.categorical(key, logits, axis=-1)
    logp = jax.nn.log_softmax(logits)[jnp.arange(logits.shape[0]), action]
    return (np.asarray(action), np.asarray(logp), np.asarray(value))
