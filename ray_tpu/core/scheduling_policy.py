"""Cluster scheduling policies, shared by the GCS actor scheduler and the
node managers' task spillback.

Ref analogs: src/ray/raylet/scheduling/policy/ —
hybrid_scheduling_policy.h:85 (top-k critical-resource scoring),
spread_scheduling_policy.cc (round-robin over feasible nodes),
node_affinity / node_label policies, plus the "draining" filter.

Every policy consumes the same view shape the GCS broadcasts
(`get_cluster_resources`): {node_hex: {"total", "available", "alive",
"address", "labels"}}.
"""

from __future__ import annotations

import random
from typing import Any

from ray_tpu.core.common import (NodeAffinitySchedulingStrategy,
                                 NodeLabelSchedulingStrategy)

# Hybrid policy knobs (ref: RAY_scheduler_top_k_fraction /
# scheduler_spread_threshold in ray_config_def.h)
TOP_K = 3
SPREAD_THRESHOLD = 0.5


def node_schedulable(view: dict,
                     topology: dict[str, str] | None = None) -> bool:
    """THE shared liveness/label filter every policy (and the placement
    plane) routes through: a node takes new work only if it is alive and
    not draining, and — when a topology constraint is given — its
    topology labels (``ici-slice`` / ``dcn-locality``, advertised by the
    node manager; see core/placement.py) match exactly."""
    if not view.get("alive"):
        return False
    labels = view.get("labels") or {}
    if labels.get("draining"):
        return False
    if topology:
        for k, v in topology.items():
            if labels.get(k) != v:
                return False
    return True


def feasible(view: dict, demand: dict[str, float],
             topology: dict[str, str] | None = None) -> bool:
    if not node_schedulable(view, topology):
        return False
    avail = view.get("available", {})
    return all(avail.get(r, 0.0) >= amt - 1e-9 for r, amt in demand.items())


def capacity_feasible(view: dict, demand: dict[str, float],
                      topology: dict[str, str] | None = None) -> bool:
    """Could this node EVER run the demand (total capacity, ignoring
    current usage)? Used to route constrained tasks to a busy-but-matching
    node's lease queue instead of declaring them infeasible."""
    if not node_schedulable(view, topology):
        return False
    total = view.get("total", {})
    return all(total.get(r, 0.0) >= amt - 1e-9 for r, amt in demand.items())


def critical_utilization(view: dict, demand: dict[str, float]) -> float:
    """Max over resources of (used + demand) / total AFTER placing the
    demand — the reference's 'critical resource utilization' score."""
    total = view.get("total", {})
    avail = view.get("available", {})
    worst = 0.0
    for r, cap in total.items():
        if cap <= 0:
            continue
        used = cap - avail.get(r, 0.0) + demand.get(r, 0.0)
        worst = max(worst, used / cap)
    return worst


def _label_groups(candidates: list[tuple[str, dict]],
                  strategy: NodeLabelSchedulingStrategy | None):
    """Apply hard label filtering; return (preferred, rest) by soft
    labels."""
    if strategy is None:
        return candidates, []
    if strategy.hard:
        candidates = [
            (nid, v) for nid, v in candidates
            if all(v.get("labels", {}).get(k) == val
                   for k, val in strategy.hard.items())]
    if not strategy.soft:
        return candidates, []
    preferred = [
        (nid, v) for nid, v in candidates
        if all(v.get("labels", {}).get(k) == val
               for k, val in strategy.soft.items())]
    rest = [c for c in candidates if c not in preferred]
    return preferred, rest


def hybrid_pick(views: dict[str, dict], demand: dict[str, float],
                *, exclude: set[str] | None = None,
                label_strategy: NodeLabelSchedulingStrategy | None = None,
                top_k: int = TOP_K, rng: random.Random | None = None,
                by_capacity: bool = False) -> str | None:
    """The default policy (ref hybrid_scheduling_policy.h:85): among
    feasible nodes, prefer those whose post-placement critical-resource
    utilization stays under SPREAD_THRESHOLD (packing up to the threshold,
    spreading past it), then pick uniformly among the best `top_k` to
    avoid herd behavior when many callers schedule concurrently."""
    rng = rng or random
    fit = capacity_feasible if by_capacity else feasible
    cands = [(nid, v) for nid, v in views.items()
             if (exclude is None or nid not in exclude)
             and fit(v, demand)]
    for group in _label_groups(cands, label_strategy):
        if not group:
            continue
        # under-threshold nodes TIE (score 0) and pack in stable id order
        # — the reference's semantics: pack until the threshold, spread by
        # utilization past it (hybrid_scheduling_policy.h:85)
        scored = sorted(
            ((critical_utilization(v, demand), nid) for nid, v in group),
            key=lambda t: ((t[0] if t[0] >= SPREAD_THRESHOLD else 0.0),
                           t[1]))
        top = scored[:max(1, top_k)]
        return rng.choice(top)[1]
    return None


def spread_pick(views: dict[str, dict], demand: dict[str, float],
                counter: int, *,
                label_strategy: NodeLabelSchedulingStrategy | None = None,
                by_capacity: bool = False) -> str | None:
    """SPREAD strategy: round-robin over feasible nodes in stable (id)
    order — `counter` is the caller's monotonically increasing pick
    count (ref: spread_scheduling_policy.cc)."""
    fit = capacity_feasible if by_capacity else feasible
    cands = [(nid, v) for nid, v in sorted(views.items())
             if fit(v, demand)]
    for group in _label_groups(cands, label_strategy):
        if group:
            return group[counter % len(group)][0]
    return None


def pick_node(views: dict[str, dict], demand: dict[str, float],
              strategy: Any = None, *, exclude: set[str] | None = None,
              spread_counter: int = 0,
              rng: random.Random | None = None,
              by_capacity: bool = False) -> str | None:
    """Strategy dispatch. Returns a node id hex or None.

    strategy: None (hybrid) | "SPREAD" | NodeAffinitySchedulingStrategy |
    NodeLabelSchedulingStrategy. PG strategies never reach here — their
    demands are rewritten onto reserved bundle resources upstream
    (core_worker._demand_for)."""
    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        nid = strategy.node_id.hex()
        view = views.get(nid)
        if view is not None and feasible(view, demand):
            return nid
        if not strategy.soft:
            return None
        return hybrid_pick(views, demand, exclude=exclude, rng=rng)
    label = strategy if isinstance(strategy,
                                   NodeLabelSchedulingStrategy) else None
    if strategy == "SPREAD":
        return spread_pick(views, demand, spread_counter,
                           label_strategy=label)
    return hybrid_pick(views, demand, exclude=exclude,
                       label_strategy=label, rng=rng,
                       by_capacity=by_capacity)
