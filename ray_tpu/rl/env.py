"""Vectorized environments (ref analog: rllib's gymnasium vector envs in
env/single_agent_env_runner.py:64 — the env API is gymnasium-shaped so
real gym envs drop in, but CartPole ships built-in so the library has no
gym dependency)."""

from __future__ import annotations

import numpy as np


class VectorEnv:
    """num_envs independent environments stepped in lockstep with
    auto-reset (done envs restart immediately, final obs in info)."""

    num_envs: int
    observation_size: int
    num_actions: int

    def reset(self, seed: int | None = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, actions: np.ndarray):
        """-> (obs [n, obs_size], reward [n], terminated [n], truncated [n],
        final_obs [n, obs_size]).

        `obs` is post-auto-reset; `final_obs` is the pre-reset observation
        of each env (== obs where not done) so truncated episodes can be
        bootstrapped with the critic's value of the true final state."""
        raise NotImplementedError


class CartPoleVectorEnv(VectorEnv):
    """Classic cart-pole balancing, vectorized in numpy (dynamics match
    gymnasium's CartPole-v1: max 500 steps, +1 reward per step)."""

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LEN = 0.5
    FORCE = 10.0
    DT = 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500

    def __init__(self, num_envs: int = 8, seed: int = 0):
        self.num_envs = num_envs
        self.observation_size = 4
        self.num_actions = 2
        self._rng = np.random.RandomState(seed)
        self._state = np.zeros((num_envs, 4), np.float64)
        self._steps = np.zeros(num_envs, np.int64)

    def reset(self, seed: int | None = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._state = self._rng.uniform(-0.05, 0.05, (self.num_envs, 4))
        self._steps[:] = 0
        return self._state.astype(np.float32)

    def _reset_envs(self, mask: np.ndarray):
        n = int(mask.sum())
        if n:
            self._state[mask] = self._rng.uniform(-0.05, 0.05, (n, 4))
            self._steps[mask] = 0

    def step(self, actions: np.ndarray):
        x, x_dot, theta, theta_dot = self._state.T
        force = np.where(actions == 1, self.FORCE, -self.FORCE)
        cos_t, sin_t = np.cos(theta), np.sin(theta)
        total_mass = self.CART_MASS + self.POLE_MASS
        pole_ml = self.POLE_MASS * self.POLE_HALF_LEN
        temp = (force + pole_ml * theta_dot ** 2 * sin_t) / total_mass
        theta_acc = (self.GRAVITY * sin_t - cos_t * temp) / (
            self.POLE_HALF_LEN * (4.0 / 3.0
                                  - self.POLE_MASS * cos_t ** 2 / total_mass))
        x_acc = temp - pole_ml * theta_acc * cos_t / total_mass
        x = x + self.DT * x_dot
        x_dot = x_dot + self.DT * x_acc
        theta = theta + self.DT * theta_dot
        theta_dot = theta_dot + self.DT * theta_acc
        self._state = np.stack([x, x_dot, theta, theta_dot], axis=1)
        self._steps += 1

        terminated = ((np.abs(x) > self.X_LIMIT)
                      | (np.abs(theta) > self.THETA_LIMIT))
        truncated = (self._steps >= self.MAX_STEPS) & ~terminated
        reward = np.ones(self.num_envs, np.float32)
        final_obs = self._state.astype(np.float32)
        self._reset_envs(terminated | truncated)
        return (self._state.astype(np.float32), reward,
                terminated, truncated, final_obs)


_ENV_REGISTRY = {"CartPole-v1": CartPoleVectorEnv}


def register_env(name: str, creator):
    """creator(num_envs, seed) -> VectorEnv (ref analog: tune.register_env)."""
    _ENV_REGISTRY[name] = creator


def make_vector_env(name: str, num_envs: int, seed: int = 0) -> VectorEnv:
    if name not in _ENV_REGISTRY:
        raise KeyError(f"unknown env {name!r}; register_env() it first")
    return _ENV_REGISTRY[name](num_envs, seed)
