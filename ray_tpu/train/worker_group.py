"""WorkerGroup: the gang of training actors (ref analogs:
train/_internal/worker_group.py:102 `WorkerGroup`/`RayTrainWorker:19`,
train/v2/_internal/execution/worker_group/worker_group.py:97).

TPU-first: one worker per TPU host, gang-placed via a placement group
(STRICT_PACK within a slice); worker 0 is the mesh coordinator. The
worker actor is threaded (max_concurrency=2) so the controller can drain
results while the user loop runs.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional

import cloudpickle

import ray_tpu as rt
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train import session


class TrainWorker:
    """Hosts the user's train_loop_per_worker (ref: RayTrainWorker)."""

    def setup(self, rank: int, world_size: int, experiment_path: str,
              experiment_name: str, latest_checkpoint: Optional[str],
              mesh_axes: Optional[dict], group_name: str,
              ingest_spec=None, run_id: Optional[str] = None) -> dict:
        from ray_tpu.util import collective

        self._group_name = group_name
        node_id = ""
        try:
            from ray_tpu.core.object_ref import get_core_worker

            cw = get_core_worker()
            if cw is not None:
                node_id = cw.node_id.hex()
        except Exception:
            pass
        ctx = session.TrainContext(rank, world_size, experiment_path,
                                   experiment_name, latest_checkpoint,
                                   mesh_axes, ingest_spec=ingest_spec,
                                   run_id=run_id, node_id=node_id)
        session.set_context(ctx)
        self._ctx = ctx
        # Host-plane communicator: barriers, coordinator-address exchange
        # (the jax.distributed bootstrap analog of NCCLUniqueId rendezvous).
        if world_size > 1:
            collective.init_collective_group(world_size, rank,
                                             group_name=group_name)
        return {"rank": rank, "pid": os.getpid()}

    def run(self, fn_blob: bytes, config: Optional[dict]) -> dict:
        fn = cloudpickle.loads(fn_blob)
        try:
            if _wants_config(fn):
                fn(config or {})
            elif config:
                raise TypeError(
                    f"train loop {getattr(fn, '__name__', fn)!r} takes "
                    "no config parameter but a non-empty "
                    "train_loop_config was given — it would be silently "
                    "ignored")
            else:
                fn()
        finally:
            # drain buffered step records before the actor can be torn
            # down — the run's tail must reach the GCS train manager
            self._ctx.close_telemetry()
        return {"rank": self._ctx.rank, "status": "finished"}

    def drain_results(self) -> list[dict]:
        return self._ctx.drain_results()

    def barrier(self):
        from ray_tpu.util import collective

        if self._ctx.world_size > 1:
            collective.barrier(group_name=self._group_name)
        return True

    def teardown(self):
        from ray_tpu.util import collective

        if self._ctx.world_size > 1:
            try:
                collective.destroy_collective_group(self._group_name)
            except Exception:
                pass
        return True


def actor_options_from_resources(res: dict, *,
                                 max_concurrency: int = 2) -> dict:
    """Map a resources dict ({'CPU': 1, 'TPU': 4, 'memory': ..., custom})
    to rt.remote actor options. 'memory' is accounted per-node, not
    scheduled as a custom resource."""
    opts: dict[str, Any] = {"max_concurrency": max_concurrency,
                            "num_cpus": res.get("CPU", 1)}
    if res.get("TPU"):
        opts["num_tpus"] = res["TPU"]
    if res.get("memory"):
        opts["memory"] = res["memory"]
    extra = {k: v for k, v in res.items()
             if k not in ("CPU", "TPU", "memory")}
    if extra:
        opts["resources"] = extra
    return opts


def _wants_config(fn: Callable) -> bool:
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    return len(sig.parameters) > 0


class WorkerGroup:
    def __init__(self, scaling: ScalingConfig, run_config: RunConfig,
                 experiment_path: str, experiment_name: str,
                 group_seq: int, run_id: Optional[str] = None):
        self.scaling = scaling
        self.run_config = run_config
        self.experiment_path = experiment_path
        self.experiment_name = experiment_name
        self.group_seq = group_seq
        self.run_id = run_id
        self.workers: list = []
        self.pg = None

    def start(self, latest_checkpoint: Optional[str]):
        n = self.scaling.num_workers
        actor_cls = rt.remote(TrainWorker)
        if n > 1:
            self.pg = self._reserve_gang()
        res = self.scaling.worker_resources()
        group_name = f"train-{self.experiment_name}-{self.group_seq}"
        self.workers = []
        for i in range(n):
            o = actor_options_from_resources(res)
            if self.pg is not None:
                o["scheduling_strategy"] = self.pg.bundle_strategy(i)
            self.workers.append(actor_cls.options(**o).remote())
        setup_refs = [
            w.setup.remote(i, n, self.experiment_path, self.experiment_name,
                           latest_checkpoint, self.scaling.mesh, group_name,
                           self.scaling.ingest, self.run_id)
            for i, w in enumerate(self.workers)]
        return rt.get(setup_refs, timeout=120)

    def _reserve_gang(self):
        """Gang-reserve the workers through the placement plane. TPU
        groups (use_tpu or a topology hint) first try SLICE_PACK — the
        whole gang inside one ICI slice, so collectives stay on-mesh and
        DAG edges to these workers compile co-located — and fall back to
        the configured strategy when no single slice fits the gang
        (e.g. an unlabeled dev cluster smaller than the request)."""
        bundles = self.scaling.bundles()
        if (self.scaling.use_tpu or self.scaling.topology) and \
                self.scaling.placement_strategy in ("PACK",
                                                    "SLICE_PACK"):
            try:
                return rt.placement_group(bundles,
                                          strategy="SLICE_PACK",
                                          timeout=30.0)
            except TimeoutError:
                pass
        return rt.placement_group(
            bundles, strategy=self.scaling.placement_strategy)

    def run_async(self, train_fn: Callable, config: Optional[dict]):
        from ray_tpu._internal.serialization import dumps_code

        blob = dumps_code(train_fn)
        return [w.run.remote(blob, config) for w in self.workers]

    def drain_results(self) -> list[dict]:
        out: list[dict] = []
        for ref in [w.drain_results.remote() for w in self.workers]:
            try:
                # results are small metric dicts; a submit to a DEAD
                # worker never resolves, so a short timeout bounds the
                # failure-recovery stall (storage markers cover anything
                # undrained — controller._recover_checkpoints_from_storage)
                out.extend(rt.get(ref, timeout=10))
            except Exception:
                pass  # dead worker: run-ref error surface handles it
        return out

    def shutdown(self):
        for w in self.workers:
            try:
                rt.kill(w)
            except Exception:
                pass
        if self.pg is not None:
            try:
                rt.remove_placement_group(self.pg)
            except Exception:
                pass
        self.workers = []
        self.pg = None
