"""CoreWorker — the per-process runtime (driver and workers alike).

Ref analog: src/ray/core_worker/core_worker.h:166 plus its transport stack
(normal_task_submitter.h:108, actor_task_submitter.h:75, scheduling
queues), task_manager.h:212 (retries), memory_store.h:42.

Threading model: user code runs on its own threads and calls the sync API,
which hops onto a dedicated asyncio IO loop (EventLoopThread — the analog
of the C++ io_service threads). Task execution happens on executor
threads; async actors get their own asyncio loop.
"""

from __future__ import annotations

import asyncio
import collections
import os
import socket
import sys
import threading
import time
import traceback
import weakref
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any

import cloudpickle

from ray_tpu._internal.config import get_config
from ray_tpu._internal.ids import (ActorID, JobID, NodeID, ObjectID, TaskID,
                                   WorkerID)
from ray_tpu._internal.logging_utils import setup_logger
from ray_tpu._internal.rpc import (Connection, ConnectionLost, RemoteError,
                                   RpcError, RpcServer, EventLoopThread,
                                   connect)
from ray_tpu._internal.serialization import (chunks_to_bytes, deserialize,
                                             serialize, serialize_to_bytes,
                                             serialized_size)
from ray_tpu.core.common import (ActorDiedError, ActorState, Address,
                                 GetTimeoutError,
                                 NodeAffinitySchedulingStrategy,
                                 NodeLabelSchedulingStrategy,
                                 ObjectLostError, ObjectMeta,
                                 PlacementGroupSchedulingStrategy,
                                 TaskCancelledError, TaskError, TaskSpec,
                                 WorkerCrashedError, WorkerInfo)
from ray_tpu.core.gcs import CH_ACTOR, CH_NODE, GcsClient
from ray_tpu.core.object_ref import ObjectRef, set_core_worker
from ray_tpu.core.device_objects import (DeviceObjectStore,
                                          deserialize_array,
                                          is_device_value,
                                          serialize_array)
from ray_tpu.core.object_store import MemoryStore, make_shm_store
from ray_tpu.core.reference_counter import ReferenceCounter

logger = setup_logger("core_worker")

_TASK_PUSH_TIMEOUT = 7 * 24 * 3600.0


def _dumps_code(fn) -> bytes:
    from ray_tpu._internal.serialization import dumps_code

    return dumps_code(fn)


def _trace_carrier():
    """Active OTel span context for TaskSpec.trace_ctx (None when
    tracing is off — the common, zero-overhead case)."""
    from ray_tpu._internal import otel

    if not otel.tracing_enabled():
        return None
    return otel.current_context_carrier()


@dataclass
class RefArg:
    """Marker for an ObjectRef positioned as a top-level task argument."""
    object_id: ObjectID
    owner: WorkerInfo | None


@dataclass
class _PendingTask:
    spec: TaskSpec
    retries_left: int
    pinned: list[ObjectID] = field(default_factory=list)
    done: bool = False
    cancelled: bool = False
    running_on: Any = None     # WorkerInfo while pushed to a worker
    lease_waiter: Any = None   # (pool, fut) while queued for a lease


@dataclass
class _LeasePool:
    """Per-scheduling-key lease pipeline state (ref analog: the
    per-SchedulingKey entry in normal_task_submitter.h:108): tasks
    waiting for a worker, idle leased workers kept warm, and the number
    of outstanding lease requests against the cluster."""
    idle: list = field(default_factory=list)       # [(winfo, token, nm_addr)]
    waiters: list = field(default_factory=list)    # [Future]
    inflight: int = 0


class _ExecutionContext(threading.local):
    task_id: TaskID | None = None
    job_id: JobID | None = None     # owning job of the executing task


class _ShmGetPin:
    """Pin bookkeeping for ONE zero-copy get: the store's get-ref is held
    while ``count`` > 0. Slots: one per live out-of-band buffer wrapper
    (the numpy views handed to pickle — reconstructed arrays keep them
    alive as their buffer base) plus, optionally, one for the local
    ObjectRef(s), dropped when the last counted ref dies.

    Reentrancy design (a GC can fire ObjectRef.__del__ at ANY allocation,
    including inside store internals): wrapper finalizers and the
    ref-drop path only ever append to the owner's event deque
    (reentrancy-safe, lock-free); every count mutation after seal() and
    every ``store.release`` happens inside CoreWorker._drain_pin_events,
    whose locks are all acquired non-blocking. Wrappers are held by
    STRONG refs until seal() arms their finalizers, so no event for this
    pin can exist before its count is final.
    Ref analog: plasma's client-side object refcount, which keeps a
    Get() buffer mapped until the last PlasmaBuffer is destroyed."""

    __slots__ = ("oid", "_events", "_count", "_wrappers")

    def __init__(self, oid: ObjectID, events: collections.deque):
        self.oid = oid
        self._events = events
        self._count = 1          # guard until seal()/abort()
        self._wrappers: list = []

    @property
    def n_wrappers(self) -> int:
        return len(self._wrappers)

    def wrap(self, view: memoryview):
        """buffer_wrapper for deserialize(): interpose a weakref-able
        holder between pickle and the raw shm view."""
        import numpy as np

        w = np.frombuffer(view, dtype=np.uint8)
        self._wrappers.append(w)  # strong ref: finalizer armed at seal()
        return w

    def seal(self, ref_held: bool) -> bool:
        """Fix the slot count and arm the wrapper finalizers. True =>
        nothing pins the mapping (no views, no counted ref): the caller
        must queue this pin on the event deque, whose drain drops the
        remaining guard slot and releases the store's get-ref."""
        wrappers, self._wrappers = self._wrappers, []
        self._count = len(wrappers) + (1 if ref_held else 0)
        if self._count == 0:
            self._count = 1  # consumed by the caller's queued event
            return True
        for w in wrappers:
            weakref.finalize(w, self._events.append, self)
        return False

    def abort(self):
        """Deserialize failed: drop the wrapper refs and queue one
        release for the store's get-ref."""
        self._wrappers = []
        self._count = 1
        self._events.append(self)

    def dec(self) -> bool:
        """One slot died. Called ONLY under the owner's drain lock (the
        single consumer), so no pin-level lock is needed. True => last
        slot: the drain releases the store's get-ref."""
        self._count -= 1
        return self._count == 0


class CoreWorker:
    def __init__(self, mode: str, job_id: JobID, gcs_address: Address,
                 node_address: Address, node_id: NodeID):
        assert mode in ("driver", "worker")
        self.mode = mode
        self.job_id = job_id
        self.gcs_address = gcs_address
        self.node_address = node_address
        self.node_id = node_id
        self.worker_id = WorkerID.random()
        self.io = EventLoopThread()
        self.server = RpcServer()
        self.server.add_service(self)
        self.memory_store = MemoryStore(self.io.loop)
        self.shm = make_shm_store(node_id)
        # device-resident objects held by THIS worker process
        # (payloads in the local jax client; see device_objects.py)
        self.device_store = DeviceObjectStore()
        self.object_meta: dict[ObjectID, ObjectMeta] = {}
        self._object_events: dict[ObjectID, asyncio.Event] = {}
        self.pending_tasks: dict[TaskID, _PendingTask] = {}
        self._return_to_task: dict[ObjectID, TaskID] = {}
        # streaming-generator tasks we own (ref: generator_waiter.cc)
        self._streams: dict[TaskID, Any] = {}
        # zero-copy get pins: oid -> pins holding a live ref-holder slot;
        # _pin_events queues slot-death notifications (finalizer-safe)
        self._shm_pins: dict[ObjectID, list[_ShmGetPin]] = {}
        self._pin_lock = threading.Lock()
        self._pin_events: collections.deque = collections.deque()
        self._pin_drain_lock = threading.Lock()
        self.reference_counter = ReferenceCounter(
            is_owner=self._owns, free_fn=self._free_object,
            notify_owner_fn=self._notify_owner_refcount,
            release_local_fn=self._release_shm_pins)
        self.root_task_id = TaskID.for_normal_task(job_id)
        self._exec_ctx = _ExecutionContext()
        self._put_index = 0
        self._put_lock = threading.Lock()
        self._conns: dict[str, Connection] = {}
        self._conn_locks: dict[str, asyncio.Lock] = {}
        self._node_addrs: dict[NodeID, Address] = {}
        self._dead_nodes: set[NodeID] = set()
        self._lease_cache: dict[tuple, _LeasePool] = {}
        self._actor_submitters: dict[ActorID, _ActorTaskSubmitter] = {}
        # worker-mode execution state
        self.executor = ThreadPoolExecutor(max_workers=1,
                                           thread_name_prefix="rayt-exec")
        self._running_normal_task: TaskID | None = None
        self._exec_thread_ident: int | None = None
        self.actor_instance = None
        self.actor_id: ActorID | None = None
        self._actor_async_loop: EventLoopThread | None = None
        self._actor_seq_state: dict[str, dict] = {}
        self._shutdown = False
        # approximate in-flight count backing the queue-depth gauge
        # (racy += is fine for telemetry; never used for control flow)
        self._inflight_tasks = 0
        # every fire-and-forget coroutine goes through _spawn (on-loop) or
        # _spawn_from_thread (foreign threads) so shutdown can
        # cancel-and-await them: an abandoned pending task at loop
        # teardown prints "Task was destroyed but it is pending!" and can
        # mask a real hang. _closing gates late spawns during the sweep.
        self._bg_tasks: set[asyncio.Task] = set()
        self._closing = False
        self.gcs: GcsClient | None = None
        self.node_conn: Connection | None = None
        self.worker_info: WorkerInfo | None = None
        # task-event tracing (ref: task_event_buffer.cc); flushed to the
        # GCS ring by _task_event_flush_loop, rendered by `rayt timeline`
        from ray_tpu._internal.tracing import TaskEventBuffer

        self.task_events = TaskEventBuffer(self.worker_id.hex(),
                                           self.node_id.hex())

    def _emit_task_event(self, spec: TaskSpec, state: str, *,
                         error: dict | None = None):
        """Record one lifecycle state transition for `spec` (ref:
        task_event_buffer.cc RecordTaskStatusEvent). Never fails the
        caller — telemetry must not break submission/execution. The
        attempt number rides the spec (set by the submitter before each
        dispatch), so worker-side events carry it too."""
        try:
            if spec.is_actor_creation:
                kind = "actor_creation"
            elif spec.actor_id is not None:
                kind = "actor_task"
            else:
                kind = "task"
            self.task_events.record_transition(
                task_id=spec.task_id.hex(),
                name=spec.name or spec.method_name or "task",
                kind=kind, state=state, job_id=spec.job_id.hex(),
                actor_id=spec.actor_id.hex() if spec.actor_id else "",
                attempt=getattr(spec, "attempt", 0), error=error)
        except Exception:
            pass

    def _spawn(self, coro) -> "asyncio.Task | None":
        """ensure_future + lifetime tracking (must run on the IO loop).
        During the shutdown sweep new background work is dropped — a task
        scheduled after the cancel-and-await would be destroyed pending."""
        if self._closing:
            coro.close()
            return None
        t = asyncio.ensure_future(coro)
        self._bg_tasks.add(t)
        t.add_done_callback(self._bg_tasks.discard)
        return t

    def _spawn_from_thread(self, coro) -> None:
        """Thread-safe fire-and-forget onto the IO loop, shutdown-tracked
        (the raw io.spawn future is untracked — fine only when the caller
        awaits it)."""
        if self._closing:
            # io.stop() halts the loop without closing it, so a
            # post-shutdown call_soon_threadsafe would "succeed" and the
            # callback never run, leaking a never-awaited coroutine
            coro.close()
            return
        try:
            self.io.loop.call_soon_threadsafe(self._spawn, coro)
        except RuntimeError:  # loop already closed
            coro.close()

    # ------------------------------------------------------------ bootstrap
    def connect_cluster(self):
        self.io.run(self._async_connect())
        set_core_worker(self)

    async def _async_connect(self):
        host = "127.0.0.1"
        port = await self.server.start(host, 0)
        self.worker_info = WorkerInfo(self.worker_id, self.node_id,
                                      Address(host, port))
        self.gcs = await GcsClient.connect(self.gcs_address)
        self.node_conn = await connect(self.node_address.host,
                                       self.node_address.port)
        for n in await self.gcs.get_all_nodes():
            self._node_addrs[n.node_id] = n.address

        def on_node_event(msg):
            info = msg["node"]
            if msg["event"] == "added":
                self._node_addrs[info.node_id] = info.address
                self._dead_nodes.discard(info.node_id)
            elif msg["event"] == "removed":
                # Prune the dead node from location metadata so gets stop
                # trying to pull from it; objects whose only copies lived
                # there become candidates for lineage reconstruction (ref:
                # object_recovery_manager.h:38).
                self._dead_nodes.add(info.node_id)
                self._node_addrs.pop(info.node_id, None)
                for meta in self.object_meta.values():
                    if info.node_id in meta.node_ids:
                        meta.node_ids.remove(info.node_id)

        await self.gcs.subscribe(CH_NODE, on_node_event)

        def on_actor_event(info):
            sub = self._actor_submitters.get(info.actor_id)
            if sub is not None:
                self._spawn(sub.on_actor_update(info))

        await self.gcs.subscribe(CH_ACTOR, on_actor_event)
        self._spawn(self._task_event_flush_loop())
        if self.mode == "worker":
            await self.node_conn.call(
                "register_worker", (self.worker_info, os.getpid()))

    def shutdown(self):
        if self._shutdown:
            return
        self._shutdown = True
        set_core_worker(None)
        try:
            self.io.run(self._async_shutdown(), timeout=5)
        except Exception:
            pass
        self.executor.shutdown(wait=False)
        self.io.stop()

    async def _async_shutdown(self):
        # stop background work BEFORE tearing down connections: a lease
        # expiry or flush tick racing the close would error, and any task
        # still pending when the loop stops prints "Task was destroyed".
        # _closing first, so a cancelled task's cleanup can't re-spawn.
        self._closing = True
        for t in list(self._bg_tasks):
            t.cancel()
        if self._bg_tasks:
            await asyncio.gather(*list(self._bg_tasks),
                                 return_exceptions=True)
        self._bg_tasks.clear()
        for pool in self._lease_cache.values():
            for winfo, token, nm_addr, _ in pool.idle:
                await self._release_lease(winfo, token, nm_addr,
                                          reusable=False)
            pool.idle.clear()
        self._lease_cache.clear()
        for conn in self._conns.values():
            await conn.close()
        if self.gcs is not None:
            await self.gcs.close()
        if self.node_conn is not None:
            await self.node_conn.close()
        await self.server.stop()
        self.shm.close()

    # ---------------------------------------------------------- connections
    async def _conn_to(self, address: Address) -> Connection:
        key = address.key()
        lock = self._conn_locks.setdefault(key, asyncio.Lock())
        async with lock:
            conn = self._conns.get(key)
            if conn is None or conn.closed:
                conn = await connect(address.host, address.port)
                self._conns[key] = conn
            return conn

    # ------------------------------------------------------------ ownership
    def _owns(self, oid: ObjectID) -> bool:
        meta = self.object_meta.get(oid)
        if meta is not None or self.memory_store.contains(oid):
            return True
        return oid in self._return_to_task

    def current_task_id(self) -> TaskID:
        return self._exec_ctx.task_id or self.root_task_id

    def _free_shm_copies(self, meta: ObjectMeta):
        """Tell every node holding a shm copy of the object to drop its
        pin (ref: the free_objects path through the local object
        manager). Fire-and-forget from any thread."""
        oid = meta.object_id

        async def _free():
            try:
                for nid in meta.node_ids:
                    if nid == self.node_id:
                        await self.node_conn.call("free_object", oid)
                    else:
                        addr = self._node_addrs.get(nid)
                        if addr is not None:
                            c = await self._conn_to(addr)
                            await c.call("free_object", oid)
            except Exception:
                pass
        try:
            self._spawn_from_thread(_free())
        except Exception:
            pass

    # ------------------------------------------------- zero-copy get pins
    def _release_shm_pins(self, oid: ObjectID):
        """The last counted local ref to oid died: queue a sentinel that
        drops the registered pin's ref-holder slot (live buffer views
        keep their own slots, so the mapping stays pinned until they die
        too). This runs from ObjectRef.__del__ — i.e. potentially inside
        a GC triggered ANYWHERE, including while this very thread holds
        the pin or store locks — so it must only append + try-drain."""
        self._pin_events.append(oid)
        self._drain_pin_events()

    def _drain_pin_events(self):
        """Process queued pin-slot deaths and release store get-refs.
        Single-consumer, and every lock here is acquired NON-blocking: a
        reentrant call (a GC collecting an ObjectRef while this thread
        is inside the pin registration block or store internals) bails
        out or requeues, leaving its events for the active drainer / the
        periodic flush loop. Events are either _ShmGetPin (one slot
        died) or an ObjectID sentinel (ref-holder slot drop)."""
        if not self._pin_drain_lock.acquire(blocking=False):
            return
        try:
            requeue = []
            while True:
                try:
                    ev = self._pin_events.popleft()
                except IndexError:
                    break
                if isinstance(ev, _ShmGetPin):
                    pins = (ev,)
                elif self._pin_lock.acquire(blocking=False):
                    try:
                        pins = tuple(self._shm_pins.pop(ev, ()))
                    finally:
                        self._pin_lock.release()
                else:
                    requeue.append(ev)  # registration in progress: later
                    continue
                for pin in pins:
                    if pin.dec():
                        try:
                            self.shm.release(pin.oid)
                        except Exception:
                            pass
            self._pin_events.extend(requeue)
        finally:
            self._pin_drain_lock.release()

    def _free_object(self, oid: ObjectID):
        self._release_shm_pins(oid)
        self.memory_store.delete(oid)
        meta = self.object_meta.pop(oid, None)
        # Lineage retention (ref: task_manager.h:212 lineage pinning): the
        # VALUE is freed, but a reconstructable task's spec is kept so a
        # downstream task that lost its own output can transitively
        # re-execute this producer. Bounded by max_lineage_entries.
        tid = self._return_to_task.get(oid)
        keep_lineage = False
        if tid is not None:
            pt = self.pending_tasks.get(tid)
            keep_lineage = (
                pt is not None and pt.spec.actor_id is None
                and pt.spec.max_retries > 0
                and len(self.pending_tasks)
                < get_config().max_lineage_entries)
        if not keep_lineage:
            self._return_to_task.pop(oid, None)
            if tid is not None:
                pt = self.pending_tasks.get(tid)
                if pt is not None and pt.done:
                    self.pending_tasks.pop(tid, None)
        if meta is not None and meta.in_shm:
            self._free_shm_copies(meta)
        if meta is not None and meta.in_device:
            self.device_store.delete(oid)
            holder = meta.holder
            if holder is not None and holder.worker_id != self.worker_id:
                async def _free_dev():
                    try:
                        c = await self._conn_to(holder.address)
                        await c.call("free_device_object", oid)
                    except Exception:
                        pass
                try:
                    self._spawn_from_thread(_free_dev())
                except Exception:
                    pass

    def _notify_owner_refcount(self, oid: ObjectID, owner, kind: str):
        if owner is None:
            return

        async def _send():
            try:
                conn = await self._conn_to(owner.address)
                await conn.notify(kind, (oid, self.worker_info.address.key()))
            except Exception:
                pass
        try:
            self._spawn_from_thread(_send())
        except Exception:
            pass

    def rpc_add_borrower(self, conn, arg):
        oid, key = arg
        self.reference_counter.add_borrower(oid, key)

    def rpc_remove_borrower(self, conn, arg):
        oid, key = arg
        self.reference_counter.remove_borrower(oid, key)

    # ------------------------------------------------- shm create helpers
    def _shm_create_blocking(self, oid: ObjectID, chunks: list, size: int):
        """Create+seal a serialize() chunk list holding the create-ref
        (so LRU can't evict before the node manager pins) — each chunk is
        written straight into the segment, the payload is never joined
        host-side; on arena-OOM ask the node manager to spill and retry
        (ref: plasma create-request queue)."""
        for _ in range(100):
            try:
                self.shm.create_from_chunks(oid, chunks, size, hold=True)
                return
            except MemoryError:
                try:
                    freed = self.io.run(self.node_conn.call(
                        "spill_now", size), timeout=60)
                except Exception:
                    freed = 0
                if not freed:
                    time.sleep(0.1)
        raise MemoryError(
            f"object store full: could not place {size} bytes")

    async def _shm_create_async(self, oid: ObjectID, chunks: list,
                                size: int):
        for _ in range(100):
            try:
                self.shm.create_from_chunks(oid, chunks, size, hold=True)
                return
            except MemoryError:
                try:
                    freed = await self.node_conn.call("spill_now", size)
                except Exception:
                    freed = 0
                if not freed:
                    await asyncio.sleep(0.1)
        raise MemoryError(
            f"object store full: could not place {size} bytes")

    def _release_create_ref(self, oid: ObjectID):
        release = getattr(self.shm, "release_create_ref", None)
        if release is not None:
            try:
                release(oid)
            except Exception:
                pass

    # ---------------------------------------------------------------- put
    def put(self, value: Any) -> ObjectRef:
        with self._put_lock:
            self._put_index += 1
            idx = self._put_index
        oid = ObjectID.for_put(self.current_task_id(), idx)
        self._store_owned_value(oid, value)
        return ObjectRef(oid, self.worker_info)

    def put_device(self, value: Any) -> ObjectRef:
        """Store a jax.Array as a DEVICE-RESIDENT object: the payload
        stays in this process's device memory (HBM on TPU); only
        metadata reaches the object directory. get() in this process
        returns the same jax.Array; get() elsewhere host-stages the raw
        shard bytes over RPC — never a pickle of the device buffer
        (ref analog: torch_tensor_nccl_channel.py device channels)."""
        if not is_device_value(value):
            raise TypeError(
                f"put_device expects a jax.Array, got {type(value)}")
        with self._put_lock:
            self._put_index += 1
            idx = self._put_index
        oid = ObjectID.for_put(self.current_task_id(), idx)
        self.device_store.put(oid, value)
        self.object_meta[oid] = ObjectMeta(
            oid, size=getattr(value, "nbytes", -1), in_device=True,
            holder=self.worker_info, node_ids=[self.node_id])
        self._signal_object_ready(oid)
        return ObjectRef(oid, self.worker_info)

    def _store_owned_value(self, oid: ObjectID, value: Any,
                           is_exception: bool = False):
        cfg = get_config()
        chunks = None
        size = -1
        try:
            # serialize to a chunk list: big payloads go straight from
            # the value's buffers into the shm segment, never joined
            chunks = serialize(value)
            size = serialized_size(chunks)
        except Exception as e:
            value = TaskError(e, "serialization", traceback.format_exc())
            is_exception = True
        if chunks is not None and size > cfg.max_direct_call_object_size \
                and not is_exception:
            self._shm_create_blocking(oid, chunks, size)
            meta = ObjectMeta(oid, size=size, in_shm=True,
                              node_ids=[self.node_id])
            self.object_meta[oid] = meta

            async def _announce(oid=oid, size=size):
                try:
                    await self.node_conn.call(
                        "object_created", (oid, size, self.worker_info))
                finally:
                    self._release_create_ref(oid)

            self._spawn_from_thread(_announce())
        else:
            self.memory_store.put(oid, value, is_exception)
            self.object_meta[oid] = ObjectMeta(oid, size=size, inline=True)
        self._signal_object_ready(oid)

    def _signal_object_ready(self, oid: ObjectID):
        def _set():
            ev = self._object_events.pop(oid, None)
            if ev is not None:
                ev.set()
        self.io.loop.call_soon_threadsafe(_set)

    # ---------------------------------------------------------------- get
    def get(self, refs: list[ObjectRef], timeout: float | None = None) -> list:
        deadline = None if timeout is None else time.monotonic() + timeout

        async def _get_all():
            return await asyncio.gather(
                *[self._async_get(r, deadline) for r in refs])

        values = self.io.run(_get_all())
        out = []
        for ref, (v, kind) in zip(refs, values):
            if kind == "shm":
                # deserialize OFF the IO loop, zero-copy over the mapping
                v, kind = self._load_shm_value(ref, v[0], v[1], deadline)
            if kind == "exc":
                raise v
            if kind == "des" and isinstance(v, BaseException):
                raise v
            out.append(v)
        return out

    def _load_shm_value(self, ref: ObjectRef, oid: ObjectID, size: int,
                        deadline: float | None):
        """Map + deserialize a local sealed shm object with NO copy: the
        returned value's arrays alias the shared-memory mapping (read-
        only). Pin contract: the mapping is held open while any counted
        local ObjectRef to oid exists OR any aliasing view is alive;
        the pin drops when both are gone. If the local copy vanished
        between resolve and map (freed / spilled / evicted), re-resolve
        through _async_get — that path restores or re-pulls it."""
        for _ in range(4):
            try:
                view = self.shm.get_view(oid, size)
            except (KeyError, FileNotFoundError, TypeError, ValueError):
                # gone (freed/spilled/evicted) or a concurrent release
                # closed the mapping under us: re-resolve — that path
                # restores, re-pulls, or reopens the segment
                v, kind = self.io.run(self._async_get(ref, deadline))
                if kind == "shm":
                    oid, size = v
                    continue
                return v, kind
            pin = _ShmGetPin(oid, self._pin_events)
            try:
                value = deserialize(memoryview(view).toreadonly(),
                                    buffer_wrapper=pin.wrap)
            except BaseException:
                pin.abort()
                self._drain_pin_events()
                raise
            ref_held = (pin.n_wrappers > 0
                        and self.reference_counter.has_record(oid))
            # registration + seal under ONE lock hold: a ref-drop
            # sentinel (which needs this lock, non-blocking, to pop the
            # list) can never observe the pin before its count is final
            with self._pin_lock:
                pins = self._shm_pins.setdefault(oid, []) \
                    if ref_held else None
                if pins:
                    # one ref-holder slot per oid suffices to pin the
                    # segment for the ref's lifetime — repeated gets of
                    # a live ref must not grow the pin list (this pin
                    # then lives only as long as its views do)
                    ref_held = False
                release_now = pin.seal(ref_held=ref_held)
                if ref_held:
                    pins.append(pin)
            if ref_held and not self.reference_counter.has_record(oid):
                # the ref died inside the registration window and its
                # sentinel may have fired before our append: reclaim the
                # orphan slot unless a later sentinel already popped it
                with self._pin_lock:
                    lst = self._shm_pins.get(oid)
                    if lst and pin in lst:
                        lst.remove(pin)
                        if not lst:
                            del self._shm_pins[oid]
                        self._pin_events.append(pin)  # drop its ref slot
            if release_now:
                # nothing aliases the mapping and no counted ref exists:
                # the queued event drops the guard slot + store get-ref
                self._pin_events.append(pin)
            self._drain_pin_events()
            return value, "des"
        raise ObjectLostError(f"{oid}: local shm copy keeps vanishing")

    async def _async_get(self, ref: ObjectRef, deadline: float | None):
        oid = ref.id
        pull_failures = 0
        while True:
            # 1. owner-local inline
            obj = self.memory_store.get_if_exists(oid)
            if obj is not None:
                return (obj.value, "exc" if obj.is_exception else "val")
            meta = self.object_meta.get(oid)
            if meta is not None and meta.error is not None:
                return (meta.error, "exc")
            # 2a. device-resident object: zero-copy if we hold it, else
            # host-staged fetch from the holder worker (device_objects.py)
            if meta is not None and meta.in_device:
                local = self.device_store.get(oid)
                if local is not None:
                    return (local, "val")
                arr = await self._fetch_device_object(oid, meta.holder,
                                                      deadline)
                if arr is not None:
                    return (arr, "val")
                if deadline is not None and time.monotonic() >= deadline:
                    raise GetTimeoutError(f"get({oid}) timed out")
                if self._owns(oid) and self._maybe_recover_object(oid):
                    continue
                raise ObjectLostError(
                    f"{oid}: device-object holder is gone and the value "
                    "is not reconstructable")
            # 2. shm object we own: read locally, pull cross-node, or
            # reconstruct via lineage (ref: object_recovery_manager.h:38)
            if meta is not None and meta.in_shm:
                if self.shm.contains_locally(oid):
                    return ((oid, meta.size), "shm")
                if await self._pull_object(oid, meta.size, meta.node_ids,
                                           ref.owner or self.worker_info):
                    if self.node_id not in meta.node_ids:
                        meta.node_ids.append(self.node_id)
                    return ((oid, meta.size), "shm")
                if self._owns(oid) and self._maybe_recover_object(oid):
                    continue
                raise ObjectLostError(
                    f"{oid}: all copies lost and not reconstructable")
            if self.shm.contains_locally(oid):
                info = await self.node_conn.call("object_lookup", oid)
                if info is not None:
                    return ((oid, info["size"]), "shm")
            if self._owns(oid):
                tid = self._return_to_task.get(oid)
                pt = self.pending_tasks.get(tid) if tid is not None else None
                if (pt is not None and pt.done and meta is None
                        and not self.memory_store.contains(oid)):
                    # freed value with retained lineage: re-execute
                    if not self._maybe_recover_object(oid):
                        raise ObjectLostError(
                            f"{oid}: freed and not reconstructable")
                    continue
                # pending task return: wait for completion signal
                ok = await self._wait_object_event(oid, deadline)
                if not ok:
                    raise GetTimeoutError(f"get({oid}) timed out")
                continue
            # 3. remote owner
            if ref.owner is None:
                raise ObjectLostError(f"{oid} has no known owner")
            res = await self._remote_status(ref, wait_s=self._poll_budget(deadline))
            kind = res[0]
            if kind == "inline":
                _, blob, is_exc = res
                val = deserialize(blob)
                return (val, "exc" if is_exc else "val")
            if kind == "shm":
                _, size, locations = res
                if not self.shm.contains_locally(oid):
                    if not await self._pull_object(
                            oid, size, [nid for nid, _ in locations],
                            ref.owner, addrs=dict(locations)):
                        # a location may have died between the owner's
                        # answer and our pull; re-ask the owner (it prunes
                        # dead nodes and may lineage-reconstruct)
                        pull_failures += 1
                        if pull_failures >= 3:
                            raise ObjectLostError(f"could not pull {oid}")
                        await asyncio.sleep(0.1)
                        continue
                return ((oid, size), "shm")
            if kind == "device":
                _, holder = res
                local = self.device_store.get(oid)
                if local is not None:
                    return (local, "val")  # we ARE the holder: zero-copy
                arr = await self._fetch_device_object(oid, holder, deadline)
                if arr is not None:
                    return (arr, "val")
                # tell the owner its holder looks dead so IT can lineage-
                # reconstruct (the owner can't see worker-level deaths on
                # other nodes); then re-ask — a recovering owner answers
                # "pending" until the re-execution lands
                pull_failures += 1
                try:
                    conn = await self._conn_to(ref.owner.address)
                    await conn.call("report_device_object_lost",
                                    (oid, holder.worker_id))
                except Exception:
                    pass
                if pull_failures >= 3:
                    raise ObjectLostError(
                        f"could not fetch device object {oid}")
                await asyncio.sleep(0.1)
                continue
            if kind == "pending":
                if deadline is not None and time.monotonic() >= deadline:
                    raise GetTimeoutError(f"get({oid}) timed out")
                continue
            raise ObjectLostError(f"{oid}: owner reports {kind}")

    async def _pull_object(self, oid: ObjectID, size: int,
                           node_ids: list[NodeID], owner,
                           addrs: dict | None = None) -> bool:
        """Pull a shm object from any live holder into the local node's
        store (ref: pull_manager.h:52 owner-directed pull)."""
        for nid in list(node_ids):
            if nid in self._dead_nodes:
                continue
            if nid == self.node_id:
                # local but not in shm: it may have been SPILLED to disk —
                # ask the node manager to restore it (ref: un-spill path
                # in local_object_manager)
                try:
                    if await self.node_conn.call("restore_object", oid):
                        return True
                except Exception:
                    pass
                continue
            addr = (addrs or {}).get(nid) or self._node_addrs.get(nid)
            if addr is None:
                continue
            try:
                ok = await self.node_conn.call(
                    "store_remote_object", (oid, size, owner, addr),
                    timeout=300)
            except Exception:
                ok = False
            if ok:
                return True
        return self.shm.contains_locally(oid)

    async def _fetch_device_object(self, oid: ObjectID, holder,
                                   deadline: float | None = None):
        """Host-staged device-object transfer: raw shard bytes from the
        holder worker's HBM -> local device_put. Never pickles the
        device buffer (ref analog: NCCL channel p2p, host-staged for
        the MPMD plane; in-mesh transfers ride XLA collectives).

        Returns None when the holder is unreachable/doesn't have the
        object (callers may recover via lineage); REMOTE errors (e.g.
        the holder failing to serialize the array) propagate — they
        would recur on retry and must not masquerade as a lost holder."""
        if holder is None:
            return None
        budget = 300.0
        if deadline is not None:
            budget = max(0.05, min(budget, deadline - time.monotonic()))
        try:
            conn = await self._conn_to(holder.address)
            res = await conn.call("fetch_device_object", oid,
                                  timeout=budget)
        except RemoteError:
            raise
        except Exception as e:
            logger.warning("device-object fetch of %s from %s failed: %s",
                           oid, holder.address, e)
            return None
        if res is None:
            return None
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, deserialize_array, res)

    def _maybe_recover_object(self, oid: ObjectID) -> bool:
        """Lineage reconstruction: resubmit the task that produced `oid`
        (ref: object_recovery_manager.h:38 + task_manager.h:212 lineage
        resubmission). Returns True if a re-execution is (now) in flight.
        Runs on the IO loop, so state flips are race-free."""
        tid = self._return_to_task.get(oid)
        if tid is None:
            return False
        pt = self.pending_tasks.get(tid)
        if pt is None or pt.spec.actor_id is not None:
            return False  # puts and actor tasks are not reconstructable
        if not pt.done:
            return True  # a resubmission is already in flight
        if pt.retries_left <= 0:
            return False
        pt.retries_left -= 1
        pt.done = False
        for i in range(pt.spec.num_returns):
            roid = ObjectID.for_return(tid, i)
            self.object_meta.pop(roid, None)
            self.memory_store.delete(roid)
        for aid in pt.pinned:
            self.reference_counter.add_task_pin(aid)
        logger.warning("reconstructing %s by re-executing task %s",
                       oid, pt.spec.name)
        self._spawn(self._run_normal_task(pt.spec))
        return True

    def _poll_budget(self, deadline: float | None) -> float:
        if deadline is None:
            return 5.0
        return max(0.05, min(5.0, deadline - time.monotonic()))

    async def _remote_status(self, ref: ObjectRef, wait_s: float):
        conn = await self._conn_to(ref.owner.address)
        return await conn.call("get_object", (ref.id, wait_s),
                               timeout=wait_s + 30.0)

    async def _wait_object_event(self, oid: ObjectID,
                                 deadline: float | None) -> bool:
        ev = self._object_events.get(oid)
        if ev is None:
            ev = asyncio.Event()
            self._object_events[oid] = ev
        # re-check after registering to avoid lost wakeups
        if self.memory_store.contains(oid) or (
                self.object_meta.get(oid) is not None
                and not self._is_pending(oid)):
            return True
        try:
            budget = None if deadline is None else max(
                0.0, deadline - time.monotonic())
            await asyncio.wait_for(ev.wait(), budget)
            return True
        except asyncio.TimeoutError:
            return False

    def _is_pending(self, oid: ObjectID) -> bool:
        meta = self.object_meta.get(oid)
        if meta is not None:
            return meta.size == -1 and not meta.inline and meta.error is None
        tid = self._return_to_task.get(oid)
        if tid is None:
            return False
        pt = self.pending_tasks.get(tid)
        return pt is not None and not pt.done

    async def rpc_get_object(self, conn, arg):
        """Owner-side object status/fetch (long-poll when pending)."""
        oid, wait_s = arg
        deadline = time.monotonic() + max(0.0, wait_s)
        while True:
            obj = self.memory_store.get_if_exists(oid)
            if obj is not None:
                return ("inline", serialize_to_bytes(obj.value), obj.is_exception)
            meta = self.object_meta.get(oid)
            if meta is not None and meta.error is not None:
                return ("inline", serialize_to_bytes(meta.error), True)
            if meta is not None and meta.in_device:
                return ("device", meta.holder)
            if meta is not None and meta.in_shm:
                locs = [(nid, self._node_addrs.get(nid)) for nid in meta.node_ids
                        if self._node_addrs.get(nid) is not None]
                if locs or self.shm.contains_locally(oid):
                    return ("shm", meta.size, locs)
                # every copy died with its node: reconstruct, then serve
                # the borrower from the fresh copy (transitive recovery)
                if self._maybe_recover_object(oid):
                    continue
                return ("unknown",)
            if self._is_pending(oid):
                if time.monotonic() >= deadline:
                    return ("pending",)
                ok = await self._wait_object_event(oid, deadline)
                if not ok:
                    return ("pending",)
                continue
            # freed value with retained lineage: reconstruct, then serve
            if self._maybe_recover_object(oid):
                continue
            return ("unknown",)

    def rpc_report_device_object_lost(self, conn, arg):
        """A borrower failed to reach the recorded holder of a device
        object we own: drop the stale meta and lineage-reconstruct if
        possible (ref: object_recovery_manager.h:38)."""
        oid, holder_wid = arg
        meta = self.object_meta.get(oid)
        if meta is None or not meta.in_device or meta.holder is None                 or meta.holder.worker_id != holder_wid:
            return False  # already recovered / different holder now
        if self.device_store.contains(oid):
            return False  # we hold a live copy ourselves
        return self._maybe_recover_object(oid)

    async def rpc_fetch_device_object(self, conn, oid: ObjectID):
        """Serve a device object we hold as raw host bytes (+dtype/shape).
        Runs the gather on an executor thread — device_get can block."""
        value = self.device_store.get(oid)
        if value is None:
            return None
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, serialize_array, value)

    def rpc_free_device_object(self, conn, oid: ObjectID):
        self.device_store.delete(oid)
        return True

    # --------------------------------------------------------------- wait
    def wait(self, refs: list[ObjectRef], num_returns: int = 1,
             timeout: float | None = None):
        """Event-driven wait: owned refs block on the object-ready event,
        remote refs long-poll the owner — no fixed-interval re-polling
        (ref: CoreWorker::Wait fulfills from memory-store/plasma
        callbacks, not polling)."""
        deadline = None if timeout is None else time.monotonic() + timeout

        def _ready_now(ref: ObjectRef) -> bool:
            oid = ref.id
            if self.memory_store.contains(oid):
                return True
            if self.object_meta.get(oid) is not None or self._owns(oid):
                return not self._is_pending(oid)
            return self.shm.contains_locally(oid)

        async def _wait_ready(ref: ObjectRef):
            """Resolves (to the ref) only when the ref becomes ready."""
            oid = ref.id
            while True:
                if _ready_now(ref):
                    return ref
                if ref.owner is None \
                        or ref.owner.worker_id == self.worker_id:
                    if not self._owns(oid):
                        # freed self-owned ref: status is "unknown", which
                        # counts as no-longer-pending (matches the remote
                        # owner path's semantics)
                        return ref
                    await self._wait_object_event(oid, deadline)
                    if deadline is not None \
                            and time.monotonic() >= deadline:
                        return None
                    continue
                # remote owner: long-poll its status endpoint
                budget = self._poll_budget(deadline)
                try:
                    res = await self._remote_status(ref, wait_s=budget)
                except Exception:
                    await asyncio.sleep(0.5)  # owner unreachable; retry
                    res = ("pending",)
                if res[0] != "pending":
                    return ref
                if deadline is not None and time.monotonic() >= deadline:
                    return None

        async def _wait_loop():
            waiters = {asyncio.ensure_future(_wait_ready(r)): r
                       for r in refs}
            ready_ids = set()
            try:
                while len(ready_ids) < num_returns and waiters:
                    budget = None if deadline is None else max(
                        0.0, deadline - time.monotonic())
                    done, _ = await asyncio.wait(
                        waiters.keys(), timeout=budget,
                        return_when=asyncio.FIRST_COMPLETED)
                    if not done:
                        break  # deadline hit with nothing new
                    for t in done:
                        r = waiters.pop(t)
                        if not t.cancelled() and t.exception() is None \
                                and t.result() is not None:
                            ready_ids.add(r.id)
            finally:
                for t in waiters:
                    t.cancel()
                if waiters:
                    await asyncio.gather(*waiters, return_exceptions=True)
            ready = [r for r in refs if r.id in ready_ids]
            not_ready = [r for r in refs if r.id not in ready_ids]
            return ready, not_ready

        return self.io.run(_wait_loop())

    # ------------------------------------------------------ task submission
    def submit_task(self, function: Any, args: tuple, kwargs: dict,
                    options) -> list[ObjectRef]:
        task_id = TaskID.for_normal_task(self.job_id)
        spec_args, pinned = self._prepare_args(args)
        spec_kwargs, pinned_kw = self._prepare_args(kwargs)
        cfg = get_config()
        max_retries = options.max_retries
        if max_retries < 0:
            max_retries = cfg.default_max_retries
        if options.num_returns == -1 and options.tensor_transport:
            raise ValueError(
                "tensor_transport is not supported for streaming "
                "generators; yielded items go through the object store")
        if options.num_returns == -1:
            # retrying a partially-consumed stream would replay items
            max_retries = 0
        spec = TaskSpec(
            task_id=task_id, job_id=self.job_id,
            name=options.name or getattr(function, "__name__", "task"),
            function_blob=_dumps_code(function),
            args=spec_args, kwargs=spec_kwargs,
            num_returns=options.num_returns,
            resources=self._demand_for(options),
            owner=self.worker_info, max_retries=max_retries,
            retry_exceptions=options.retry_exceptions,
            scheduling_strategy=options.scheduling_strategy,
            runtime_env=self._package_runtime_env(options.runtime_env),
            tensor_transport=options.tensor_transport,
            trace_ctx=_trace_carrier())
        refs = self._register_task(spec, pinned + pinned_kw)
        self._emit_task_event(spec, "PENDING_ARGS")
        try:
            from ray_tpu.util import builtin_metrics as _bm

            self._inflight_tasks += 1
            _bm.tasks_submitted.inc()
            _bm.task_queue_depth.set(
                float(self._inflight_tasks),
                tags={"owner": self.worker_id.hex()[:12]})
        except Exception:
            pass  # telemetry must never fail a submission
        self._spawn_from_thread(self._run_normal_task(spec))
        if spec.num_returns == -1:
            from ray_tpu.core.streaming import ObjectRefGenerator

            return ObjectRefGenerator(self, spec.task_id)
        return refs

    def _package_runtime_env(self, renv: dict | None) -> dict | None:
        """Validate + upload a runtime_env at submission time (ref:
        _private/runtime_env/packaging.py). Raises on unsupported keys —
        never silently drops the option."""
        if not renv:
            return None
        from ray_tpu._internal import runtime_env as renv_mod

        def kv_put(key: str, data: bytes):
            self.io.run(self.gcs.kv_put(
                key, data, namespace=renv_mod.KV_NAMESPACE))

        return renv_mod.package(renv, kv_put)

    def _apply_runtime_env(self, spec: TaskSpec):
        """Worker side: materialize the packaged env before execution.

        Returns a restore callable. Normal tasks run on POOLED workers, so
        the caller must revert (env vars / cwd / sys.path leak into the
        next task otherwise); actor creation keeps the env for the actor's
        lifetime — its worker is dedicated (ref: the reference dedicates
        workers per runtime-env hash)."""
        if not spec.runtime_env:
            return None
        import sys

        from ray_tpu._internal import runtime_env as renv_mod

        saved_keys = list(spec.runtime_env.get("env_vars") or {})
        if spec.runtime_env.get("pip"):
            saved_keys += ["VIRTUAL_ENV", "PATH"]  # venv splice reverts too
        if spec.runtime_env.get("conda"):
            saved_keys += ["CONDA_PREFIX", "PATH"]
        saved_env = {k: os.environ.get(k) for k in saved_keys}
        saved_cwd = os.getcwd()
        saved_path = list(sys.path)

        def kv_get(key: str):
            return self.io.run(self.gcs.kv_get(
                key, namespace=renv_mod.KV_NAMESPACE))

        renv_mod.materialize(spec.runtime_env, kv_get)

        def restore():
            for k, v in saved_env.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            try:
                os.chdir(saved_cwd)
            except OSError:
                pass
            sys.path[:] = saved_path
            if spec.runtime_env.get("pip"):
                renv_mod.release_pip_venv(spec.runtime_env["pip"])
                # modules imported from the venv must not satisfy later
                # imports on this pooled worker (sys.modules outlives the
                # sys.path splice)
                venv_root = renv_mod._VENV_ROOT
                for name, mod in list(sys.modules.items()):
                    f = getattr(mod, "__file__", None) or ""
                    if f.startswith(venv_root):
                        del sys.modules[name]

        return restore

    def _demand_for(self, options) -> dict[str, float]:
        demand = options.resources.to_demand()
        strat = options.scheduling_strategy
        if isinstance(strat, PlacementGroupSchedulingStrategy):
            # rewrite demand onto the PG's reserved bundle resources
            pgid = strat.placement_group_id
            idx = strat.bundle_index
            if idx >= 0:
                demand = {f"{r}_pg_{pgid.hex()}_{idx}": amt
                          for r, amt in demand.items()}
        return demand

    def _prepare_args(self, args):
        pinned: list[ObjectID] = []
        if isinstance(args, dict):
            out = {}
            for k, v in args.items():
                if isinstance(v, ObjectRef):
                    out[k] = RefArg(v.id, v.owner)
                    self.reference_counter.add_task_pin(v.id)
                    pinned.append(v.id)
                else:
                    out[k] = v
            return out, pinned
        out = []
        for v in args:
            if isinstance(v, ObjectRef):
                out.append(RefArg(v.id, v.owner))
                self.reference_counter.add_task_pin(v.id)
                pinned.append(v.id)
            else:
                out.append(v)
        return out, pinned

    def _register_task(self, spec: TaskSpec, pinned) -> list[ObjectRef]:
        pt = _PendingTask(spec=spec, retries_left=spec.max_retries,
                          pinned=pinned)
        self.pending_tasks[spec.task_id] = pt
        if spec.num_returns == -1:  # streaming generator
            from ray_tpu.core.streaming import _StreamState

            self._streams[spec.task_id] = _StreamState(
                spec.task_id, get_config().generator_backpressure_num_objects)
            return []
        refs = []
        for i in range(spec.num_returns):
            oid = ObjectID.for_return(spec.task_id, i)
            self._return_to_task[oid] = spec.task_id
            refs.append(ObjectRef(oid, self.worker_info))
        return refs

    # --- lease management (ref: normal_task_submitter lease reuse) ---
    def _lease_key(self, demand: dict[str, float], strategy=None) -> tuple:
        # the scheduling class includes the strategy (ref: SchedulingClass
        # keyed by resource shape + strategy) so an affinity/SPREAD lease
        # is never handed to a task with different placement constraints
        if strategy is None:
            skey = None
        elif isinstance(strategy, NodeAffinitySchedulingStrategy):
            skey = ("affinity", strategy.node_id.hex(), strategy.soft)
        elif isinstance(strategy, NodeLabelSchedulingStrategy):
            # canonical: equal strategies share a pool regardless of dict
            # insertion order
            skey = ("label", tuple(sorted(strategy.hard.items())),
                    tuple(sorted(strategy.soft.items())))
        else:
            skey = repr(strategy)
        return (tuple(sorted(demand.items())), skey)

    def _lease_pool_for(self, key: tuple) -> "_LeasePool":
        pool = self._lease_cache.get(key)
        if pool is None:
            pool = _LeasePool()
            self._lease_cache[key] = pool
        return pool

    async def _acquire_lease(self, demand: dict[str, float], strategy=None,
                             pt: "_PendingTask | None" = None):
        """Get a leased worker for `demand`: reuse an idle cached lease if
        one exists, otherwise queue as a waiter and make sure enough lease
        fetches are in flight (ref: normal_task_submitter.cc:291 — one
        scheduling-key pipeline, workers handed task-to-task without a
        raylet round-trip). `pt` registers the waiter for withdrawal on
        cancel (a cancelled queued task must stop competing for capacity)."""
        key = self._lease_key(demand, strategy)
        pool = self._lease_pool_for(key)
        if pool.idle:
            entry = pool.idle.pop()
            return entry[0], entry[1], entry[2]
        fut = asyncio.get_running_loop().create_future()
        pool.waiters.append(fut)
        if pt is not None:
            pt.lease_waiter = (pool, fut)
        if pool.inflight < len(pool.waiters):
            pool.inflight += 1
            self._spawn(
                self._fetch_lease(key, demand, pool, strategy))
        try:
            entry = await fut
        finally:
            if pt is not None:
                pt.lease_waiter = None
        return entry[0], entry[1], entry[2]

    async def _fetch_lease(self, key: tuple, demand: dict[str, float],
                           pool: "_LeasePool", strategy=None):
        """One in-flight lease request against the cluster; the grant goes
        to whichever waiter is first in line."""
        try:
            entry = await self._request_cluster_lease(demand, strategy)
        except BaseException as e:
            # BaseException: a shutdown-sweep CancelledError must run the
            # same bookkeeping, else pool.inflight stays inflated and a
            # waiter future hangs forever (its task destroyed pending).
            pool.inflight -= 1
            # fetches and waiters are ~1:1 (one spawned per new waiter),
            # so a failed fetch fails exactly ONE waiter — the same blast
            # radius as the old request-per-task design. Other waiters
            # keep their own in-flight fetches.
            while pool.waiters:
                fut = pool.waiters.pop(0)
                if not fut.done():
                    if isinstance(e, asyncio.CancelledError):
                        fut.set_exception(
                            WorkerCrashedError("shutting down"))
                        # the waiter task is likely cancelled too; mark
                        # the exception retrieved so GC doesn't warn
                        fut.exception()
                    else:
                        fut.set_exception(e)
                    break
            if isinstance(e, asyncio.CancelledError):
                raise
            return
        pool.inflight -= 1
        self._offer_lease(key, pool, entry, recycled=False)

    def _offer_lease(self, key: tuple, pool: "_LeasePool", entry,
                     recycled: bool):
        """Hand a granted/finished lease to the next waiter; otherwise keep
        a recycled lease warm for lease_reuse_idle_s, and return a fetched
        lease nobody wants (holding it would starve other clients queued
        at the node manager)."""
        while pool.waiters:
            fut = pool.waiters.pop(0)
            if not fut.done():
                fut.set_result(entry)
                return
        idle_s = get_config().lease_reuse_idle_s
        if not recycled or idle_s <= 0 or self._shutdown:
            self._spawn(self._release_lease(
                entry[0], entry[1], entry[2], reusable=False))
            return
        # identity sentinel: the same lease can be recycled repeatedly, so
        # an expire timer from an EARLIER idle period must not evict the
        # lease's newer idle incarnation (tuple equality would)
        idle_entry = (entry[0], entry[1], entry[2], object())
        pool.idle.append(idle_entry)

        async def _expire():
            await asyncio.sleep(idle_s)
            for i, cand in enumerate(pool.idle):
                if cand[3] is idle_entry[3]:
                    del pool.idle[i]
                    await self._release_lease(
                        entry[0], entry[1], entry[2], reusable=False)
                    return
        self._spawn(_expire())

    async def _request_cluster_lease(self, demand: dict[str, float],
                                     strategy=None):
        nm_addr = Address(self.node_address.host, self.node_address.port)
        allow_spill = True
        infeasible_deadline: float | None = None
        hop = 0
        while hop < 1000:
            hop += 1
            try:
                conn = (self.node_conn
                        if nm_addr.key() == self.node_address.key()
                        else await self._conn_to(nm_addr))
                res = await conn.call("request_lease",
                                      (demand, allow_spill, strategy),
                                      timeout=_TASK_PUSH_TIMEOUT)
            except (ConnectionLost, RpcError, OSError):
                if nm_addr.key() == self.node_address.key():
                    raise  # our own node manager is gone — unrecoverable
                # spillback target died (stale cluster view); fall back to
                # the local manager, whose view refreshes via heartbeat
                self._conns.pop(nm_addr.key(), None)
                nm_addr = Address(self.node_address.host,
                                  self.node_address.port)
                allow_spill = True
                await asyncio.sleep(0.3)
                continue
            if res[0] == "granted":
                return res[1], res[2], nm_addr
            if res[0] == "spillback":
                nm_addr = res[1]
                allow_spill = False
                continue
            # infeasible NOW: publish the unmet demand so an autoscaler can
            # act on it (ref: raylets feeding resource_demands to the
            # autoscaler), and keep retrying until lease_timeout_s —
            # capacity may be on its way
            if infeasible_deadline is None:
                infeasible_deadline = (time.monotonic()
                                       + get_config().lease_timeout_s)
            if time.monotonic() >= infeasible_deadline:
                raise RuntimeError(f"infeasible task: {res[1]}")
            try:
                autoscaler_listening = await self.gcs.call(
                    "report_task_demand", demand)
            except Exception:
                autoscaler_listening = False
            if not autoscaler_listening:
                # nothing will ever grow the cluster — fail fast
                raise RuntimeError(f"infeasible task: {res[1]}")
            nm_addr = Address(self.node_address.host, self.node_address.port)
            allow_spill = True
            await asyncio.sleep(0.5)
        raise RuntimeError("lease spillback loop exceeded")

    async def _release_lease(self, winfo, token, nm_addr,
                             reusable: bool = True):
        try:
            conn = (self.node_conn if nm_addr.key() == self.node_address.key()
                    else await self._conn_to(nm_addr))
            await conn.call("return_lease", token)
        except Exception:
            pass

    def _recycle_lease(self, demand: dict[str, float], winfo, token, nm_addr,
                       strategy=None):
        """A task finished on this leased worker: hand the lease straight
        to the next queued task of the same shape, or keep it warm for
        lease_reuse_idle_s. Runs on the IO loop."""
        key = self._lease_key(demand, strategy)
        self._offer_lease(key, self._lease_pool_for(key),
                          (winfo, token, nm_addr), recycled=True)

    async def _run_normal_task(self, spec: TaskSpec):
        pt = self.pending_tasks[spec.task_id]
        # PG strategies were already rewritten into bundle-reserved demand
        strat = spec.scheduling_strategy
        if isinstance(strat, PlacementGroupSchedulingStrategy):
            strat = None
        t_sched = time.perf_counter()
        while True:
            try:
                winfo, token, nm_addr = await self._acquire_lease(
                    spec.resources, strat, pt)
                spec.attempt = spec.max_retries - pt.retries_left
                self._emit_task_event(spec, "SCHEDULED")
                if t_sched is not None:  # first grant only, not retries
                    self._observe_sched_latency(
                        time.perf_counter() - t_sched)
                    t_sched = None
            except asyncio.CancelledError:
                if pt.cancelled or pt.done:
                    return  # waiter withdrawn by cancel(); returns failed
                raise      # shutdown sweep — propagate
            except Exception as e:
                self._fail_task(spec, TaskError(e, spec.name, ""))
                return
            if pt.cancelled or pt.done:
                # cancelled while queued: returns were already failed by
                # cancel_task; just hand the lease back
                self._recycle_lease(spec.resources, winfo, token, nm_addr,
                                    strat)
                return
            try:
                pt.running_on = winfo
                self._emit_task_event(spec, "DISPATCHED")
                conn = await self._conn_to(winfo.address)
                reply = await conn.call("push_task", spec,
                                        timeout=_TASK_PUSH_TIMEOUT)
            except (ConnectionLost, RpcError, OSError) as e:
                pt.running_on = None
                await self._release_lease(winfo, token, nm_addr, reusable=False)
                if pt.cancelled:
                    # force-cancel kills the worker mid-task; that death is
                    # the cancellation succeeding, not a crash
                    self._fail_task(spec, TaskCancelledError(
                        f"task {spec.name} cancelled while running"))
                    return
                if pt.retries_left > 0:
                    pt.retries_left -= 1
                    logger.warning("task %s worker crash, retrying (%s)",
                                   spec.name, e)
                    await asyncio.sleep(0.05)
                    continue
                self._fail_task(spec, WorkerCrashedError(
                    f"worker died running {spec.name}: {e}"))
                return
            pt.running_on = None
            if pt.cancelled:
                # cancel() already returned True — it wins even when the
                # worker raced to a result. Never recycle this lease: on
                # force-cancel the worker is milliseconds from os._exit.
                self._spawn(self._release_lease(
                    winfo, token, nm_addr, reusable=False))
                self._fail_task(spec, TaskCancelledError(
                    f"task {spec.name} cancelled while running"))
                return
            if strat == "SPREAD":
                # no sticky reuse for SPREAD: recycling would funnel the
                # whole wave onto the first-granted node; releasing makes
                # every task take the round-robin path at the node manager
                # (fire-and-forget: no reply-latency cost per task)
                self._spawn(self._release_lease(
                    winfo, token, nm_addr, reusable=False))
            else:
                self._recycle_lease(spec.resources, winfo, token, nm_addr,
                                    strat)
            if reply[0] == "task_error":
                _, err_blob, tb = reply
                if spec.retry_exceptions and pt.retries_left > 0:
                    pt.retries_left -= 1
                    continue
                try:
                    cause = deserialize(err_blob)
                except Exception as e:
                    cause = RuntimeError(f"undeserializable task error: {e}")
                self._fail_task(spec, TaskError(cause, spec.name, tb))
                return
            self._complete_task(spec, reply[1], winfo)
            return

    @staticmethod
    def _observe_sched_latency(dur_s: float):
        try:
            from ray_tpu.util import builtin_metrics as _bm

            _bm.task_sched_latency.observe(dur_s)
        except Exception:
            pass

    def _task_finished(self, status: str):
        try:
            from ray_tpu.util import builtin_metrics as _bm

            self._inflight_tasks = max(0, self._inflight_tasks - 1)
            _bm.tasks_finished.inc(tags={"status": status})
            _bm.task_queue_depth.set(
                float(self._inflight_tasks),
                tags={"owner": self.worker_id.hex()[:12]})
        except Exception:
            pass

    def _complete_task(self, spec: TaskSpec, results: list, winfo: WorkerInfo):
        pt = self.pending_tasks.get(spec.task_id)
        if pt is not None and pt.done:
            return  # lost the race with a cancel-fail; returns hold errors
        for i, entry in enumerate(results):
            if entry[0] == "stream_done":
                # all generator_item RPCs were acked before this reply was
                # sent, so the buffer is complete — close the stream
                stream = self._streams.get(spec.task_id)
                if stream is not None:
                    stream.finish(entry[1])
                continue
            oid = ObjectID.for_return(spec.task_id, i)
            if entry[0] == "inline":
                _, blob, is_exc = entry
                try:
                    value = deserialize(blob)
                except Exception as e:
                    value, is_exc = TaskError(e, spec.name, ""), True
                self.memory_store.put(oid, value, is_exc)
                self.object_meta[oid] = ObjectMeta(oid, size=len(blob),
                                                   inline=True)
            elif entry[0] == "device":
                _, size, holder = entry
                self.object_meta[oid] = ObjectMeta(
                    oid, size=size, in_device=True, holder=holder,
                    node_ids=[holder.node_id])
            else:  # ("shm", size)
                _, size = entry
                self.object_meta[oid] = ObjectMeta(
                    oid, size=size, in_shm=True, node_ids=[winfo.node_id])
            self._signal_object_ready(oid)
        if pt is not None:
            pt.done = True
            for oid in pt.pinned:
                self.reference_counter.remove_task_pin(oid)
            if spec.actor_id is None:  # actor calls aren't counted at
                self._task_finished("ok")  # submit; keep the pair honest

    def _fail_task(self, spec: TaskSpec, error: Exception):
        pt = self.pending_tasks.get(spec.task_id)
        if pt is not None and pt.done:
            # already failed/completed (e.g. cancelled while queued, then
            # the lease path errored too): a second pass would double-
            # decrement the arg pins
            return
        stream = self._streams.get(spec.task_id)
        if stream is not None:
            stream.abort(error)
        from ray_tpu._internal.tracing import truncate_error

        cause = getattr(error, "cause", None)  # TaskError wraps the app exc
        if not isinstance(cause, BaseException):
            cause = error
        # a deliberate rt.cancel() is CANCELLED, not a failure — it must
        # not pollute `rayt list tasks --state FAILED` or failure counts
        terminal = ("CANCELLED" if isinstance(error, TaskCancelledError)
                    else "FAILED")
        self._emit_task_event(
            spec, terminal,
            error=truncate_error(
                type(cause).__name__, str(cause),
                getattr(error, "remote_traceback", "")))
        for i in range(max(spec.num_returns, 0)):
            oid = ObjectID.for_return(spec.task_id, i)
            self.memory_store.put(oid, error, is_exception=True)
            meta = self.object_meta.setdefault(oid, ObjectMeta(oid))
            meta.error = error
            self._signal_object_ready(oid)
        if pt is not None:
            pt.done = True
            for oid in pt.pinned:
                self.reference_counter.remove_task_pin(oid)
            if spec.actor_id is None:
                self._task_finished("error")

    # ------------------------------------------------------ actor lifecycle
    def create_actor(self, cls: Any, args: tuple, kwargs: dict,
                     options) -> ActorID:
        actor_id = ActorID.of(self.job_id)
        task_id = TaskID.for_actor_task(actor_id)
        spec_args, pinned = self._prepare_args(args)
        spec_kwargs, pinned_kw = self._prepare_args(kwargs)
        spec = TaskSpec(
            task_id=task_id, job_id=self.job_id,
            name=getattr(cls, "__name__", "Actor"),
            function_blob=_dumps_code(cls),
            args=spec_args, kwargs=spec_kwargs, num_returns=1,
            resources=self._demand_for(options),
            owner=self.worker_info, actor_id=actor_id,
            is_actor_creation=True, actor_options=options,
            scheduling_strategy=options.scheduling_strategy,
            runtime_env=self._package_runtime_env(options.runtime_env),
            trace_ctx=_trace_carrier())
        self.io.run(self.gcs.register_actor(spec))
        return actor_id

    def get_actor_submitter(self, actor_id: ActorID) -> "_ActorTaskSubmitter":
        sub = self._actor_submitters.get(actor_id)
        if sub is None:
            sub = _ActorTaskSubmitter(self, actor_id)
            self._actor_submitters[actor_id] = sub
        return sub

    def submit_actor_task(self, actor_id: ActorID, method_name: str,
                          args: tuple, kwargs: dict, options) -> list[ObjectRef]:
        task_id = TaskID.for_actor_task(actor_id)
        spec_args, pinned = self._prepare_args(args)
        spec_kwargs, pinned_kw = self._prepare_args(kwargs)
        max_retries = options.max_retries if options.max_retries >= 0 else 0
        if options.num_returns == -1 and options.tensor_transport:
            raise ValueError(
                "tensor_transport is not supported for streaming "
                "generators; yielded items go through the object store")
        if options.num_returns == -1:
            # retrying a partially-consumed stream would replay items
            max_retries = 0
        spec = TaskSpec(
            task_id=task_id, job_id=self.job_id,
            name=f"{method_name}", function_blob=None,
            args=spec_args, kwargs=spec_kwargs,
            num_returns=options.num_returns,
            resources={}, owner=self.worker_info,
            max_retries=max_retries,
            actor_id=actor_id, method_name=method_name,
            tensor_transport=options.tensor_transport,
            trace_ctx=_trace_carrier())
        refs = self._register_task(spec, pinned + pinned_kw)
        self._emit_task_event(spec, "PENDING_ARGS")
        sub = self.get_actor_submitter(actor_id)
        self._spawn_from_thread(sub.submit(spec))
        if spec.num_returns == -1:
            from ray_tpu.core.streaming import ObjectRefGenerator

            return ObjectRefGenerator(self, spec.task_id)
        return refs

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        self.io.run(self.gcs.kill_actor(actor_id, no_restart))

    def cancel_task(self, ref: ObjectRef, force: bool = False) -> bool:
        """Best-effort cancel of the normal task producing `ref` (ref
        analog: core_worker.cc CancelTask / ray.cancel).

        Queued tasks fail immediately with TaskCancelledError; a running
        task gets an async exception raised between bytecodes (blocked C
        calls — sleep, IO — are only interrupted by force=True, which
        kills the executing worker; same limitation as the reference).
        Returns False when the task already finished — its value stands."""
        tid = self._return_to_task.get(ref.id)
        if tid is None:
            raise ValueError(
                "cancel() needs a task-return ObjectRef owned by this "
                "driver (for actors use rt.kill)")
        if tid.has_actor():
            raise ValueError(
                "cancelling actor tasks is not supported; rt.kill(actor) "
                "tears down the whole actor")
        # all bookkeeping on the IO loop: serializes against
        # _run_normal_task/_complete_task (they run there too), so the
        # done-check, flag set, and immediate fail are atomic
        return self.io.run(self._cancel_on_loop(tid, force))

    async def _cancel_on_loop(self, tid: TaskID, force: bool) -> bool:
        pt = self.pending_tasks.get(tid)
        if pt is None or pt.done:
            return False
        pt.cancelled = True
        pt.retries_left = 0
        winfo = pt.running_on
        if winfo is None:
            # not yet on a worker: fail the returns now and withdraw the
            # pending lease waiter — a cancelled task must stop competing
            # for capacity (and feeding autoscaler demand)
            lw, pt.lease_waiter = pt.lease_waiter, None
            if lw is not None:
                pool, fut = lw
                if fut in pool.waiters:
                    pool.waiters.remove(fut)
                if not fut.done():
                    fut.cancel()
            self._fail_task(pt.spec, TaskCancelledError(
                f"task {pt.spec.name} cancelled before it started"))
            return True

        async def _send():
            try:
                conn = await self._conn_to(winfo.address)
                await conn.call("cancel_task", (tid, force), timeout=10)
            except Exception:
                pass  # worker may be mid-death; push path handles it
            # If the worker replied False (push not yet arrived, or body
            # finished), pt.cancelled is still set: the push reply path
            # fails the task with TaskCancelledError either way.
        self._spawn(_send())
        return True

    # --------------------------------------------------- streaming (owner)
    async def rpc_generator_item(self, conn, arg):
        """One yielded item from a streaming task we own (ref:
        CoreWorker::ReportGeneratorItemReturns). The ack is delayed while
        the unconsumed buffer exceeds the backpressure threshold, which
        blocks the producer."""
        task_id, index, entry = arg
        stream = self._streams.get(task_id)
        if stream is None:
            return False  # consumer gone; producer may stop
        oid = ObjectID.for_return(task_id, index)
        if entry[0] == "inline":
            _, blob, is_exc = entry
            try:
                value = deserialize(blob)
            except Exception as e:
                value, is_exc = TaskError(e, "stream item", ""), True
            self.memory_store.put(oid, value, is_exc)
            self.object_meta[oid] = ObjectMeta(oid, size=len(blob),
                                               inline=True)
        else:  # ("shm", size, node_id)
            _, size, node_id = entry
            self.object_meta[oid] = ObjectMeta(
                oid, size=size, in_shm=True, node_ids=[node_id])
        await stream.wait_capacity()
        if stream.dropped:
            # consumer went away while we waited: free the stored item,
            # including the producer-node shm copy (it was pinned by
            # object_created and would otherwise leak until node restart)
            self.memory_store.delete(oid)
            dropped_meta = self.object_meta.pop(oid, None)
            if dropped_meta is not None and dropped_meta.in_shm:
                self._free_shm_copies(dropped_meta)
            return False
        stream.push(index, oid)
        return True

    # ------------------------------------------------- worker-side execution
    async def _report_stream_item(self, spec: TaskSpec, index: int, item):
        """Serialize + push one yielded item to the owner; resolves to the
        owner's ack (False = consumer dropped the stream)."""
        cfg = get_config()
        oid = ObjectID.for_return(spec.task_id, index)
        try:
            chunks = serialize(item)
            size = serialized_size(chunks)
        except Exception as e:
            entry = ("inline", serialize_to_bytes(
                TaskError(e, spec.name, traceback.format_exc())), True)
        else:
            if size > cfg.max_direct_call_object_size:
                # yielded blocks ride the same copy-free path as normal
                # returns: chunks straight into shm, no host-side join
                await self._shm_create_async(oid, chunks, size)
                try:
                    await self.node_conn.call(
                        "object_created", (oid, size, spec.owner))
                finally:
                    self._release_create_ref(oid)
                entry = ("shm", size, self.node_id)
            else:
                entry = ("inline", chunks_to_bytes(chunks), False)
        conn = await self._conn_to(spec.owner.address)
        return await conn.call(
            "generator_item", (spec.task_id, index, entry),
            timeout=_TASK_PUSH_TIMEOUT)

    def _stream_returns(self, spec: TaskSpec, gen) -> tuple:
        """Drive a (sync) generator, pushing each item to the owner as
        produced. Runs on an executor thread; each report blocks on the
        owner's ack (the backpressure point)."""
        count = 0
        for item in gen:
            alive = self.io.run(self._report_stream_item(spec, count, item))
            count += 1
            if alive is False:
                break  # consumer dropped the stream
        return ("ok", [("stream_done", count)])

    async def _stream_returns_async(self, spec: TaskSpec, agen) -> tuple:
        """Async-generator variant (async actors / Serve streaming)."""
        count = 0
        async for item in agen:
            fut = self.io.spawn(self._report_stream_item(spec, count, item))
            alive = await asyncio.wrap_future(fut)
            count += 1
            if alive is False:
                break
        return ("ok", [("stream_done", count)])

    def _ensure_executor_alive(self):
        """A stale cancellation async-exc can, in a narrow window, land in
        the pooled executor thread's idle loop and kill it silently —
        ThreadPoolExecutor never replaces dead threads, so every later
        push would hang. Detect and rebuild."""
        ident = self._exec_thread_ident
        if ident is None:
            return
        if any(t.ident == ident for t in threading.enumerate()):
            return
        # release the dead executor's bookkeeping (its work queue and
        # thread registry otherwise leak for the worker's lifetime);
        # wait=False since the only thread is already gone
        old = self.executor
        self.executor = ThreadPoolExecutor(max_workers=1,
                                           thread_name_prefix="rayt-exec")
        self._exec_thread_ident = None
        try:
            old.shutdown(wait=False)
        except Exception:
            pass

    async def rpc_push_task(self, conn, spec: TaskSpec):
        loop = asyncio.get_running_loop()
        self._ensure_executor_alive()
        return await loop.run_in_executor(
            self.executor, self._execute_task, spec)

    def _emit_task_failed(self, spec: TaskSpec, e: BaseException, tb: str):
        """Terminal failure transition carrying the LIVE exception's
        type/message plus the truncated traceback — recorded at the
        catch site so the payload never degrades to a traceback
        re-parse. A cancellation delivered into the body is CANCELLED,
        not FAILED."""
        from ray_tpu._internal.tracing import truncate_error

        self._emit_task_event(
            spec,
            "CANCELLED" if isinstance(e, TaskCancelledError) else "FAILED",
            error=truncate_error(type(e).__name__, str(e), tb))

    def _execute_task(self, spec: TaskSpec):
        from ray_tpu._internal import otel

        # visible to the RPC loop thread for cancel_task (the exec context
        # is a threading.local, so it can't serve cross-thread lookups)
        self._exec_thread_ident = threading.get_ident()
        self._running_normal_task = spec.task_id
        t0 = time.perf_counter()
        self._emit_task_event(spec, "RUNNING")
        # execution span parents remotely on the submitter's span: one
        # trace id across the whole task tree (ref: _private/tracing
        # _wrap_task_execution). No-op context when tracing is off.
        try:
            with otel.execute_span(
                    spec.name or "task", getattr(spec, "trace_ctx", None),
                    task_id=spec.task_id.hex()) as sp:
                out = self._execute_task_body(spec)
                sp["ok"] = not (isinstance(out, tuple) and out
                                and out[0] == "task_error")
        finally:
            self._running_normal_task = None
        dur = time.perf_counter() - t0
        if not (isinstance(out, tuple) and out and out[0] == "task_error"):
            self._emit_task_event(spec, "FINISHED")
        # (FAILED was emitted at the catch site with the live exception)
        self._observe_exec_latency(dur, "task")
        return out

    @staticmethod
    def _observe_exec_latency(dur_s: float, kind: str):
        try:
            from ray_tpu.util import builtin_metrics as _bm

            _bm.task_exec_latency.observe(dur_s, tags={"kind": kind})
        except Exception:
            pass

    def rpc_cancel_task(self, conn, arg):
        """Worker-side cancel (ref analog: CoreWorker::HandleCancelTask).

        Non-force: raise TaskCancelledError asynchronously in the executor
        thread — delivered between bytecodes, so C-blocked calls (sleep,
        IO) keep running until they return (reference has the same
        limitation). Force: kill this worker process shortly after the
        reply flushes; the owner maps the resulting connection loss to
        TaskCancelledError. A cancel that races task completion may land
        after the body returns — the in-flight result is then dropped via
        the errored push reply, which cancellation semantics allow."""
        tid, force = arg
        if self._running_normal_task != tid:
            return False  # finished or never arrived; owner handles it
        if force:
            # NOTE: this process may hold device-plane results of EARLIER
            # tasks (lease reuse); they die with it and their owners fall
            # back to lineage reconstruction (api.cancel documents this)
            threading.Timer(0.05, os._exit, args=(1,)).start()
            return True
        ident = self._exec_thread_ident
        if ident is None:
            return False
        import ctypes

        ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(ident), ctypes.py_object(TaskCancelledError))
        # TOCTOU guard: if the body finished between our check and the
        # raise, the pending exception would fire in the idle executor
        # loop (killing the pooled thread) or inside the NEXT task.
        # Re-check and revoke (SetAsyncExc with NULL clears a pending
        # async exc); _ensure_executor_alive covers the residual window.
        if self._running_normal_task != tid:
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(ident), None)
            return False
        return True

    def _execute_task_body(self, spec: TaskSpec):
        self._exec_ctx.task_id = spec.task_id
        self._exec_ctx.job_id = spec.job_id
        restore_env = None
        try:
            restore_env = self._apply_runtime_env(spec)
            fn = cloudpickle.loads(spec.function_blob)
            args = self._resolve_args(spec.args)
            kwargs = self._resolve_args(spec.kwargs)
            result = fn(*args, **kwargs)
            if spec.num_returns == -1:
                return self._stream_returns(spec, result)
            return self._package_returns(spec, result)
        except Exception as e:
            tb = traceback.format_exc()
            self._emit_task_failed(spec, e, tb)
            return ("task_error", serialize_to_bytes(e), tb)
        finally:
            if restore_env is not None:
                try:
                    restore_env()
                except Exception:
                    pass
            self._exec_ctx.task_id = None
            self._exec_ctx.job_id = None

    def _resolve_args(self, args):
        if isinstance(args, dict):
            return {k: (self.get([ObjectRef(v.object_id, v.owner,
                                            _add_local_ref=False)])[0]
                        if isinstance(v, RefArg) else v)
                    for k, v in args.items()}
        return [self.get([ObjectRef(v.object_id, v.owner,
                                    _add_local_ref=False)])[0]
                if isinstance(v, RefArg) else v
                for v in args]

    def _package_returns(self, spec: TaskSpec, result):
        cfg = get_config()
        if spec.num_returns == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != spec.num_returns:
                raise ValueError(
                    f"task declared num_returns={spec.num_returns} but "
                    f"returned {len(values)} values")
        out = []
        for i, value in enumerate(values):
            oid = ObjectID.for_return(spec.task_id, i)
            if spec.tensor_transport and is_device_value(value):
                # device plane: the array never leaves this worker's HBM;
                # the owner records holder metadata only
                self.device_store.put(oid, value)
                out.append(("device", getattr(value, "nbytes", -1),
                            self.worker_info))
                continue
            try:
                chunks = serialize(value)
                size = serialized_size(chunks)
            except Exception as e:
                out.append(("inline", serialize_to_bytes(
                    TaskError(e, spec.name, traceback.format_exc())), True))
                continue
            if size > cfg.max_direct_call_object_size:
                # chunk list goes straight into the shm segment — the
                # return payload is never joined into a host-side blob
                self._shm_create_blocking(oid, chunks, size)
                try:
                    self.io.run(self.node_conn.call(
                        "object_created", (oid, size, spec.owner)))
                finally:
                    self._release_create_ref(oid)
                out.append(("shm", size))
            else:
                out.append(("inline", chunks_to_bytes(chunks), False))
        return ("ok", out)

    async def rpc_create_actor(self, conn, spec: TaskSpec):
        loop = asyncio.get_running_loop()
        opts = spec.actor_options
        if opts is not None and opts.max_concurrency > 1:
            # same leak as _ensure_executor_alive: the default 1-thread
            # executor this replaces is idle on a fresh worker — shut it
            # down rather than stranding its thread + queue
            old = self.executor
            self.executor = ThreadPoolExecutor(
                max_workers=opts.max_concurrency,
                thread_name_prefix="rayt-actor")
            try:
                old.shutdown(wait=False)
            except Exception:
                pass
        err = await loop.run_in_executor(
            None, self._instantiate_actor, spec)
        return err

    def _instantiate_actor(self, spec: TaskSpec) -> str | None:
        self._exec_ctx.task_id = spec.task_id
        self._exec_ctx.job_id = spec.job_id
        self._emit_task_event(spec, "RUNNING")
        try:
            self._apply_runtime_env(spec)
            cls = cloudpickle.loads(spec.function_blob)
            args = self._resolve_args(spec.args)
            kwargs = self._resolve_args(spec.kwargs)
            self.actor_instance = cls(*args, **kwargs)
            self.actor_id = spec.actor_id
            # async actors: methods that are coroutines (or async gens)
            # run on their own loop
            import inspect

            if any(asyncio.iscoroutinefunction(getattr(cls, m, None))
                   or inspect.isasyncgenfunction(getattr(cls, m, None))
                   for m in dir(cls) if not m.startswith("__")):
                self._actor_async_loop = EventLoopThread("rayt-actor-async")
            self._emit_task_event(spec, "FINISHED")
            return None
        except Exception as e:
            tb = traceback.format_exc()
            self._emit_task_failed(spec, e, tb)
            return tb
        finally:
            self._exec_ctx.task_id = None
            self._exec_ctx.job_id = None

    async def rpc_push_actor_task(self, conn, arg):
        """Ordered actor-task execution (ref: actor_scheduling_queue.cc).

        Ordering contract (mirrors the reference): calls from one caller
        *start* in seq order. With max_concurrency=1 the single executor
        thread makes start order == completion order (sequential actors);
        with max_concurrency>1 (threaded) or async methods, starts are
        ordered but bodies overlap — same as the reference's threaded/async
        actors (out_of_order_actor_scheduling_queue.cc)."""
        spec, caller_key = arg
        st = self._actor_seq_state.get(caller_key)
        if st is None:
            st = {"next": 0, "cond": asyncio.Condition()}
            self._actor_seq_state[caller_key] = st
        async with st["cond"]:
            await st["cond"].wait_for(lambda: st["next"] >= spec.seq_no)
            if st["next"] == spec.seq_no:
                st["next"] = spec.seq_no + 1
                st["cond"].notify_all()
        import inspect

        loop = asyncio.get_running_loop()
        method = getattr(self.actor_instance, spec.method_name, None)
        if asyncio.iscoroutinefunction(method) or \
                inspect.isasyncgenfunction(method):
            # async actor: runs concurrently on the actor's asyncio loop
            cfut = asyncio.run_coroutine_threadsafe(
                self._run_async_method(spec), self._actor_async_loop.loop)
            return await asyncio.wrap_future(cfut)
        # run_in_executor queues FIFO, so start order is preserved; the
        # executor's max_workers bounds actual concurrency
        return await loop.run_in_executor(
            self.executor, self._execute_actor_task, spec)

    async def _run_async_method(self, spec: TaskSpec):
        import inspect

        from ray_tpu._internal import otel

        self._exec_ctx.task_id = spec.task_id
        self._exec_ctx.job_id = spec.job_id
        self._emit_task_event(spec, "RUNNING")
        # span covers the async execution path too (trace ids stay
        # consistent; interleaved async spans are handled by the
        # tracer's entry-removal discipline)
        with otel.execute_span(
                spec.method_name or "actor_task",
                getattr(spec, "trace_ctx", None),
                task_id=spec.task_id.hex(),
                actor_id=(self.actor_id.hex()
                          if self.actor_id else "")) as sp:
            try:
                method = getattr(self.actor_instance, spec.method_name)
                args = self._resolve_args_async(spec.args)
                kwargs = self._resolve_args_async(spec.kwargs)
                if spec.num_returns == -1 and \
                        inspect.isasyncgenfunction(method):
                    out = await self._stream_returns_async(
                        spec, method(*args, **kwargs))
                    self._emit_task_event(spec, "FINISHED")
                    return out
                result = await method(*args, **kwargs)
                if spec.num_returns == -1:
                    out = await self._stream_returns_async(spec, result)
                    self._emit_task_event(spec, "FINISHED")
                    return out
                out = self._package_returns(spec, result)
                self._emit_task_event(spec, "FINISHED")
                return out
            except Exception as e:
                sp["ok"] = False
                tb = traceback.format_exc()
                self._emit_task_failed(spec, e, tb)
                return ("task_error", serialize_to_bytes(e), tb)
            finally:
                self._exec_ctx.task_id = None
                self._exec_ctx.job_id = None

    def _resolve_args_async(self, args):
        # async path: refs resolved via blocking get on a worker thread would
        # deadlock the actor loop only if it waited on itself; args are
        # resolved eagerly here via the IO loop (cheap for inline objects).
        return self._resolve_args(args)

    def _execute_actor_task(self, spec: TaskSpec):
        from ray_tpu._internal import otel

        t0 = time.perf_counter()
        self._emit_task_event(spec, "RUNNING")
        with otel.execute_span(
                spec.method_name or "actor_task",
                getattr(spec, "trace_ctx", None),
                task_id=spec.task_id.hex(),
                actor_id=(self.actor_id.hex()
                          if self.actor_id else "")) as sp:
            out = self._execute_actor_task_body(spec)
            sp["ok"] = not (isinstance(out, tuple) and out
                            and out[0] == "task_error")
        dur = time.perf_counter() - t0
        if not (isinstance(out, tuple) and out and out[0] == "task_error"):
            self._emit_task_event(spec, "FINISHED")
        self._observe_exec_latency(dur, "actor")
        return out

    def _execute_actor_task_body(self, spec: TaskSpec):
        self._exec_ctx.task_id = spec.task_id
        self._exec_ctx.job_id = spec.job_id
        try:
            if self.actor_instance is None:
                raise RuntimeError("actor not initialized")
            method = getattr(self.actor_instance, spec.method_name, None)
            if method is None and spec.method_name == "__rayt_apply__":
                # runtime escape hatch: run fn(actor_instance, *args) on
                # the actor without requiring the user class to define it
                # (the compiled-DAG executor loop rides this; ref analog:
                # __ray_call__ in python/ray/actor.py)
                inst = self.actor_instance
                method = lambda fn, *a, **k: fn(inst, *a, **k)  # noqa: E731
            if method is None:
                raise AttributeError(
                    f"actor has no method {spec.method_name!r}")
            args = self._resolve_args(spec.args)
            kwargs = self._resolve_args(spec.kwargs)
            result = method(*args, **kwargs)
            if spec.num_returns == -1:
                return self._stream_returns(spec, result)
            return self._package_returns(spec, result)
        except Exception as e:
            tb = traceback.format_exc()
            self._emit_task_failed(spec, e, tb)
            return ("task_error", serialize_to_bytes(e), tb)
        finally:
            self._exec_ctx.task_id = None
            self._exec_ctx.job_id = None

    async def _task_event_flush_loop(self):
        """Ship buffered task events to the GCS ring every second (ref:
        task_event_buffer.cc periodic flush to gcs_task_manager)."""
        while not self._shutdown:
            await asyncio.sleep(1.0)
            # piggyback: release shm get-pins whose last holder died on a
            # thread that couldn't drain (reentrant/contended at the time)
            self._drain_pin_events()
            events = self.task_events.drain()
            if not events:
                continue
            try:
                await self.gcs.call("add_task_events", events)
            except Exception:
                pass  # dropped on GCS hiccup: tracing is best-effort

    def rpc_exit_worker(self, conn, arg=None):
        def _die():
            os._exit(0)
        threading.Timer(0.1, _die).start()
        return True

    def rpc_dump_stacks(self, conn, arg=None):
        """All-thread stack dump (ref analog: `ray stack` via py-spy —
        here cooperative via sys._current_frames, no ptrace needed)."""
        import traceback as tb

        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        out = []
        for ident, frame in frames.items():
            out.append({
                "thread": names.get(ident, str(ident)),
                "stack": "".join(tb.format_stack(frame)),
            })
        return {"pid": os.getpid(), "worker_id": self.worker_id.hex(),
                "actor_id": self.actor_id.hex() if self.actor_id else None,
                "threads": out}

    async def rpc_profile_worker(self, conn, arg=None):
        """On-demand self-profiling (ref: dashboard profile_manager
        py-spy/memray attach — cooperative here, no ptrace): mode "cpu"
        samples all threads' stacks, mode "memory" opens a tracemalloc
        window. Runs on an executor thread so the IO loop keeps serving."""
        from ray_tpu._internal import profiler

        arg = arg or {}
        mode = arg.get("mode", "cpu")
        duration = float(arg.get("duration_s", 5.0))
        loop = asyncio.get_running_loop()
        if mode == "memory":
            return await loop.run_in_executor(
                None, profiler.sample_memory, duration,
                int(arg.get("top_n", 25)))
        return await loop.run_in_executor(
            None, profiler.sample_cpu, duration,
            float(arg.get("interval_s", 0.01)))

    def rpc_worker_stats(self, conn, arg=None):
        return {
            "worker_id": self.worker_id.hex(),
            "mode": self.mode,
            "actor_id": self.actor_id.hex() if self.actor_id else None,
            "num_pending_tasks": sum(
                1 for t in self.pending_tasks.values() if not t.done),
            "memory_store_size": len(self.memory_store),
            "refcount": self.reference_counter.stats(),
        }


class _ActorTaskSubmitter:
    """Per-actor ordered submission pipeline (ref: actor_task_submitter.h:75).

    Calls are pipelined: each gets a seq_no; the receiver reorders. The
    submitter tracks actor liveness via GCS pubsub and queues while the
    actor is PENDING/RESTARTING."""

    def __init__(self, cw: CoreWorker, actor_id: ActorID):
        self.cw = cw
        self.actor_id = actor_id
        self.seq = 0
        self.state = ActorState.PENDING
        self.address: Address | None = None
        self.node_id: NodeID | None = None
        self.death_cause = ""
        self._resolved = asyncio.Event()
        self._resolve_started = False
        # address observed to be dead (connection refused/lost); GCS may lag
        # behind the death, so an ALIVE report at this address is stale
        self._avoid_address: Address | None = None

    async def _ensure_resolved(self):
        if not self._resolve_started:
            self._resolve_started = True
            self.cw._spawn(self._resolve_loop())
        await self._resolved.wait()

    async def _resolve_loop(self):
        while True:
            try:
                res = await self.cw.gcs.actor_handle_state(self.actor_id)
            except Exception:
                await asyncio.sleep(0.25)
                continue
            if res is None:
                await asyncio.sleep(0.25)
                continue
            state, address, death_cause, _, node_id = res
            self.state = state
            self.death_cause = death_cause
            if state == ActorState.ALIVE and address is not None \
                    and address == self._avoid_address:
                # stale ALIVE record for an endpoint we saw die
                await asyncio.sleep(0.25)
                continue
            if state == ActorState.ALIVE and address is not None:
                if address != self.address:
                    self.seq = 0  # fresh incarnation: restart ordering
                self.address = address
                self.node_id = node_id
                self._resolved.set()
                return
            if state == ActorState.DEAD:
                self._resolved.set()
                return
            # PENDING/RESTARTING: pubsub (on_actor_update) delivers the
            # transition promptly; this poll is only a lost-event fallback
            await asyncio.sleep(0.25)

    async def on_actor_update(self, info):
        self.state = info.state
        self.death_cause = info.death_cause
        if info.state == ActorState.ALIVE and info.address is not None:
            if info.address == self._avoid_address:
                return
            if info.address != self.address:
                self.seq = 0
            self.address = info.address
            self.node_id = info.node_id
            self._resolved.set()
        elif info.state == ActorState.DEAD:
            self.address = None
            self._resolved.set()
        elif info.state == ActorState.RESTARTING:
            self.address = None
            self._resolved.clear()
            self.cw._spawn(self._resolve_loop())

    async def submit(self, spec: TaskSpec):
        attempts = spec.max_retries + 1
        while attempts > 0:
            attempts -= 1
            await self._ensure_resolved()
            if self.state == ActorState.DEAD:
                self.cw._fail_task(spec, ActorDiedError(
                    self.actor_id, self.death_cause))
                return
            # seq assigned synchronously post-resolution so pipelined calls
            # from this caller reach the current incarnation in order
            spec.seq_no = self.seq
            self.seq += 1
            address = self.address
            spec.attempt = spec.max_retries - attempts
            self.cw._emit_task_event(spec, "SCHEDULED")
            try:
                self.cw._emit_task_event(spec, "DISPATCHED")
                conn = await self.cw._conn_to(address)
                reply = await conn.call(
                    "push_actor_task",
                    (spec, self.cw.worker_info.address.key()),
                    timeout=_TASK_PUSH_TIMEOUT)
            except (ConnectionLost, RpcError, OSError) as e:
                # actor worker died mid-call; wait for GCS verdict. Don't
                # trust ALIVE records still pointing at the dead endpoint.
                self._avoid_address = address
                self.address = None
                self._resolved.clear()
                self.cw._spawn(self._resolve_loop())
                if attempts > 0:
                    continue
                self.cw._fail_task(spec, ActorDiedError(
                    self.actor_id, f"connection lost: {e}"))
                return
            if reply[0] == "task_error":
                _, err_blob, tb = reply
                try:
                    cause = deserialize(err_blob)
                except Exception as e:
                    cause = RuntimeError(f"undeserializable error: {e}")
                self.cw._fail_task(spec, TaskError(cause, spec.name, tb))
                return
            winfo = WorkerInfo(WorkerID.nil(),
                               self.node_id or self.cw.node_id, address)
            self.cw._complete_task(spec, reply[1], winfo)
            return
