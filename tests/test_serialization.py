import numpy as np
import pytest

from ray_tpu._internal.serialization import (deserialize, serialize,
                                             serialize_to_bytes,
                                             serialized_size)


def test_roundtrip_simple():
    for obj in [1, "x", {"a": [1, 2, (3, None)]}, b"bytes", 3.5, True]:
        assert deserialize(serialize_to_bytes(obj)) == obj


def test_roundtrip_numpy_zero_copy():
    arr = np.arange(1 << 16, dtype=np.float32).reshape(256, 256)
    blob = serialize_to_bytes({"w": arr, "tag": "t"})
    out = deserialize(blob)
    np.testing.assert_array_equal(out["w"], arr)
    # the deserialized array must be a view over the input buffer, not a copy
    assert not out["w"].flags.owndata


def test_chunks_size_accounting():
    arr = np.ones(1000, dtype=np.int64)
    chunks = serialize(arr)
    assert serialized_size(chunks) == len(b"".join(bytes(c) for c in chunks))


def test_lambda_and_closure():
    y = 41

    def f(x):
        return x + y

    g = deserialize(serialize_to_bytes(f))
    assert g(1) == 42


def test_exception_roundtrip():
    try:
        raise ValueError("boom")
    except ValueError as e:
        err = e
    out = deserialize(serialize_to_bytes(err))
    assert isinstance(out, ValueError) and str(out) == "boom"


def test_unaligned_buffer_sizes():
    for n in [1, 7, 8, 9, 127]:
        arr = np.frombuffer(bytes(range(n % 256)) * 1, dtype=np.uint8) if n < 256 else None
        arr = np.arange(n, dtype=np.uint8)
        out = deserialize(serialize_to_bytes([arr, arr]))
        np.testing.assert_array_equal(out[0], arr)
        np.testing.assert_array_equal(out[1], arr)
