"""Node drain lifecycle: the deadline-bound drain protocol (ref analog:
DrainNodeRequest + the autoscaler v2 drain path, extended with proactive
migration).

Covers: rt.drain_node migrates restartable actors make-before-break and
stops new placement; placement groups with a bundle on a DEAD node
reschedule their gang onto live nodes (the stale-placement regression);
a PENDING PG whose client stopped polling is pruned on the config-knob
window with a WARNING event; a node re-registering after a COMPLETED
drain sheds the draining label, while a head restart MID-drain restores
DRAINING state and resumes the migration; the preemption-notice file
self-initiates a drain; drain events surface through the state API and
the `rayt status` renderer.
"""

from __future__ import annotations

import json
import os
import time

import pytest

import ray_tpu as rt
from ray_tpu import state_api
from ray_tpu.cluster_utils import Cluster


def _wait_drained(node_hex: str, timeout_s: float = 60.0) -> dict:
    deadline = time.monotonic() + timeout_s
    rec = None
    while time.monotonic() < deadline:
        try:  # tolerate a reconnect window mid-poll (head bounce tests)
            rec = state_api.drain_status().get(node_hex)
        except Exception:
            rec = None
        if rec is not None and rec.get("state") == "DRAINED":
            return rec
        time.sleep(0.2)
    raise TimeoutError(f"node {node_hex} never reached DRAINED: {rec}")


@pytest.fixture
def _config_env(monkeypatch):
    """Apply RAYT_* env overrides to this process AND (via
    RAYT_CONFIG_JSON at spawn) to cluster children."""
    from ray_tpu._internal import config as cfg_mod

    old = cfg_mod._config

    def apply(**env):
        for k, v in env.items():
            monkeypatch.setenv(k, v)
        cfg_mod.set_config(cfg_mod.load_config())

    yield apply
    cfg_mod._config = old


# ------------------------------------------------------- tentpole drill
def test_drain_migrates_actor_and_stops_placement(capsys):
    """rt.drain_node: the restartable actor on the draining node fails
    over to the other blue node while the old instance still runs (make
    before break), new blue demand lands elsewhere, the record flips to
    DRAINED, and the events + status surfaces tell the story."""
    with Cluster(head_resources={"CPU": 2.0}) as cluster:
        node_b = cluster.add_node(num_cpus=2, resources={"blue": 2.0})
        cluster.connect()

        @rt.remote(num_cpus=1, resources={"blue": 1.0}, max_restarts=-1)
        class Pinned:
            def where(self):
                return os.environ["RAYT_NODE_ID"]

        a = Pinned.remote()
        assert rt.get(a.where.remote(), timeout=90) == node_b.node_id_hex
        # replacement capacity arrives BEFORE the drain (the normal
        # preemption flow: autoscaler/operator provisions, then drains)
        node_c = cluster.add_node(num_cpus=2, resources={"blue": 2.0})

        assert rt.drain_node(node_b.node_id_hex, 60.0, "maintenance")
        rec = _wait_drained(node_b.node_id_hex)
        assert rec["reason"] == "maintenance"
        assert rec["migrated"]["actors"] >= 1

        # the actor survived the drain on the OTHER node
        assert rt.get(a.where.remote(), timeout=90) == node_c.node_id_hex
        # new placement for blue demand avoids the drained node
        @rt.remote(num_cpus=0.5, resources={"blue": 0.5})
        def where():
            return os.environ["RAYT_NODE_ID"]

        assert rt.get(where.remote(), timeout=90) == node_c.node_id_hex

        # events: node_draining + node_drained with the reason
        kinds = {}
        for e in state_api.list_cluster_events(severity="WARNING",
                                               limit=200):
            kinds.setdefault(e["kind"], e)
        assert "node_draining" in kinds
        assert "node_drained" in kinds
        assert kinds["node_draining"]["data"]["reason"] == "maintenance"
        assert "actors" in kinds["node_drained"]["data"]["migrated"]

        # the `rayt status` renderer shows the DRAINED row + drain line
        from ray_tpu.scripts.cli import _print_cluster_status

        _print_cluster_status(state_api.cluster_status())
        out = capsys.readouterr().out
        assert "DRAINED" in out
        assert "drains:" in out


# --------------------------------- satellite: stale-PG placement on death
def test_pg_reschedules_off_dead_node():
    """Regression: _on_node_lost used to leave placement_groups pointing
    at the dead node forever. Now the gang re-places (RESCHEDULING ->
    CREATED) and an actor scheduled into the PG lands on a LIVE node."""
    with Cluster(head_resources={"CPU": 2.0}) as cluster:
        node_b = cluster.add_node(num_cpus=2, resources={"red": 2.0})
        cluster.connect()
        pg = rt.placement_group([{"red": 1.0}], strategy="PACK",
                                timeout=60)
        assert pg.placement  # reserved on node_b (only red node)

        cluster.remove_node(node_b, graceful=False)
        node_c = cluster.add_node(num_cpus=2, resources={"red": 2.0})

        @rt.remote(num_cpus=0, resources={"red": 0.5}, max_restarts=0)
        class InPg:
            def where(self):
                return os.environ["RAYT_NODE_ID"]

        a = InPg.options(
            scheduling_strategy=pg.bundle_strategy(0)).remote()
        assert rt.get(a.where.remote(),
                      timeout=120) == node_c.node_id_hex
        rows = {p["placement_group_id"]: p
                for p in state_api.list_placement_groups()}
        assert rows[pg.id.hex()]["state"] == "CREATED"
        ev = state_api.list_cluster_events(
            kind="placement_group_rescheduled", limit=50)
        assert ev, "no placement_group_rescheduled event recorded"
        rt.remove_placement_group(pg)


# ------------------------------------ satellite: PENDING-PG prune knob
def test_pg_pending_prune_knob_and_event(_config_env):
    """An unsatisfiable PG whose client stops polling is pruned after
    the RAYT_PG_PENDING_POLL_TIMEOUT_S window (was a hardcoded 15s) and
    leaves a placement_group_pruned WARNING in the event log."""
    _config_env(RAYT_PG_PENDING_POLL_TIMEOUT_S="1.0")
    import ray_tpu

    ray_tpu.init(num_cpus=1)
    try:
        from ray_tpu._internal.ids import PlacementGroupID
        from ray_tpu.core.runtime import get_runtime_context

        cw = get_runtime_context().core_worker
        pg_id = PlacementGroupID.random()
        placement = cw.io.run(cw.gcs.conn.call(
            "create_placement_group", (pg_id, [{"CPU": 64.0}], "PACK")))
        assert placement is None  # infeasible -> PENDING
        time.sleep(1.3)           # client "gave up": poll gap > knob
        pending = cw.io.run(cw.gcs.conn.call("get_pending_demand"))
        assert pg_id not in [p["pg_id"]
                             for p in pending.get("placement_groups", [])]
        ev = state_api.list_cluster_events(kind="placement_group_pruned",
                                           limit=50)
        assert ev and ev[0]["data"]["placement_group_id"] == pg_id.hex()
    finally:
        ray_tpu.shutdown()


# ------------------- satellite: drain -> die -> re-register starts fresh
def test_reregister_after_completed_drain_clears_label(tmp_path):
    """A node re-registering after its drain COMPLETED must come back
    schedulable: the restored snapshot's draining label and the DRAINED
    record are both shed on register."""
    cluster = Cluster(gcs_only_head=True,
                      persist_path=str(tmp_path / "gcs.snap"))
    node = cluster.add_node(num_cpus=2, resources={"blue": 2.0})
    cluster.connect()
    try:
        assert rt.drain_node(node.node_id_hex, 10.0, "scale-in")
        _wait_drained(node.node_id_hex, timeout_s=30.0)
        time.sleep(0.5)                # snapshot flush (100ms debounce)
        cluster.kill_head(graceful=False)
        cluster.restart_head()
        # the node's reconnect loop re-registers it: fresh lifecycle
        deadline = time.monotonic() + 30.0
        entry = None
        while time.monotonic() < deadline:
            try:
                entry = {n["node_id"]: n for n in state_api.list_nodes()
                         }.get(node.node_id_hex)
            except Exception:  # reconnect window
                entry = None
            if entry is not None and entry["alive"]:
                break
            time.sleep(0.2)
        assert entry is not None and entry["alive"]
        assert "draining" not in entry["labels"]
        assert node.node_id_hex not in state_api.drain_status()

        @rt.remote(num_cpus=1, resources={"blue": 1.0})
        def where():
            return os.environ["RAYT_NODE_ID"]

        assert rt.get(where.remote(), timeout=90) == node.node_id_hex
    finally:
        cluster.shutdown()


# ---------------------- satellite: head restart mid-drain resumes drain
def test_head_restart_mid_drain_resumes_migration(tmp_path):
    """The GCS dies while a drain is migrating: the restored snapshot
    carries the DRAINING record, the re-registering node KEEPS its
    draining label, and the resumed coordinator finishes the migration
    (actor ends up ALIVE on the other node, record flips to DRAINED)."""
    cluster = Cluster(gcs_only_head=True,
                      persist_path=str(tmp_path / "gcs.snap"))
    node_b = cluster.add_node(num_cpus=2, resources={"blue": 2.0})
    cluster.connect()
    try:
        @rt.remote(num_cpus=1, resources={"blue": 1.0}, max_restarts=-1)
        class Slow:
            def __init__(self):
                time.sleep(2.0)   # keeps the migration in flight

            def where(self):
                return os.environ["RAYT_NODE_ID"]

        a = Slow.remote()    # only node_b has blue yet
        assert rt.get(a.where.remote(), timeout=90) == node_b.node_id_hex
        node_c = cluster.add_node(num_cpus=2, resources={"blue": 2.0})

        assert rt.drain_node(node_b.node_id_hex, 60.0, "preempt")
        time.sleep(0.6)  # coordinator enters phase 2; snapshot flushes
        rec = state_api.drain_status().get(node_b.node_id_hex)
        assert rec is not None and rec["state"] == "DRAINING"
        cluster.kill_head(graceful=False)
        cluster.restart_head()

        rec = _wait_drained(node_b.node_id_hex, timeout_s=60.0)
        assert rec["reason"] == "preempt"
        assert rt.get(a.where.remote(),
                      timeout=120) == node_c.node_id_hex
    finally:
        cluster.shutdown()


# ------------------------------------- preemption notice self-drain E2E
def test_preemption_notice_triggers_self_drain(tmp_path, _config_env):
    """The node manager polls the (TPU-maintenance-event stand-in)
    notice file and initiates its OWN drain: record appears with the
    notice's reason/deadline, a preemption_notice WARNING is logged,
    and the node ends DRAINED."""
    _config_env(
        RAYT_PREEMPTION_NOTICE_FILE=str(tmp_path / "notice-{node_id}"),
        RAYT_PREEMPTION_POLL_INTERVAL_S="0.1")
    with Cluster(head_resources={"CPU": 2.0}) as cluster:
        node = cluster.add_node(num_cpus=2)
        cluster.connect()
        with open(tmp_path / f"notice-{node.node_id_hex}", "w") as f:
            json.dump({"deadline_s": 30.0,
                       "reason": "maintenance event"}, f)
        rec = _wait_drained(node.node_id_hex, timeout_s=30.0)
        assert rec["reason"] == "maintenance event"
        ev = state_api.list_cluster_events(kind="preemption_notice",
                                           limit=50)
        assert ev and ev[0]["node_id"] == node.node_id_hex
