"""Multi-process crash stress for the native shm arena (VERDICT r3 #9).

N writer processes hammer one arena (create/seal/read with CRC-stamped
payloads) while the parent SIGKILLs them at random — including while they
hold the process-shared robust mutex. Afterwards the arena must still be
usable from a fresh process (EOWNERDEAD recovery via
pthread_mutex_consistent, ray_tpu/_native/shm_store.cpp:90) and every
object a writer RECORDED AS SEALED must read back bit-exact (ref analog:
plasma store crash tests / TSAN discipline, SURVEY.md §4).

Also covers the fallback-to-disk allocation path (plasma_allocator.cc
fallback mmaps): objects that outgrow the arena land in per-node files
and stay readable/unlinkable across processes.
"""

from __future__ import annotations

import os
import random
import signal
import subprocess
import sys
import time
import zlib

import pytest

from ray_tpu._internal.ids import ObjectID
from ray_tpu._native import NativeArenaStore, load_shm_lib

pytestmark = pytest.mark.skipif(load_shm_lib() is None,
                                reason="native toolchain unavailable")

_WRITER = r"""
import os, random, sys, time, zlib
sys.path.insert(0, {repo!r})
from ray_tpu._internal.ids import ObjectID
from ray_tpu._native import NativeArenaStore

name, manifest_dir, seed = sys.argv[1], sys.argv[2], int(sys.argv[3])
rng = random.Random(seed)
store = NativeArenaStore(name, {capacity})
manifest = open(os.path.join(manifest_dir, f"w{{seed}}.log"), "a")
while True:
    oid = ObjectID.random()
    size = rng.randrange(256, 8192)
    payload = bytes([rng.randrange(256)]) * size
    if not store.create_unsealed(oid, size):
        continue
    store.write_at(oid, 0, payload)
    store.seal(oid)
    # record AFTER seal: every recorded object must be consistent
    manifest.write(f"{{oid.hex()}},{{size}},{{zlib.crc32(payload)}}\n")
    manifest.flush()
    # read back a random earlier object of OURS and verify
    try:
        data = store.read_bytes(oid, size)
        assert zlib.crc32(data) == zlib.crc32(payload), "self readback"
    except KeyError:
        pass  # evicted under pressure: fine
"""


def test_crash_storm_keeps_arena_consistent(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    name = f"raytstress_{ObjectID.random().hex()[:8]}"
    capacity = 4 << 20
    script = tmp_path / "writer.py"
    script.write_text(_WRITER.format(repo=repo, capacity=capacity))
    owner = NativeArenaStore(name, capacity)  # keeps the segment alive
    procs: list = []
    rng = random.Random(0)
    try:
        def spawn(seed):
            return subprocess.Popen(
                [sys.executable, str(script), name, str(tmp_path),
                 str(seed)],
                stdout=subprocess.DEVNULL,
                stderr=open(os.path.join(str(tmp_path),
                                         f"err{seed}.txt"), "wb"))

        def manifest_lines() -> int:
            return sum(len(mf.read_text().splitlines())
                       for mf in tmp_path.glob("w*.log"))

        seed = 0
        for _ in range(3):
            procs.append(spawn(seed))
            seed += 1
        # wait until writers are past interpreter startup and actually
        # mutating the arena — killing mid-import proves nothing
        deadline = time.monotonic() + 60.0
        while manifest_lines() < 50 and time.monotonic() < deadline:
            time.sleep(0.2)
        assert manifest_lines() >= 50, "writers never started"
        kills = 0
        for _ in range(8):
            time.sleep(rng.uniform(0.3, 0.8))  # mid-critical-section odds
            victim = procs[0]  # oldest: certainly inside the write loop
            victim.kill()      # SIGKILL while possibly holding the mutex
            victim.wait()
            kills += 1
            procs.remove(victim)
            procs.append(spawn(seed))
            seed += 1
        assert kills >= 5
        for p in procs:
            p.kill()
            p.wait()

        # ---- recovery: the arena must be fully usable from here on ----
        # (this get/create path takes the robust mutex; a dead owner's
        # lock must have been marked consistent)
        sealed = []
        for mf in tmp_path.glob("w*.log"):
            for line in mf.read_text().splitlines():
                h, size, crc = line.split(",")
                sealed.append((h, int(size), int(crc)))
        assert len(sealed) > 20, "writers made no progress"
        verified = 0
        for h, size, crc in sealed:
            oid = ObjectID.from_hex(h)
            if not owner.contains_locally(oid):
                continue  # evicted: allowed
            data = owner.read_bytes(oid, size)
            assert zlib.crc32(data) == crc, f"corrupt object {h}"
            verified += 1
        assert verified > 0, "every sealed object was evicted?"
        # allocator still works after the storm
        for i in range(25):
            oid = ObjectID.random()
            payload = bytes([i % 256]) * 4096
            owner.create_from_bytes(oid, payload)
            assert owner.read_bytes(oid, 4096) == payload
    finally:
        for p in procs:
            try:
                p.kill()
            except Exception:
                pass
        owner.close()
        NativeArenaStore.destroy(name)


def test_fallback_to_disk_allocation():
    name = f"raytfb_{ObjectID.random().hex()[:8]}"
    store = NativeArenaStore(name, 256 * 1024)   # tiny arena
    try:
        big = os.urandom(512 * 1024)              # 2x the arena
        oid = ObjectID.random()
        n = store.create_from_bytes(oid, big)
        assert n == len(big)
        assert store.contains_locally(oid)
        assert store.read_bytes(oid, len(big)) == big
        # visible from a SECOND process attaching the same arena
        other = NativeArenaStore(name, 256 * 1024)
        try:
            assert other.contains_locally(oid)
            assert other.read_bytes(oid, len(big)) == big
        finally:
            other.close()
        store.unlink(oid)
        assert not store.contains_locally(oid)
        # chunked unsealed path falls back too
        oid2 = ObjectID.random()
        assert store.create_unsealed(oid2, len(big))
        store.write_at(oid2, 0, big[:100_000])
        store.write_at(oid2, 100_000, big[100_000:])
        store.seal(oid2)
        assert store.read_bytes(oid2, len(big)) == big
    finally:
        store.close()
        NativeArenaStore.destroy(name)
