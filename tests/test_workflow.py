"""Durable workflows: checkpoint-per-step, resume skips finished steps
(ref analog: python/ray/workflow/tests; executor at
workflow_executor.py:32)."""

import pytest

import ray_tpu as rt
from ray_tpu import workflow


def test_workflow_runs_dag_and_checkpoints(local_cluster, tmp_path):
    @workflow.step
    def double(x):
        return x * 2

    @workflow.step
    def add(a, b):
        return a + b

    d1 = double.bind(3)
    d2 = double.bind(4)
    final = add.bind(d1, d2)
    out = workflow.run(final, workflow_id="wf1", storage=str(tmp_path))
    assert out == 14
    assert workflow.get_output("wf1", storage=str(tmp_path)) == 14
    metas = workflow.list_workflows(storage=str(tmp_path))
    assert metas[0]["status"] == "SUCCESSFUL"


def test_workflow_resume_skips_checkpointed_steps(local_cluster, tmp_path):
    marker = tmp_path / "ran"

    @workflow.step
    def expensive():
        # side-effect file counts executions across run + resume
        with open(marker, "a") as f:
            f.write("x")
        return 10

    @workflow.step
    def flaky(x, fail_file):
        import os

        if os.path.exists(fail_file):
            raise RuntimeError("boom")
        return x + 1

    fail_file = str(tmp_path / "fail")
    open(fail_file, "w").close()
    final = flaky.bind(expensive.bind(), fail_file)

    with pytest.raises(Exception):
        workflow.run(final, workflow_id="wf2", storage=str(tmp_path))
    assert marker.read_text() == "x"  # expensive ran once, checkpointed
    meta = workflow.list_workflows(storage=str(tmp_path))
    assert any(m.get("status") == "FAILED" for m in meta)

    import os

    os.remove(fail_file)  # heal the failure, then resume
    out = workflow.resume("wf2", final, storage=str(tmp_path))
    assert out == 11
    assert marker.read_text() == "x"  # NOT re-executed on resume


def test_workflow_step_identity_invalidates_downstream(local_cluster,
                                                       tmp_path):
    @workflow.step
    def src(v):
        return v

    @workflow.step
    def sink(x):
        return x * 100

    a = sink.bind(src.bind(1))
    b = sink.bind(src.bind(2))
    # different plain args -> different step ids for BOTH levels
    assert a.step_id() != b.step_id()
    assert a.upstream()[0].step_id() != b.upstream()[0].step_id()
    assert workflow.run(a, workflow_id="wf3",
                        storage=str(tmp_path)) == 100
    assert workflow.run(b, workflow_id="wf3",
                        storage=str(tmp_path)) == 200


def test_workflow_independent_branches_run_concurrently(local_cluster,
                                                        tmp_path):
    """Steps with no dependency between them are submitted together:
    the two branches' execution intervals overlap (load-immune check —
    each step records its own start/end wall-clock)."""
    import time

    @workflow.step
    def slow(tag):
        start = time.time()
        time.sleep(1.2)
        return {"tag": tag, "start": start, "end": time.time()}

    @workflow.step
    def join(a, b):
        return [a, b]

    # warm the worker pool so boot latency doesn't mask submission overlap
    warm = rt.remote(num_cpus=1)(lambda: time.sleep(0.3))
    rt.get([warm.remote() for _ in range(2)])

    final = join.bind(slow.bind(1), slow.bind(2))
    a, b = workflow.run(final, workflow_id="wfpar", storage=str(tmp_path))
    assert {a["tag"], b["tag"]} == {1, 2}
    overlap = min(a["end"], b["end"]) - max(a["start"], b["start"])
    assert overlap > 0, f"branch intervals did not overlap ({overlap:.2f}s)"


def test_workflow_continuation_nested(local_cluster, tmp_path):
    """A step can return workflow.continuation(sub_dag): the sub-workflow
    runs under the same durable store and its result becomes the step's
    (ref: ray.workflow continuation / nested workflows)."""
    from ray_tpu.workflow import continuation

    @workflow.step
    def leaf(x):
        return x + 1

    @workflow.step
    def outer(x):
        return continuation(leaf.bind(x * 10))

    out = workflow.run(outer.bind(3), workflow_id="wfnest",
                       storage=str(tmp_path))
    assert out == 31
    # the nested step checkpointed individually under the same store
    metas = list((tmp_path / "wfnest" / "steps").glob("leaf-*.pkl"))
    assert metas


def test_workflow_events(local_cluster, tmp_path):
    """wait_for_event parks the workflow until send_event delivers a
    durable payload; resume replays the recorded event."""
    import threading
    import time

    @workflow.step
    def combine(evt, base):
        return f"{base}-{evt}"

    final = combine.bind(workflow.wait_for_event("go"), "ready")

    def deliver():
        time.sleep(1.0)
        workflow.send_event("wfevt", "go", "signal-7",
                            storage=str(tmp_path))

    t = threading.Thread(target=deliver)
    t.start()
    out = workflow.run(final, workflow_id="wfevt", storage=str(tmp_path))
    t.join()
    assert out == "ready-signal-7"
    # resume replays the checkpointed event without waiting
    assert workflow.resume("wfevt", final,
                           storage=str(tmp_path)) == "ready-signal-7"

    # timeout path
    final2 = combine.bind(workflow.wait_for_event("never", timeout_s=0.5),
                          "x")
    with pytest.raises(Exception):
        workflow.run(final2, workflow_id="wfevt2", storage=str(tmp_path))
