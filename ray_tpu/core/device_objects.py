"""Device-resident objects: ObjectRefs whose payload lives in
accelerator memory (HBM on TPU) instead of the host object store.

Ref analog: the reference's GPU-tensor channels
(python/ray/experimental/channel/torch_tensor_nccl_channel.py,
core_worker/experimental_mutable_object_manager.cc) — tensors move
worker-to-worker without a host pickle bounce. The TPU-native design
differs structurally: the *intra-mesh* device plane is XLA collectives
inside one jit (SPMD), so what an MPMD runtime needs is (a) zero-copy
handoff within a process, and (b) a host-staged transfer between
worker processes (same host or across DCN) that never pickles the
device buffer — raw shard bytes + dtype/shape/sharding metadata.

The holder of a device object is a WORKER PROCESS (not a node): the
payload sits in that process's jax client. `rt.get` in the holder
returns the same jax.Array object; `rt.get` elsewhere fetches raw bytes
from the holder over RPC and `jax.device_put`s locally. Sharded arrays
are gathered to host on the holder; the consumer rebuilds an unsharded
array and re-shards onto its own mesh (a per-shard streamed path is a
future optimization).
"""

from __future__ import annotations

import threading
from typing import Any

from ray_tpu._internal.ids import ObjectID


def is_device_value(value: Any) -> bool:
    """True for jax.Array values that should ride the device plane."""
    try:
        import jax
    except Exception:  # pragma: no cover - jax is a hard dep in practice
        return False
    return isinstance(value, jax.Array)


def host_shard_view(arr):
    """jax.Array -> host numpy view of its payload, WITHOUT the full
    gather when one addressable shard already covers the whole array
    (single-shard, or fully replicated — the common case for weights):
    ship that shard's bytes directly instead of routing through jax's
    gather path. Truly sharded arrays still gather to host — the
    cross-process plane is host-staged by design (ICI transfers happen
    inside jit, not here)."""
    import numpy as np

    shards = getattr(arr, "addressable_shards", None)
    if shards:
        try:
            one = shards[0].data
            covers = (tuple(one.shape) == tuple(arr.shape)
                      and (len(shards) == 1
                           or bool(getattr(arr, "is_fully_replicated",
                                           False))))
        except Exception:
            covers = False
        if covers:
            return np.asarray(one)  # zero-copy on CPU clients
    return np.asarray(arr)  # device_get; gathers sharded arrays


def serialize_array(arr) -> tuple:
    """jax.Array -> (raw host bytes, dtype str, shape). Single-shard /
    fully-replicated arrays ship one addressable shard's bytes (see
    host_shard_view); only truly sharded arrays gather to host."""
    np_val = host_shard_view(arr)
    return (np_val.tobytes(), str(np_val.dtype), np_val.shape)


def deserialize_array(payload: tuple):
    """(bytes, dtype, shape) -> jax.Array on the local default device."""
    import jax
    import numpy as np
    from ml_dtypes import bfloat16  # noqa: F401 (registers dtype strings)

    raw, dtype, shape = payload
    np_val = np.frombuffer(raw, dtype=np.dtype(dtype)).reshape(shape)
    return jax.device_put(np_val)


class DeviceObjectStore:
    """Per-process table of device-resident objects (oid -> jax.Array).

    The jax client keeps the buffers alive; dropping the table entry
    releases the HBM. Thread-safe: puts come from executor threads,
    fetches from the IO loop.
    """

    def __init__(self):
        self._objects: dict[ObjectID, Any] = {}
        self._lock = threading.Lock()

    def put(self, oid: ObjectID, value: Any):
        with self._lock:
            self._objects[oid] = value

    def get(self, oid: ObjectID):
        with self._lock:
            return self._objects.get(oid)

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._objects

    def delete(self, oid: ObjectID):
        with self._lock:
            self._objects.pop(oid, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._objects)

    def nbytes(self) -> int:
        with self._lock:
            return sum(getattr(v, "nbytes", 0)
                       for v in self._objects.values())
