"""Sequence/context parallelism: ring attention and Ulysses.

The reference has NO sequence parallelism (SURVEY.md §2.4 — verified
absent); for the TPU build it is a core op. Two schemes, both expressed
over the `seq` mesh axis inside shard_map:

* Ring attention (`ring_attention`): K/V shards rotate around the ICI
  ring via `ppermute` while each device accumulates blockwise
  online-softmax attention for its resident Q shard. Memory O(s/N),
  compute overlapped with neighbor transfers by XLA's async collective
  scheduling. (Liu et al. 2023 — blockwise parallel transformers.)

* Ulysses (`ulysses_attention`): `all_to_all` re-shards seq -> heads so
  each device sees the full sequence for h/N heads, runs dense (flash)
  attention locally, and all_to_alls back. Cheaper at moderate seq
  lengths, requires n_heads % seq_parallelism == 0.

Both are callable only inside shard_map with the axis bound; the model
layer wraps them (ray_tpu/models/llama.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _block_stats(q, k, v, q_offset, k_offset, causal, scale):
    """One blockwise attention step, returning online-softmax stats.

    q: [b, sq, h, d], k/v: [b, sk, h, d] (kv already GQA-expanded or
    head counts equal). Returns m [b,h,sq,1], l [b,h,sq,1], pv [b,sq,h,d].
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = k_offset + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)                   # [b,h,sq,1]
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(s - m_safe)
    p = jnp.where(m <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)                   # [b,h,sq,1]
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return m_safe, l, pv


def _repeat_kv(x, n_rep):
    if n_rep == 1:
        return x
    b, s, hk, d = x.shape
    return jnp.broadcast_to(
        x[:, :, :, None, :], (b, s, hk, n_rep, d)).reshape(b, s, hk * n_rep, d)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = "seq", *, causal: bool = True,
                   scale: float | None = None) -> jax.Array:
    """Call inside shard_map with seq sharded over `axis_name`.

    q/k/v: [b, s_local, h|hk, d]. Returns [b, s_local, h, d].
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    n_rep = h // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    if scale is None:
        scale = d ** -0.5
    q_offset = idx * s_local

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, t):
        k_blk, v_blk, m, l, acc = carry
        src = (idx - t) % n
        k_offset = src * s_local
        m_i, l_i, pv_i = _block_stats(q, k_blk, v_blk, q_offset, k_offset,
                                      causal, scale)
        m_new = jnp.maximum(m, m_i)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_i - m_new)
        l_new = alpha * l + beta * l_i
        # pv_i was computed against m_i; rescale into the new basis
        acc_new = acc * alpha.transpose(0, 2, 1, 3) + \
            pv_i * beta.transpose(0, 2, 1, 3)
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, m_new, l_new, acc_new), None

    m0 = jnp.full((b, h, s_local, 1), NEG_INF / 2, jnp.float32)
    l0 = jnp.zeros((b, h, s_local, 1), jnp.float32)
    acc0 = jnp.zeros((b, s_local, h, d), jnp.float32)
    (k_f, v_f, m_f, l_f, acc_f), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n))
    l_f = jnp.where(l_f == 0.0, 1.0, l_f)
    out = acc_f / l_f.transpose(0, 2, 1, 3)
    return out.astype(q.dtype)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str = "seq", *, causal: bool = True,
                      scale: float | None = None,
                      inner_impl: str = "xla") -> jax.Array:
    """All-to-all SP: re-shard seq->heads, dense attention, shard back.

    q: [b, s_local, h, d]; requires h % axis_size == 0. Call inside
    shard_map with `axis_name` bound.
    """
    n = jax.lax.psum(1, axis_name)
    b, s_local, h, d = q.shape
    n_rep = h // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)

    # [b, s_local, h, d] -> [b, n*s_local, h/n, d]
    def scatter_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def gather_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qg, kg, vg = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    from ray_tpu.ops.attention import xla_attention

    out = xla_attention(qg, kg, vg, causal=causal, scale=scale)
    return gather_heads(out).astype(q.dtype)
