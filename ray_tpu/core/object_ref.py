"""ObjectRef: a distributed future naming an immutable object.

Ref analog: python/ray/includes/object_ref + ownership model from
src/ray/core_worker/reference_count.h:66. Each ref embeds its owner's
address so any holder can resolve the object without a directory lookup.
Deserializing a ref in another process registers that process as a
borrower with the owner; dropping the last local Python reference sends a
release. The owner garbage-collects the object when local + borrower
counts hit zero.
"""

from __future__ import annotations

import weakref
from typing import TYPE_CHECKING, Optional

from ray_tpu._internal.ids import ObjectID

if TYPE_CHECKING:
    from ray_tpu.core.common import WorkerInfo

# The process-wide core worker, set by runtime bootstrap. ObjectRef talks to
# it for gets and ref-count events.
_core_worker = None


def set_core_worker(cw) -> None:
    global _core_worker
    _core_worker = cw


def get_core_worker():
    return _core_worker


class ObjectRef:
    __slots__ = ("id", "owner", "_released", "__weakref__")

    def __init__(self, object_id: ObjectID, owner: Optional["WorkerInfo"] = None,
                 *, _add_local_ref: bool = True):
        self.id = object_id
        self.owner = owner
        self._released = False
        cw = _core_worker
        if _add_local_ref and cw is not None:
            cw.reference_counter.add_local_ref(self)

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def get(self, timeout: float | None = None):
        cw = _core_worker
        if cw is None:
            raise RuntimeError("ray_tpu not initialized")
        return cw.get([self], timeout=timeout)[0]

    def __reduce__(self):
        # Serializing a ref hands it to another process: record the pass so
        # the receiving side is registered as a borrower.
        cw = _core_worker
        if cw is not None:
            cw.reference_counter.on_ref_serialized(self)
        return (_deserialize_ref, (self.id, self.owner))

    def __del__(self):
        if not self._released and _core_worker is not None:
            try:
                _core_worker.reference_counter.remove_local_ref(self)
            except Exception:
                pass

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __hash__(self):
        return hash(self.id)

    def __repr__(self):
        return f"ObjectRef({self.id.hex()[:16]})"

    # Allow `await ref` inside async actors.
    def __await__(self):
        import asyncio

        async def _get():
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(None, self.get)

        return _get().__await__()


def _deserialize_ref(object_id: ObjectID, owner) -> ObjectRef:
    ref = ObjectRef(object_id, owner, _add_local_ref=False)
    cw = _core_worker
    if cw is not None:
        cw.reference_counter.on_ref_deserialized(ref)
    return ref
