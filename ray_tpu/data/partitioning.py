"""Partition scheme <-> path mapping for partitioned file reads/writes
(ref analog: python/ray/data/datasource/partitioning.py —
`Partitioning`, `PathPartitionEncoder/Parser`).

Hive style encodes every field as ``col=value`` path segments
(``base/country=us/year=2024/part-....parquet``); directory style
encodes bare values in field order (``base/us/2024/...``). Values are
stringified on encode; parse best-effort casts numeric-looking values
back to int/float (standard hive-reader inference — note a zero-padded
string like ``"007"`` comes back as ``7``; use non-numeric values when
the spelling matters), everything else stays a string.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional
from urllib.parse import quote, unquote

from ray_tpu.data.block import Block, iter_rows


@dataclasses.dataclass(frozen=True)
class Partitioning:
    """A partition scheme: which columns key the directory tree and how
    they are spelled into it."""

    field_names: tuple
    style: str = "hive"  # "hive" (col=value) | "dir" (bare values)

    def __post_init__(self):
        object.__setattr__(self, "field_names", tuple(self.field_names))
        if self.style not in ("hive", "dir"):
            raise ValueError(f"unknown partition style {self.style!r}")
        if not self.field_names:
            raise ValueError("Partitioning requires at least one field")

    # ------------------------------------------------------------- encode
    def relpath(self, values: dict) -> str:
        """The partition directory (relative) for one field-value set."""
        parts = []
        for f in self.field_names:
            if f not in values:
                raise KeyError(f"partition field {f!r} missing from row")
            v = quote(str(values[f]), safe="")
            parts.append(f"{quote(str(f), safe='')}={v}"
                         if self.style == "hive" else v)
        return os.path.join(*parts)

    # -------------------------------------------------------------- parse
    def parse(self, path: str, base_dir: Optional[str] = None) -> dict:
        """Partition field values encoded in ``path`` (a file or dir path,
        absolute or relative to ``base_dir``). Unmatched fields are
        simply absent, so callers can detect non-partitioned files."""
        rel = os.path.relpath(path, base_dir) if base_dir else path
        segments = [s for s in rel.split(os.sep)
                    if s not in ("", ".", "..")]
        # drop a trailing FILENAME segment. Hive partition segments
        # always carry "=", so a dotted value dir ("ratio=0.5") is
        # never mistaken for a file; dir style has no such marker and
        # keeps the dotted-name heuristic.
        if segments and "." in segments[-1] and (
                self.style == "dir" or "=" not in segments[-1]):
            segments = segments[:-1]
        out: dict = {}
        if self.style == "hive":
            for seg in segments:
                if "=" not in seg:
                    continue
                k, _, v = seg.partition("=")
                k = unquote(k)
                if k in self.field_names:
                    out[k] = _auto_cast(unquote(v))
        else:
            # dir style: the LAST len(fields) segments are the values
            tail = segments[-len(self.field_names):]
            if len(tail) == len(self.field_names):
                for f, seg in zip(self.field_names, tail):
                    out[f] = _auto_cast(unquote(seg))
        return out


def _auto_cast(v: str):
    """Best-effort cast of a path-encoded partition value back to a
    scalar (hive readers do the same; strings stay strings)."""
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v)
    except ValueError:
        return v


def split_by_partition(block: Block,
                       partitioning: Partitioning) -> dict[str, list]:
    """Group a block's rows by their partition directory. Returns
    {relative partition dir -> rows with the partition fields REMOVED}
    (hive semantics: the path carries the values, the file doesn't)."""
    fields = set(partitioning.field_names)
    groups: dict[str, list] = {}
    for row in iter_rows(block):
        rel = partitioning.relpath(row)
        kept = {k: v for k, v in row.items() if k not in fields}
        groups.setdefault(rel, []).append(kept)
    return groups
