"""PPO CartPole benchmark: records learner throughput (samples/s) and
the return curve into RL_BENCH.json under "ppo_cartpole".

BASELINE config #1 (rllib/tuned_examples PPO on CartPole-v1) artifact:
the reference's tuned example targets return >=150 on CartPole; this
records both the sustained sample rate through the sample -> GAE ->
update -> broadcast loop and the learning curve that proves the rate
is of a learning run, not a no-op loop.

Usage: python tools/rl_ppo_bench.py [num_runners] [iters]
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"  # ambient env pins axon; setdefault would keep it
os.environ.setdefault("RAYT_WORKER_STARTUP_TIMEOUT_S", "900")
os.environ.setdefault("RAYT_LEASE_TIMEOUT_S", "600")
os.environ.setdefault("RAYT_RPC_REQUEST_TIMEOUT_S", "300")


def _bench_body(num_runners: int, iters: int) -> dict:
    from ray_tpu.rl.ppo import PPOConfig

    algo = PPOConfig(
        env="CartPole-v1",
        num_env_runners=num_runners,
        num_envs_per_runner=8,
        rollout_fragment_length=128,
        minibatch_size=1024,
        num_epochs=6,
        entropy_coeff=0.003,
        lr=4e-4,
        seed=0).build()
    r = algo.train()  # warmup: compile the learner update
    curve = [r["episode_return_mean"]]
    steps = 0
    t0 = time.perf_counter()
    for _ in range(iters):
        r = algo.train()
        steps += r["num_env_steps_sampled"]
        curve.append(r["episode_return_mean"])
    dt = time.perf_counter() - t0
    out = {
        "bench": "ppo_cartpole",
        "num_env_runners": num_runners,
        "num_envs_per_runner": 8,
        "rollout_fragment_length": 128,
        "host_cores": os.cpu_count(),
        "iterations": iters,
        "env_steps": steps,
        "samples_per_s": round(steps / dt, 1),
        "episode_return_mean_final": r["episode_return_mean"],
        "episode_return_best": max(curve),
        "return_curve": [round(c, 1) for c in curve],
    }
    algo.stop()
    return out


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import ray_tpu as rt

    num_runners = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 20

    rt.init(num_cpus=max(num_runners + 4, os.cpu_count() or 1))
    try:
        out = _bench_body(num_runners, iters)
    finally:
        rt.shutdown()
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "RL_BENCH.json")
    existing = {}
    if os.path.exists(path):
        with open(path) as f:
            existing = json.load(f)
    existing["ppo_cartpole"] = out
    with open(path, "w") as f:
        json.dump(existing, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
