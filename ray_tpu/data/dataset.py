"""Dataset — lazy, streaming-executed distributed data (ref analogs:
python/ray/data/dataset.py API, _internal/plan.py logical plan,
_internal/iterator/ for iter_batches, output_splitter for
streaming_split)."""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Callable, Iterator, Optional

import ray_tpu as rt
from ray_tpu.data.block import (Block, concat_blocks,
                                iter_batches_from_blocks, num_rows_of,
                                slice_rows, split_block, to_batch)
from ray_tpu.data.executor import (ActorPoolStrategy, MapSpec,
                                   StreamingExecutor)


@dataclasses.dataclass
class _AllToAll:
    kind: str      # repartition | shuffle | sort | dedup
    args: dict


@dataclasses.dataclass
class _Limit:
    n: int


class Dataset:
    """Lazy plan over source block refs. Transforms append stages; the
    streaming executor runs map stages with bounded in-flight blocks and
    barriers only at all-to-all stages."""

    def __init__(self, source_refs: list, stages: Optional[list] = None,
                 executor: Optional[StreamingExecutor] = None):
        self._source_refs = source_refs
        self._stages = stages or []
        self._executor = executor or StreamingExecutor()

    # ----------------------------------------------------------- transforms
    def _with(self, stage) -> "Dataset":
        return Dataset(self._source_refs, self._stages + [stage],
                       self._executor)

    def map(self, fn: Callable, **fn_kwargs) -> "Dataset":
        return self._with(MapSpec("map", fn, fn_kwargs=fn_kwargs))

    def filter(self, fn: Callable) -> "Dataset":
        return self._with(MapSpec("filter", fn))

    def flat_map(self, fn: Callable) -> "Dataset":
        return self._with(MapSpec("flat_map", fn))

    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = None,
                    batch_format: str = "numpy",
                    compute: Optional[ActorPoolStrategy] = None,
                    fn_constructor_args: tuple = (),
                    **fn_kwargs) -> "Dataset":
        return self._with(MapSpec(
            "map_batches", fn, batch_size=batch_size,
            batch_format=batch_format, compute=compute,
            fn_constructor_args=tuple(fn_constructor_args),
            fn_kwargs=fn_kwargs))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with(_AllToAll("repartition", {"n": num_blocks}))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._with(_AllToAll("shuffle", {"seed": seed}))

    def sort(self, key: str | Callable, descending: bool = False) -> "Dataset":
        # the RAW key travels to the executor: a string key lets the
        # exchange run vectorized columnar kernels (argsort/searchsorted
        # over the key column); callable keys force row kernels
        return self._with(_AllToAll(
            "sort", {"key": key, "descending": descending}))

    def drop_duplicates(self, key: Optional[str] = None) -> "Dataset":
        """Keep one row per distinct `key` (whole-row identity when
        key=None — that path materializes rows even for columnar
        blocks). Runs as a hash exchange + per-partition
        first-occurrence set; row ORDER across the dataset is not
        preserved (rows land in hash-partition order)."""
        return self._with(_AllToAll("dedup", {"key": key}))

    def unique(self, key: str) -> list:
        """Distinct values of column `key`, sorted when the values are
        mutually orderable (mixed/nullable columns come back in
        partition order instead). The exchange's map side projects to
        the key column before hash partitioning, so only key values —
        never full rows — cross the wire or reach the driver."""
        from ray_tpu.data.block import key_values

        refs = self._executor.unique_values(
            list(self._iter_block_refs()), key)
        vals: list = []
        for block in rt.get(refs):  # one batched gather, not n RTTs
            if num_rows_of(block):
                kv = key_values(block, key)
                vals.extend(kv.tolist() if hasattr(kv, "tolist") else kv)
        try:
            return sorted(vals)
        except TypeError:  # unorderable mix (e.g. None next to str)
            return vals

    def limit(self, n: int) -> "Dataset":
        return self._with(_Limit(n))

    def union(self, *others: "Dataset") -> "Dataset":
        refs = list(self._iter_block_refs())
        for o in others:
            refs.extend(o._iter_block_refs())
        return Dataset(refs)

    def zip(self, other: "Dataset") -> "Dataset":
        left = self.take_all()
        right = other.take_all()
        if len(left) != len(right):
            raise ValueError("zip requires equal row counts "
                             f"({len(left)} vs {len(right)})")
        rows = []
        for a, b in zip(left, right):
            row = dict(a)
            for k, v in b.items():
                row[k if k not in row else f"{k}_1"] = v
            rows.append(row)
        return from_items_rows(rows, num_blocks=max(1, len(
            self._source_refs)))

    def groupby(self, key: str) -> "GroupedData":
        from ray_tpu.data.grouped import GroupedData

        return GroupedData(self, key)

    # ------------------------------------------------------------ execution
    def explain(self) -> list[str]:
        """The optimized plan as stage descriptions (ref analog:
        logical-plan printout in data/_internal/plan.py)."""
        from ray_tpu.data.plan import describe, optimize

        return describe(optimize(list(self._stages)))

    def _iter_block_refs(self) -> Iterator:
        from ray_tpu.data.plan import optimize

        stages = optimize(list(self._stages))
        refs: Iterator = iter(self._source_refs)
        i = 0
        while i < len(stages):
            stage = stages[i]
            if isinstance(stage, MapSpec):
                # consecutive map-family stages run as ONE pipelined
                # operator topology with per-op queues + backpressure
                # (data/streaming_executor.py)
                segment = [stage]
                while i + 1 < len(stages) and isinstance(stages[i + 1],
                                                         MapSpec):
                    i += 1
                    segment.append(stages[i])
                refs = self._executor.stream_pipeline(refs, segment)
            elif isinstance(stage, _AllToAll):
                refs = self._run_all_to_all(refs, stage)
            elif isinstance(stage, _Limit):
                refs = self._limit_refs(refs, stage.n)
            i += 1
        return refs

    def _run_all_to_all(self, refs: Iterator, stage) -> Iterator:
        """All-to-all stages run through the pipelined exchange
        (data/exchange.py). Input refs are materialized only to fix the
        output partition count; the exchange itself overlaps map and
        reduce tasks instead of barriering between them."""
        materialized = list(refs)
        if stage.kind == "repartition":
            return iter(self._executor.repartition(
                materialized, stage.args["n"]))
        if stage.kind == "shuffle":
            return iter(self._executor.random_shuffle(
                materialized, stage.args["seed"]))
        if stage.kind == "dedup":
            return iter(self._executor.dedup(
                materialized, stage.args["key"]))
        return iter(self._executor.sort(
            materialized, stage.args["key"], stage.args["descending"]))

    def _limit_refs(self, refs: Iterator, n: int) -> Iterator:
        remaining = n
        for ref in refs:
            if remaining <= 0:
                return
            block = rt.get(ref)
            n_rows = num_rows_of(block)
            if n_rows > remaining:
                yield rt.put(slice_rows(block, 0, remaining))
                return
            remaining -= n_rows
            yield ref

    def materialize(self) -> "Dataset":
        return Dataset(list(self._iter_block_refs()))

    # ------------------------------------------------------------- consumers
    def iter_rows(self) -> Iterator[dict]:
        from ray_tpu.data.block import iter_rows as _block_iter_rows

        for ref in self._iter_block_refs():
            yield from _block_iter_rows(rt.get(ref))

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator[Any]:
        # columnar end-to-end: blocks are sliced/concatenated, never
        # shattered into per-row dicts (ref: _internal/block_batching)
        yield from iter_batches_from_blocks(
            (rt.get(ref) for ref in self._iter_block_refs()),
            batch_size, batch_format, drop_last)

    def take(self, n: int = 20) -> list:
        from ray_tpu.data.block import block_rows

        out: list = []
        for ref in self._iter_block_refs():
            out.extend(block_rows(rt.get(ref)))
            if len(out) >= n:
                return out[:n]
        return out

    def take_all(self) -> list:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(num_rows_of(rt.get(ref))
                   for ref in self._iter_block_refs())

    def num_blocks(self) -> int:
        return len(self._source_refs)

    def schema(self) -> Optional[list[str]]:
        first = self.take(1)
        if not first:
            return None
        row = first[0]
        return sorted(row.keys()) if isinstance(row, dict) else ["item"]

    def aggregate(self, *agg_fns) -> dict:
        """Global aggregation via AggregateFn plugins (ref:
        dataset.py aggregate + aggregate.py): one accumulate task per
        block, tiny accumulators merge on the driver — rows never leave
        their blocks."""
        from ray_tpu.data.block import iter_rows as _block_iter_rows

        def accumulate(block: Block) -> list:
            accs = []
            for fn in agg_fns:
                acc = fn.init()
                for row in _block_iter_rows(block):
                    acc = fn.accumulate_row(acc, row)
                accs.append(acc)
            return accs

        acc_task = rt.remote(num_cpus=1)(accumulate)
        partials = rt.get([acc_task.remote(ref)
                           for ref in self._iter_block_refs()])
        out = {}
        for i, fn in enumerate(agg_fns):
            acc = fn.init()
            for p in partials:
                acc = fn.merge(acc, p[i])
            out[fn.name] = fn.finalize(acc)
        return out

    def sum(self, on: str) -> float:
        return sum(row[on] for row in self.iter_rows())

    def min(self, on: str):
        return min(row[on] for row in self.iter_rows())

    def max(self, on: str):
        return max(row[on] for row in self.iter_rows())

    def mean(self, on: str) -> float:
        total, n = 0.0, 0
        for row in self.iter_rows():
            total += row[on]
            n += 1
        return total / n if n else float("nan")

    def write_datasink(self, sink, **kwargs) -> list:
        """Fan blocks out to a Datasink (one retryable write task per
        block, atomic per-file commit — data/datasink.py)."""
        from ray_tpu.data.datasink import write_datasink

        return write_datasink(self, sink, **kwargs)

    def write_parquet(self, path: str, *,
                      partition_cols: Optional[list] = None) -> list:
        from ray_tpu.data.datasink import ParquetDatasink

        return self.write_datasink(
            ParquetDatasink(path, partition_cols=partition_cols))

    def write_jsonl(self, path: str, *,
                    partition_cols: Optional[list] = None) -> list:
        from ray_tpu.data.datasink import JSONLDatasink

        return self.write_datasink(
            JSONLDatasink(path, partition_cols=partition_cols))

    def write_npz(self, path: str, *,
                  partition_cols: Optional[list] = None) -> list:
        from ray_tpu.data.datasink import NpzDatasink

        return self.write_datasink(
            NpzDatasink(path, partition_cols=partition_cols))

    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame(self.take_all())

    # ------------------------------------------------- train-ingest surface
    def streaming_split(self, n: int, *, equal: bool = True,
                        seed: Optional[int] = None) -> list["DataIterator"]:
        """Split into n iterators, one per train worker (ref:
        output_splitter.py streaming_split + train DataConfig)."""
        refs = list(self._iter_block_refs())
        shards: list[list] = [[] for _ in range(n)]
        if equal:
            rows = concat_blocks([rt.get(r) for r in refs])
            per = num_rows_of(rows) // n
            for i, part in enumerate(
                    split_block(slice_rows(rows, 0, per * n), n)):
                shards[i].append(rt.put(part))
        else:
            for i, ref in enumerate(refs):
                shards[i % n].append(ref)
        return [DataIterator(shard) for shard in shards]

    def split(self, n: int) -> list["Dataset"]:
        refs = list(self._iter_block_refs())
        out: list[list] = [[] for _ in range(n)]
        for i, ref in enumerate(refs):
            out[i % n].append(ref)
        return [Dataset(refs_i) for refs_i in out]

    def __repr__(self):
        return (f"Dataset(num_blocks={len(self._source_refs)}, "
                f"stages={len(self._stages)})")


class DataIterator:
    """Picklable per-worker shard iterator (resolves block refs lazily in
    the consuming worker)."""

    def __init__(self, refs: list):
        self._refs = refs

    def iter_rows(self) -> Iterator[dict]:
        from ray_tpu.data.block import iter_rows as _block_iter_rows

        for ref in self._refs:
            yield from _block_iter_rows(rt.get(ref))

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator[Any]:
        yield from iter_batches_from_blocks(
            (rt.get(ref) for ref in self._refs),
            batch_size, batch_format, drop_last)

    def count(self) -> int:
        return sum(num_rows_of(rt.get(ref)) for ref in self._refs)

    def __reduce__(self):
        return (DataIterator, (self._refs,))


def from_items_rows(rows: list, num_blocks: int = 8) -> Dataset:
    num_blocks = max(1, min(num_blocks, max(1, len(rows))))
    return Dataset([rt.put(b) for b in split_block(rows, num_blocks)])
