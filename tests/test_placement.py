"""Placement-plane tests (core/placement.py): topology labels, the
measured-cost greedy placer (PACK/SPREAD/SLICE_PACK), ordered gang
admission (two concurrent gangs at partial capacity never deadlock and
never leak a partial reservation), per-job fair-share quotas, and the
end-to-end placement-quality metric — a gang placed through the plane
compiles its DAG edges onto the preferred (non-DCN) channel kinds."""

import asyncio
import threading
import time

import pytest

import ray_tpu as rt
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core.placement import (GangAdmission, PlacementPlane,
                                    QuotaManager, preferred_kind_summary,
                                    topology_labels)


def _view(total, avail, alive=True, labels=None):
    return {"total": total, "available": avail, "alive": alive,
            "labels": labels or {}, "address": None}


# ------------------------------------------------------------ pure units
def test_topology_labels_env_wins_then_head_resource_inference():
    # explicit env knobs take precedence
    labels = topology_labels({"TPU-v5p-16-head": 1.0},
                             env={"RAYT_ICI_SLICE": "s9",
                                  "RAYT_DCN_LOCALITY": "rack-3"})
    assert labels == {"ici-slice": "s9", "dcn-locality": "rack-3"}
    # otherwise the slice-head custom resource names the slice
    labels = topology_labels({"TPU-v5p-16-head": 1.0, "CPU": 8.0}, env={})
    assert labels == {"ici-slice": "TPU-v5p-16"}
    # neither: unlabeled (anonymous slice)
    assert topology_labels({"CPU": 8.0}, env={}) == {}


def test_preferred_kind_summary_counts_dcn_fallbacks():
    s = preferred_kind_summary([
        {"transport": "shm", "device": False},
        {"transport": "dcn", "device": False},
        {"transport": "shm", "device": True},
        {"transport": "dcn", "device": True},
    ])
    assert s["preferred"] == ["shm", "shm", "device", "device"]
    assert s["matched"] == 2 and s["total"] == 4
    assert s["ratio"] == pytest.approx(0.5)
    assert preferred_kind_summary([])["ratio"] is None


def test_quota_manager_weighted_shares_floor_and_dilution():
    qm = QuotaManager(resource="CPU")
    qm.set_quota("a", weight=3.0)
    qm.set_quota("b", weight=1.0, floor=5.0)
    view = qm.view(cluster_total=16.0, active_jobs=["a", "b"],
                   usage={"a": {"CPU": 2.0}})
    assert view["a"]["share"] == pytest.approx(12.0)
    assert view["a"]["used"] == pytest.approx(2.0)
    # floor lifts b above its weighted 4.0
    assert view["b"]["share"] == pytest.approx(5.0)
    # an active UNQUOTA'D job dilutes shares (default weight 1) but
    # never appears in the enforcement view
    view = qm.view(cluster_total=16.0, active_jobs=["a", "b", "c"],
                   usage={})
    assert set(view) == {"a", "b"}
    assert view["a"]["share"] == pytest.approx(3.0 / 5.0 * 16.0)
    # weight<=0, floor<=0 removes the quota
    qm.set_quota("a", 0.0, 0.0)
    assert "a" not in qm.quotas


def test_placer_pack_spread_and_strict_all_or_nothing():
    views = {
        "n1": _view({"CPU": 4}, {"CPU": 4}),
        "n2": _view({"CPU": 4}, {"CPU": 4}),
        "dead": _view({"CPU": 8}, {"CPU": 8}, alive=False),
        "drain": _view({"CPU": 8}, {"CPU": 8},
                       labels={"draining": "1"}),
    }
    plane = PlacementPlane(views_fn=lambda: views)
    # PACK reuses one node while it fits; dead/draining never placed
    got = plane.place_bundles([{"CPU": 2}] * 2, "PACK")
    assert got is not None and len(set(got)) == 1
    assert set(got) <= {"n1", "n2"}
    # STRICT_PACK refuses a gang that cannot fit one node
    assert plane.place_bundles([{"CPU": 3}] * 2, "STRICT_PACK") is None
    # SPREAD lands one bundle per node
    got = plane.place_bundles([{"CPU": 2}] * 2, "SPREAD")
    assert sorted(got) == ["n1", "n2"]
    # STRICT_SPREAD is all-or-nothing past the node count
    assert plane.place_bundles([{"CPU": 1}] * 3,
                               "STRICT_SPREAD") is None
    # whole-gang atomicity: an unplaceable gang returns None, never a
    # partial list
    assert plane.place_bundles([{"CPU": 4}, {"CPU": 5}], "PACK") is None


def test_placer_cost_order_prefers_quiet_nodes():
    views = {
        "busy": _view({"CPU": 8}, {"CPU": 8}),
        "quiet": _view({"CPU": 8}, {"CPU": 8}),
    }
    pending = {"busy": 7, "quiet": 0}
    plane = PlacementPlane(views_fn=lambda: views,
                           pending_fn=lambda h: pending[h])
    assert plane.place_bundles([{"CPU": 1}], "PACK") == ["quiet"]


def test_slice_pack_keeps_gang_inside_one_slice():
    views = {
        "a1": _view({"CPU": 2}, {"CPU": 2}, labels={"ici-slice": "A"}),
        "a2": _view({"CPU": 2}, {"CPU": 2}, labels={"ici-slice": "A"}),
        "b1": _view({"CPU": 4}, {"CPU": 4}, labels={"ici-slice": "B"}),
    }
    plane = PlacementPlane(views_fn=lambda: views)
    # 4 CPUs fit slice A only across BOTH hosts (multi-host is fine) or
    # slice B on one; every valid answer stays within one slice
    got = plane.place_bundles([{"CPU": 1}] * 4, "SLICE_PACK")
    slices = {views[h]["labels"]["ici-slice"] for h in got}
    assert len(slices) == 1
    # a gang too big for any single slice is refused whole
    assert plane.place_bundles([{"CPU": 1}] * 5, "SLICE_PACK") is None
    # unlabeled clusters degrade to PACK (one shared anonymous slice)
    anon = {"x": _view({"CPU": 2}, {"CPU": 2}),
            "y": _view({"CPU": 2}, {"CPU": 2})}
    plane2 = PlacementPlane(views_fn=lambda: anon)
    assert len(plane2.place_bundles([{"CPU": 1}] * 4,
                                    "SLICE_PACK")) == 4


def test_gang_admission_is_fifo_and_exclusive():
    order = []

    async def gang(adm, name, hold_s):
        async with adm.admit(name):
            order.append(("enter", name))
            await asyncio.sleep(hold_s)
            order.append(("exit", name))

    async def main():
        adm = GangAdmission()
        t1 = asyncio.create_task(gang(adm, "g1", 0.05))
        await asyncio.sleep(0.01)   # g1 holds the window first
        t2 = asyncio.create_task(gang(adm, "g2", 0.0))
        await asyncio.gather(t1, t2)
        return adm

    adm = asyncio.run(main())
    # windows never overlap, and arrival order is admission order
    assert order == [("enter", "g1"), ("exit", "g1"),
                     ("enter", "g2"), ("exit", "g2")]
    assert adm.stats()["admitted"] == 2


# ------------------------------------------------------------ end-to-end
@pytest.fixture(scope="module")
def plane_cluster():
    # head (the driver's node): 4 CPUs, anonymous slice; node B: 2 CPUs
    # in a DIFFERENT labeled slice — SLICE_PACK must never mix them, and
    # B is deliberately SMALLER than every gang below so the plane's
    # choice of the head is deterministic (no cost-order coin flips).
    # "blue" pins baseline actors to node B deterministically.
    cluster = Cluster(head_resources={"CPU": 4.0})
    node_b = cluster.add_node(num_cpus=2, resources={"blue": 4.0},
                              labels={"ici-slice": "remote"})
    cluster.connect()
    try:
        yield cluster, node_b
    finally:
        cluster.shutdown()


def test_node_manager_advertises_topology_labels(plane_cluster):
    _, node_b = plane_cluster
    from ray_tpu import state_api

    st = state_api.placement_state()
    assert st["slices"].get("remote") == [node_b.node_id_hex]
    # the head rides the anonymous slice
    assert len(st["slices"].get("", [])) == 1
    assert st["cluster_total"] == pytest.approx(6.0)


def test_concurrent_gangs_all_or_nothing(plane_cluster):
    """Two gangs each needing >half the 2-node cluster race: exactly one
    reserves; the loser either fails whole or completes AFTER the winner
    releases — and no partial reservation is ever leaked."""
    results = {}

    def reserve(name):
        try:
            results[name] = rt.placement_group(
                [{"CPU": 2.0}] * 2, strategy="PACK", timeout=4.0)
        except TimeoutError:
            results[name] = None

    threads = [threading.Thread(target=reserve, args=(n,))
               for n in ("g1", "g2")]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    winners = [n for n, pg in results.items() if pg is not None]
    assert len(winners) == 1, f"expected exactly one winner: {results}"
    loser = "g2" if winners == ["g1"] else "g1"

    # the loser backed off WHOLE: releasing the winner must free the
    # full 6 CPUs, and the loser's retry then fits
    rt.remove_placement_group(results[winners[0]])
    pg = rt.placement_group([{"CPU": 2.0}] * 2, strategy="PACK",
                            timeout=30.0)
    assert len(pg.placement) == 2
    rt.remove_placement_group(pg)
    del loser

    # nothing leaked: every CPU is available again
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        avail = rt.available_resources()
        if avail.get("CPU", 0.0) == pytest.approx(6.0):
            return
        time.sleep(0.2)
    raise AssertionError(
        f"leaked reservation: available={rt.available_resources()}")


def test_plane_placed_dag_compiles_preferred_kinds(plane_cluster):
    """The acceptance gate: a gang that fits one slice, placed through
    the plane, compiles >=90% of its DAG edges onto the preferred
    channel kind; the same DAG over a scattered baseline placement
    measurably pays the DCN fallback."""
    from ray_tpu.core.common import NodeAffinitySchedulingStrategy
    from ray_tpu._internal.ids import NodeID

    @rt.remote(num_cpus=1)
    class Stage:
        def step(self, x):
            return x + 1

    from ray_tpu.dag import InputNode

    def ratio_for(actors):
        with InputNode() as inp:
            out = inp
            for a in actors:
                out = a.step.bind(out)
        dag = out.experimental_compile()
        try:
            assert dag.execute(0).get(timeout=90) == len(actors)
            return dag.preferred_kind_ratio
        finally:
            dag.teardown()
            for a in actors:
                try:
                    rt.kill(a)
                except Exception:
                    pass

    # BASELINE: scatter the pipeline across both nodes ("blue" pins one
    # stage onto node B) — its edges pay the DCN fallback
    scattered = [Stage.remote(),
                 Stage.options(resources={"blue": 1.0}).remote(),
                 Stage.remote()]
    base_ratio = ratio_for(scattered)
    assert base_ratio < 0.9, f"baseline unexpectedly co-located: " \
                             f"{base_ratio}"

    # PLANE: the gang fits one slice; SLICE_PACK advises a single-slice
    # placement and soft affinity pins the actors there
    advised = rt.place_gang([{"CPU": 1.0}] * 3, "SLICE_PACK")
    assert advised is not None and len(set(advised)) == 1
    placed = [Stage.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            NodeID(bytes.fromhex(h)), soft=True)).remote()
        for h in advised]
    plane_ratio = ratio_for(placed)
    assert plane_ratio >= 0.9, \
        f"plane placement ratio {plane_ratio} < 0.9 (baseline " \
        f"{base_ratio})"
    assert plane_ratio > base_ratio


def test_job_quota_surfaces_and_work_conservation(plane_cluster):
    """A quota'd job with a tiny share still runs alone (enforcement is
    work-conserving: throttling needs a competing tenant), and the
    ledger shows up in cluster_status / placement_state / the GCS
    snapshot path."""
    from ray_tpu import state_api

    rt.set_job_quota(weight=0.001, floor=0.5)
    try:
        @rt.remote(num_cpus=1)
        def burst(i):
            return i * 2

        # far past the 0.5-CPU share — with no other tenant every lease
        # must still be granted
        assert rt.get([burst.remote(i) for i in range(8)],
                      timeout=120) == [i * 2 for i in range(8)]

        job_hex = rt.get_runtime_context().get_job_id()
        status = state_api.cluster_status()
        q = status["quotas"].get(job_hex)
        assert q is not None
        assert q["floor"] == pytest.approx(0.5)
        # the ONLY participant owns the whole weighted pool regardless
        # of its tiny weight — shares divide among active tenants
        assert q["share"] == pytest.approx(6.0)
        st = state_api.placement_state()
        assert job_hex in st["quotas"]
    finally:
        rt.set_job_quota(weight=0.0, floor=0.0)   # remove
    assert rt.get_runtime_context()  # cluster still healthy


# ------------------------------------------------- slow: envelope gate
@pytest.mark.slow
def test_multi_tenant_floor_gate():
    """The envelope leg as a gate (tools/envelope_bench.py --only
    placement): three concurrent tenant drivers — quota'd serve + train
    hold their throughput floors while an unfloored shuffle tenant
    bursts, and the train gang's DAG compiles onto preferred channel
    kinds. The leg itself asserts the floors; this test asserts the leg
    and its throttle evidence."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    from envelope_bench import measure_placement

    cluster = Cluster(head_resources={"CPU": 4.0})
    cluster.add_node(num_cpus=2, labels={"ici-slice": "bench-slice"})
    cluster.connect()
    try:
        out = measure_placement(rt, cluster, seconds=8.0)
    finally:
        cluster.shutdown()
    # the floored tenants held their floors (asserted inside the leg);
    # the plane recorded the tenants' quotas while they ran
    assert len(out["quotas_mid_run"]) >= 2, out["quotas_mid_run"]
    assert out["serve"]["per_s"] > 0 and out["train"]["per_s"] > 0
    ratio = out["preferred_kind_ratio"]
    assert ratio is not None and ratio >= 0.9, out["train"]
