"""Core microbenchmarks (ref analog: python/ray/_private/ray_perf.py:93,
run by `ray microbenchmark`). Measures the task/actor/object substrate —
the scalability-envelope numbers SURVEY.md §6 tracks."""

from __future__ import annotations

import time
from typing import Callable

import numpy as np


def _timeit(name: str, fn: Callable, multiplier: int = 1,
            duration: float = 2.0) -> dict:
    # warmup
    fn()
    start = time.perf_counter()
    count = 0
    while time.perf_counter() - start < duration:
        fn()
        count += 1
    dt = time.perf_counter() - start
    rate = count * multiplier / dt
    return {"benchmark": name, "rate_per_s": round(rate, 1)}


def run_microbenchmarks(duration: float = 2.0) -> list[dict]:
    import ray_tpu as rt

    results = []

    @rt.remote
    def tiny(x):
        return x

    # batch submission throughput (tasks/s)
    def submit_batch():
        rt.get([tiny.remote(i) for i in range(100)])

    results.append(_timeit("tasks_per_second", submit_batch, 100, duration))

    # steady-state burst: one pre-built 500-task wave per iteration —
    # long enough that lease batching + hot-lease chaining dominate the
    # measurement instead of the wave's spin-up/drain edges
    def submit_burst():
        rt.get([tiny.remote(i) for i in range(500)])

    results.append(_timeit("tasks_per_second_burst", submit_burst, 500,
                           max(duration, 1.0)))

    @rt.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

        async def aincr(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    results.append(_timeit(
        "actor_calls_sync_per_second", lambda: rt.get(c.incr.remote()),
        1, duration))

    def actor_batch():
        rt.get([c.incr.remote() for _ in range(100)])

    results.append(_timeit("actor_calls_async_per_second", actor_batch,
                           100, duration))

    ac = Counter.remote()

    def async_actor_batch():
        rt.get([ac.aincr.remote() for _ in range(100)])

    results.append(_timeit("async_actor_calls_per_second",
                           async_actor_batch, 100, duration))

    small = np.zeros(16, np.float64)
    results.append(_timeit(
        "put_small_per_second", lambda: rt.put(small), 1, duration))

    big = np.zeros(1 << 27, np.uint8)  # 128 MiB

    def put_get_big():
        rt.get(rt.put(big))

    r = _timeit("put_get_gigabytes_per_second", put_get_big, 1,
                max(duration, 1.0))
    r["rate_per_s"] = round(r["rate_per_s"] * big.nbytes / (1 << 30), 3)
    results.append(r)

    # repeated get of ONE sealed object: isolates the read path (the
    # zero-copy contract — shm-backed views, no deserialize-time copy)
    # from put/seal cost, which put_get above mixes in
    big_ref = rt.put(big)

    def get_big():
        rt.get(big_ref)

    r = _timeit("get_gigabytes_per_second", get_big, 1, max(duration, 1.0))
    r["rate_per_s"] = round(r["rate_per_s"] * big.nbytes / (1 << 30), 3)
    results.append(r)
    del big_ref

    # compiled-DAG per-tick cost: per-call executor vs pre-allocated shm
    # channel loops (ref: compiled_dag_node.py fast path; VERDICT r3 #3)
    @rt.remote
    class Echo:
        def apply(self, x):
            return x

    e1, e2 = Echo.remote(), Echo.remote()
    from ray_tpu.dag import InputNode

    with InputNode() as inp:
        dag_out = e2.apply.bind(e1.apply.bind(inp))
    legacy = dag_out.experimental_compile(channels=False)
    legacy.execute(0).get(timeout=60)
    results.append(_timeit(
        "dag_percall_ticks_per_second",
        lambda: legacy.execute(1).get(timeout=60), 1, duration))
    chan = dag_out.experimental_compile(channels=True)
    chan.execute(0).get(timeout=60)
    results.append(_timeit(
        "dag_channel_ticks_per_second",
        lambda: chan.execute(1).get(timeout=60), 1, duration))
    chan.teardown()

    # zero-copy channel bandwidth: a 1 MiB numpy payload echoed through a
    # single-stage channel DAG (driver ring -> actor -> output ring); the
    # scatter write + slot-view deserialize path must sustain GB/s where
    # the old pickle+join+bytes() tick plateaued well under 1
    e3 = Echo.remote()
    with InputNode() as inp:
        gb_node = e3.apply.bind(inp)
    gdag = gb_node.experimental_compile(channels=True,
                                        buffer_size_bytes=2 << 20)
    mib = np.zeros(1 << 20, np.uint8)
    gdag.execute(mib).get(timeout=60)
    r = _timeit("dag_channel_gigabytes_per_second",
                lambda: gdag.execute(mib).get(timeout=60), 1,
                max(duration, 1.0))
    r["rate_per_s"] = round(r["rate_per_s"] * mib.nbytes / (1 << 30), 3)
    results.append(r)
    gdag.teardown()

    # DCN ring channel tick rate: producer->consumer items over the RPC
    # plane (loopback), credit window pacing the pipeline — the per-tick
    # cost of a cross-node DAG edge
    import uuid as _uuid

    from ray_tpu.dag.dcn_channel import DcnProducerChannel, create_endpoint

    cons = create_endpoint(f"bench-{_uuid.uuid4().hex[:12]}", 8, 1 << 20)
    prod = DcnProducerChannel(cons.spec)

    def dcn_window():
        for i in range(8):
            prod.write(i)
        for _ in range(8):
            cons.read(timeout=60)

    results.append(_timeit("dag_dcn_ticks_per_second", dcn_window, 8,
                           duration))
    prod.close()
    cons.close()

    # device channel tick rate: same-client handoff of a jax.Array —
    # the value OBJECT moves producer->consumer with no serialize /
    # deserialize round trip on the hot path (the acceptance bar: this
    # must beat the shm ring's tick rate for jax.Array payloads)
    import jax.numpy as jnp

    from ray_tpu.dag.channel import ShmChannel
    from ray_tpu.dag.device_channel import (DeviceChannel,
                                            DeviceChannelSpec,
                                            DeviceTransportChannel,
                                            attach_device)

    dev = DeviceChannel.create(n_slots=8)
    dpeer = attach_device(dev.spec)
    small_dev = jnp.zeros(1024, jnp.float32)

    def dev_window():
        for _ in range(8):
            dev.write(small_dev)
        for _ in range(8):
            dpeer.read(timeout=60)

    results.append(_timeit("dag_device_ticks_per_second", dev_window, 8,
                           duration))
    dpeer.close()
    dev.close()

    # device-edge bandwidth over the CROSS-PROCESS framing: a 1 MiB
    # jax.Array as raw shard bytes through a shm ring (scatter write)
    # with a device_put rebuild on the consumer side — the byte path a
    # compiled-DAG device edge pays between processes
    inner = ShmChannel.create(slot_size=2 << 20, n_slots=4)
    dspec = DeviceChannelSpec(name=inner.spec.name, inner=inner.spec)
    dprod = DeviceTransportChannel(inner, dspec)
    dcons = DeviceTransportChannel(ShmChannel.attach(inner.spec), dspec)
    mib_dev = jnp.zeros(1 << 18, jnp.float32)  # 1 MiB

    def dev_gb():
        dprod.write(mib_dev)
        dcons.read(timeout=60)

    r = _timeit("dag_device_gigabytes_per_second", dev_gb, 1,
                max(duration, 1.0))
    r["rate_per_s"] = round(r["rate_per_s"] * mib_dev.nbytes / (1 << 30),
                            3)
    results.append(r)
    dcons.close()
    dprod.close()

    for a in (c, ac, e1, e2, e3):
        rt.kill(a)
    return results
