"""Dashboard head: HTTP observability + job submission (ref analogs:
python/ray/dashboard/head.py:65, dashboard/modules/job/job_manager.py:59,
_private/metrics_agent.py:483 Prometheus export)."""

from ray_tpu.dashboard.head import DashboardHead, JobManager  # noqa: F401
