"""HTTP ingress proxy (ref analog: python/ray/serve/_private/proxy.py:1135
— uvicorn in the reference; aiohttp here).

Routes: POST/GET /<app_name> (body JSON becomes the request payload) →
app ingress handle → JSON response. Runs as an async actor; blocking
ObjectRef gets ride a DEDICATED thread executor (sized by
``RAYT_SERVE_PROXY_THREADS``) so the event loop keeps accepting — and
shedding — connections even when every worker thread is parked on a
result.

Admission control (see serve/admission.py): each request first passes
the per-app admission window sized from the routing table (replicas x
max_ongoing_requests x headroom). The capacity read is CACHED (~1s) and
refreshed off the request path on a small auxiliary executor, so the
accept/shed decision itself never needs a thread from the (possibly
saturated) request executor: shed requests answer 503 + ``Retry-After``
straight from the event loop — no executor thread, no replica traffic —
keeping a flat, fast rejection path under exactly the overload the
window exists for. Status mapping: 503 for overload/backpressure/
timeout (reasons ``shed`` / ``queue_full`` / ``timeout`` /
``no_replicas`` in the JSON body and the X-Rayt-Reason header), 500
ONLY for an exception raised by the replica's user code. Streaming
requests route BEFORE the SSE response is prepared, so an overloaded
stream sheds with a real 503 too (mid-stream failures degrade to an
``event: error`` frame — the 200 is already on the wire).
"""

from __future__ import annotations

import asyncio
import json
import os
import time
from typing import Any

from ray_tpu.serve.admission import (AdmissionWindow, count_admitted,
                                     count_shed, is_overload_error,
                                     request_timeout_s, retry_after_s)

PROXY_THREADS_ENV = "RAYT_SERVE_PROXY_THREADS"

# routing-table capacity cache TTL: admission windows follow replica
# scaling within this bound without an RPC per request
CAPACITY_TTL_S = 1.0

# controller heartbeat cadence: liveness TTL is 3x this (see
# controller.PROXY_TTL_S), so a dead proxy's window share redistributes
# to the survivors within one capacity refresh after the TTL lapses
HEARTBEAT_PERIOD_S = 1.0


class ProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000,
                 request_timeout_s: float | None = None,
                 admission_headroom: float | None = None,
                 proxy_id: str = "http-0"):
        self.host = host
        self.port = port
        self.proxy_id = proxy_id
        self._handles: dict[str, Any] = {}
        self._ingress: dict[str, str] = {}
        self._runner = None
        self._executor = None       # admitted-request result waits
        self._aux_executor = None   # capacity refreshes (never starved
        # by admitted requests parking on results)
        self._timeout_override = request_timeout_s
        self._admission = AdmissionWindow(admission_headroom, proxy_id)
        self._capacity: dict[str, tuple[int, int, int, float]] = {}
        self._cap_refreshing: set[str] = set()
        self._hb_task = None

    async def start(self) -> int:
        from concurrent.futures import ThreadPoolExecutor

        from aiohttp import web

        self._executor = ThreadPoolExecutor(
            max_workers=int(os.environ.get(PROXY_THREADS_ENV, "128")),
            thread_name_prefix="serve-proxy")
        self._aux_executor = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="serve-proxy-cap")
        app = web.Application()
        app.router.add_route("*", "/-/routes", self._routes_endpoint)
        app.router.add_route("*", "/-/healthz", self._healthz)
        app.router.add_route("*", "/-/admission", self._admission_endpoint)
        app.router.add_route("*", "/{app_name}", self._dispatch)
        app.router.add_route("*", "/{app_name}/{tail:.*}", self._dispatch)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in site._server.sockets:
            self.port = s.getsockname()[1]
            break
        self._hb_task = asyncio.create_task(self._heartbeat_loop())
        return self.port

    async def _heartbeat_loop(self):
        """Announce liveness to the controller ~1/s. ``live_proxies``
        rides the routing table back to every proxy's window math, so
        this beat is all the fleet coordination there is: a member that
        stops beating ages out after controller.PROXY_TTL_S and its
        admission share redistributes on the next capacity refresh."""
        import ray_tpu as rt
        from ray_tpu.serve.controller import CONTROLLER_NAME

        loop = asyncio.get_running_loop()

        def _beat():
            try:
                controller = rt.get_actor(CONTROLLER_NAME)
                rt.get(controller.proxy_heartbeat.remote(
                    self.proxy_id, "http", self.port), timeout=5)
            except Exception:
                pass  # controller bouncing: keep serving, beat again

        while True:
            await loop.run_in_executor(self._aux_executor, _beat)
            await asyncio.sleep(HEARTBEAT_PERIOD_S)

    def register_app(self, app_name: str, ingress_deployment: str) -> bool:
        self._ingress[app_name] = ingress_deployment
        self._handles.pop(app_name, None)
        self._capacity.pop(app_name, None)
        return True

    def unregister_app(self, app_name: str) -> bool:
        self._ingress.pop(app_name, None)
        self._handles.pop(app_name, None)
        self._capacity.pop(app_name, None)
        return True

    async def _healthz(self, request):
        from aiohttp import web

        return web.Response(text="ok")

    async def _routes_endpoint(self, request):
        from aiohttp import web

        return web.json_response(dict(self._ingress))

    async def _admission_endpoint(self, request):
        from aiohttp import web

        return web.json_response({**self._admission.snapshot(),
                                  **self._admission.fleet_snapshot()})

    def _request_timeout(self) -> float:
        if self._timeout_override is not None:
            return float(self._timeout_override)
        return request_timeout_s()

    def _unavailable(self, app_name: str, reason: str, detail: str):
        """503 + Retry-After: overload/backpressure/timeout semantics —
        the client should back off and retry, nothing is broken."""
        from aiohttp import web

        retry = retry_after_s()
        count_shed(app_name, self.proxy_id, reason)
        return web.json_response(
            {"error": detail, "reason": reason, "retry_after_s": retry},
            status=503,
            headers={"Retry-After": str(retry),
                     "X-Rayt-Reason": reason,
                     "X-Rayt-Proxy-Id": self.proxy_id})

    async def _app_capacity(self, app_name: str, handle,
                            loop) -> tuple[int, int, int]:
        """(replicas, max_ongoing, live_proxies) from the ~1s cache.
        Only the COLD read (first request for an app) waits on an RPC —
        and on the aux executor, not the request executor, so a
        saturated proxy still sheds instantly. Stale entries refresh in
        the background while the current value keeps serving decisions.
        live_proxies riding this same refresh is what redistributes a
        dead proxy's admission share within one table refresh."""
        cap = self._capacity.get(app_name)
        now = time.monotonic()
        if cap is None:
            try:
                replicas, max_ongoing, live = await loop.run_in_executor(
                    self._aux_executor, handle.capacity_info)
            except Exception:
                replicas, max_ongoing, live = 1, 16, 1  # table warming up
            self._capacity[app_name] = (replicas, max_ongoing, live,
                                        time.monotonic())
            return replicas, max_ongoing, live
        replicas, max_ongoing, live, ts = cap
        if now - ts > CAPACITY_TTL_S and \
                app_name not in self._cap_refreshing:
            self._cap_refreshing.add(app_name)

            def _refresh():
                try:
                    r, m, lp = handle.capacity_info()
                    self._capacity[app_name] = (r, m, lp,
                                                time.monotonic())
                except Exception:
                    self._capacity[app_name] = (replicas, max_ongoing,
                                                live, time.monotonic())
                finally:
                    self._cap_refreshing.discard(app_name)

            self._aux_executor.submit(_refresh)
        return replicas, max_ongoing, live

    async def _dispatch(self, request):
        from aiohttp import web

        t0 = time.perf_counter()
        app_name = request.match_info["app_name"]
        ingress = self._ingress.get(app_name)
        if ingress is None:
            return web.json_response(
                {"error": f"no app {app_name!r}"}, status=404)
        handle = self._handles.get(app_name)
        if handle is None:
            from ray_tpu.serve.handle import DeploymentHandle

            handle = DeploymentHandle(ingress, app_name)
            self._handles[app_name] = handle
        # request id: minted once per (resolved) request, echoed on
        # every response — 503s included — and rides the handle context
        # into the replica so both sides' partial GCS records coalesce
        from ray_tpu._internal.otel import (current_context_carrier,
                                            submit_span)
        from ray_tpu.serve.request_context import mint_request_id

        rid = mint_request_id()
        ctx = {"request_id": rid, "start_ts": time.time(),
               "proxy": self.proxy_id}
        if request.can_read_body:
            try:
                payload = await request.json()
            except json.JSONDecodeError:
                payload = (await request.read()).decode()
        else:
            payload = dict(request.query)
        # streaming: ?stream=1 or Accept: text/event-stream gets an SSE
        # response fed by the replica's generator (ref: serve response
        # streaming through the proxy)
        wants_stream = (request.query.get("stream") == "1"
                        or "text/event-stream" in
                        request.headers.get("Accept", ""))
        loop = asyncio.get_running_loop()
        with submit_span("serve.proxy.request", app=app_name,
                         request_id=rid, proto="http",
                         path=request.path):
            try:
                # W3C carrier captured INSIDE the proxy span: the
                # replica's execute_span parents off it, stitching one
                # trace across the two processes
                ctx["trace"] = current_context_carrier()
            except Exception:
                pass
            # ---- admission: window sized from the (cached) routing-
            # table capacity; accept/shed is sync + fast on the event
            # loop. This proxy admits its SHARE of the cluster window
            # (cluster / live_proxies) — see serve/admission.py.
            replicas, max_ongoing, live = await self._app_capacity(
                app_name, handle, loop)
            if not self._admission.try_acquire(app_name, replicas,
                                               max_ongoing, live):
                resp = self._unavailable(
                    app_name, "shed",
                    f"admission window full for app {app_name!r} (window="
                    f"{self._admission.window_for(replicas, max_ongoing, live)}"
                    f", live_proxies={live})")
                resp.headers["X-Rayt-Request-Id"] = rid
                self._finish_record(ctx, app_name, "shed", t0=t0)
                return resp
            t1 = time.perf_counter()
            count_admitted(app_name, self.proxy_id)
            # model multiplexing (ref: serve proxy forwards the model-id
            # header); the router's capacity-gate park is bounded by the
            # request timeout — a request that can't find a replica slot
            # in time is SHED (503 queue_full), never left queueing to
            # timeout
            from ray_tpu.serve.admission import queue_timeout_s

            model_id = request.headers.get("serve_multiplexed_model_id",
                                           "")
            # prefix-cache-aware routing: hash the prompt's leading
            # token block into a key the router's prefix-affinity LRU
            # steers toward replicas holding the warm KV state
            from ray_tpu.serve.handle import derive_prefix_key

            prefix_key = derive_prefix_key(payload)
            handle = handle.options(
                multiplexed_model_id=model_id or None,
                queue_timeout_s=min(queue_timeout_s(),
                                    self._request_timeout()),
                request_context=ctx,
                prefix_key=prefix_key or None)
            try:
                if wants_stream:
                    return await self._dispatch_stream(
                        request, handle, app_name, payload, ctx, t0, t1,
                        model_id)
                return await self._dispatch_unary(
                    handle, app_name, payload, loop, ctx, t0, t1,
                    model_id)
            finally:
                self._admission.release(app_name)

    def _error_response(self, app_name: str, e: Exception):
        """Map a routing/replica failure onto the 503/500 split."""
        from aiohttp import web
        from ray_tpu.core.common import GetTimeoutError

        if isinstance(e, GetTimeoutError):
            return self._unavailable(
                app_name, "timeout",
                f"request exceeded {self._request_timeout():.0f}s "
                "(RAYT_SERVE_REQUEST_TIMEOUT_S)")
        if is_overload_error(e):
            return self._unavailable(app_name, "queue_full", repr(e))
        if isinstance(e, RuntimeError) and "no replicas" in str(e):
            return self._unavailable(app_name, "no_replicas", repr(e))
        # a replica-raised user exception: a real 500
        return web.json_response({"error": repr(e)}, status=500)

    @staticmethod
    def _outcome_for(e: Exception) -> str:
        """Record outcome for a failed dispatch — mirrors the
        _error_response status mapping."""
        from ray_tpu.core.common import GetTimeoutError

        if isinstance(e, GetTimeoutError):
            return "timeout"
        if is_overload_error(e):
            return "queue_full"
        if isinstance(e, RuntimeError) and "no replicas" in str(e):
            return "no_replicas"
        return "error"

    @staticmethod
    def _finish_record(ctx: dict, app_name: str, outcome: str, *,
                       t0: float, t1: float | None = None,
                       t_first: float | None = None,
                       t_end: float | None = None, proto: str = "http",
                       model_id: str = "", ttft_s: float | None = None,
                       tpot_s: float | None = None, chunks: int = 0):
        """Assemble and publish this request's FINAL record (one publish
        per request, batched off the hot path). The proxy stages TILE
        the end-to-end wall time by construction: admission (t1-t0) +
        router (accumulated by pick()) + dispatch (remainder up to first
        output or completion) + stream (first output -> end)."""
        try:
            from ray_tpu.serve.request_context import publish_record

            if t_end is None:
                t_end = time.perf_counter()
            e2e = t_end - t0
            router_s = float(ctx.get("router_s") or 0.0)
            if t1 is None:
                # shed at the admission gate: the whole request was
                # admission time, by definition
                stages = {"admission_s": e2e}
            else:
                boundary = t_first if t_first is not None else t_end
                stages = {"admission_s": t1 - t0,
                          "router_s": router_s,
                          "dispatch_s": max(0.0,
                                            (boundary - t1) - router_s)}
                if t_first is not None:
                    stages["stream_s"] = t_end - t_first
            rec = {"kind": "request", "side": "proxy", "final": True,
                   "request_id": ctx["request_id"], "app": app_name,
                   "proto": proto, "outcome": outcome, "e2e_s": e2e,
                   "stages": stages, "pid_proxy": os.getpid(),
                   "start_ts": ctx.get("start_ts"), "ts": time.time()}
            if model_id:
                rec["model_id"] = model_id
            if ctx.get("replica"):
                rec["replica"] = ctx["replica"]
            if ctx.get("affinity"):
                rec["affinity"] = ctx["affinity"]
            if ctx.get("proxy"):
                rec["proxy"] = ctx["proxy"]
            if ctx.get("prefix"):
                rec["prefix_cache"] = ctx["prefix"]
            if ttft_s is not None:
                rec["ttft_s"] = ttft_s
            if tpot_s is not None:
                rec["tpot_s"] = tpot_s
            if chunks:
                rec["chunks"] = chunks
            publish_record(rec)
        except Exception:
            pass  # observability must never fail the request

    async def _dispatch_unary(self, handle, app_name, payload, loop,
                              ctx, t0, t1, model_id):
        from aiohttp import web

        timeout = self._request_timeout()
        try:
            response = await loop.run_in_executor(
                self._executor,
                lambda: handle.remote(payload).result(timeout=timeout))
        except Exception as e:
            resp = self._error_response(app_name, e)
            resp.headers["X-Rayt-Request-Id"] = ctx["request_id"]
            resp.headers["X-Rayt-Proxy-Id"] = self.proxy_id
            self._finish_record(ctx, app_name, self._outcome_for(e),
                                t0=t0, t1=t1, model_id=model_id)
            return resp
        self._finish_record(ctx, app_name, "ok", t0=t0, t1=t1,
                            model_id=model_id)
        if isinstance(response, (dict, list, str, int, float, bool,
                                 type(None))):
            resp = web.json_response({"result": response})
        else:
            resp = web.Response(body=str(response).encode())
        resp.headers["X-Rayt-Request-Id"] = ctx["request_id"]
        resp.headers["X-Rayt-Proxy-Id"] = self.proxy_id
        return resp

    def _observe_stream_latency(self, app_name: str, seconds: float):
        """Streaming requests record into the serve latency histogram
        too (they previously bypassed it entirely — the only serve
        latency series came from replica-side handler timing); the
        `_proxy_stream` pseudo-deployment keeps this client-visible
        series distinct from the replica's."""
        try:
            from ray_tpu.util import builtin_metrics as bm

            bm.serve_request_latency.observe(
                seconds, tags={"app": app_name,
                               "deployment": "_proxy_stream"})
        except Exception:
            pass

    async def _dispatch_stream(self, request, handle, app_name, payload,
                               ctx, t0, t1, model_id):
        from aiohttp import web

        loop = asyncio.get_running_loop()
        if isinstance(payload, dict):
            payload.pop("stream", None)
        # route BEFORE preparing the SSE response: an overloaded /
        # replica-less stream must shed with a real 503, not a 200
        # carrying an error frame
        try:
            gen = await loop.run_in_executor(
                self._executor,
                lambda: handle.options(stream=True).remote(payload))
        except Exception as e:
            resp = self._error_response(app_name, e)
            resp.headers["X-Rayt-Request-Id"] = ctx["request_id"]
            resp.headers["X-Rayt-Proxy-Id"] = self.proxy_id
            self._finish_record(ctx, app_name, self._outcome_for(e),
                                t0=t0, t1=t1, model_id=model_id)
            return resp
        resp = web.StreamResponse(
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache",
                     "X-Rayt-Request-Id": ctx["request_id"],
                     "X-Rayt-Proxy-Id": self.proxy_id})
        await resp.prepare(request)
        # TTFT stamps at the FIRST SSE chunk, the total at stream END —
        # a streaming request's latency is its last byte, not the
        # instant the 200 went on the wire. A mid-stream failure or a
        # client hang-up finalizes as `stream_aborted`, never silence.
        t_first = None
        chunks = 0
        outcome = "ok"
        try:
            async for item in gen:
                if t_first is None:
                    t_first = time.perf_counter()
                chunks += 1
                await resp.write(
                    f"data: {json.dumps(item, default=str)}\n\n".encode())
        except (ConnectionResetError, ConnectionError):
            outcome = "stream_aborted"  # client went away;
            # gen.close() stops the replica
        except Exception as e:
            # mid-stream failure: the 200 is already on the wire — an
            # error frame is the only channel left
            outcome = "stream_aborted"
            try:
                await resp.write(
                    f"event: error\ndata: "
                    f"{json.dumps(repr(e))}\n\n".encode())
            except Exception:
                pass
        finally:
            gen.close()
        t_end = time.perf_counter()
        ttft = (t_first - t0) if t_first is not None else None
        tpot = ((t_end - t_first) / (chunks - 1)
                if t_first is not None and chunks > 1 else None)
        self._finish_record(ctx, app_name, outcome, t0=t0, t1=t1,
                            t_first=t_first, t_end=t_end,
                            model_id=model_id, ttft_s=ttft, tpot_s=tpot,
                            chunks=chunks)
        self._observe_stream_latency(app_name, t_end - t0)
        try:
            await resp.write_eof()
        except Exception:
            pass
        return resp
