"""Native components, built on demand with the system toolchain.

The shm arena store (shm_store.cpp) is the plasma-equivalent C++ data
plane: one mmap'd segment per node, boundary-tag allocator, LRU eviction,
process-shared robust mutex. Python binds via ctypes (no pybind11 in the
image) and maps the same segment for zero-copy reads.

Build artifacts cache under ~/.cache/ray_tpu keyed by source hash, so the
first import on a machine pays one g++ invocation (~1s) and every later
process just dlopens.
"""

from __future__ import annotations

import ctypes
import hashlib
import mmap
import os
import subprocess
import threading
from typing import Any, Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "shm_store.cpp")

_lib = None
_lib_err: Optional[str] = None
_lib_lock = threading.Lock()


def _build_lib() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    cache_dir = os.path.join(
        os.path.expanduser(os.environ.get("RAYT_CACHE_DIR",
                                          "~/.cache/ray_tpu")))
    os.makedirs(cache_dir, exist_ok=True)
    so_path = os.path.join(cache_dir, f"libraytshm-{digest}.so")
    if os.path.exists(so_path):
        return so_path
    tmp = so_path + f".tmp{os.getpid()}"
    subprocess.run(
        ["g++", "-O2", "-fPIC", "-shared", "-pthread", "-o", tmp, _SRC,
         "-lrt"],
        check=True, capture_output=True, text=True)
    os.replace(tmp, so_path)
    return so_path


def load_shm_lib():
    """Load (building if needed) the native store; None when unavailable."""
    global _lib, _lib_err
    with _lib_lock:
        if _lib is not None or _lib_err is not None:
            return _lib
        if os.environ.get("RAYT_DISABLE_NATIVE_SHM"):
            _lib_err = "disabled via RAYT_DISABLE_NATIVE_SHM"
            return None
        try:
            lib = ctypes.CDLL(_build_lib())
        except Exception as e:
            _lib_err = repr(e)
            return None
        lib.rayt_shm_open.restype = ctypes.c_void_p
        lib.rayt_shm_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                      ctypes.c_uint64]
        lib.rayt_shm_arena_offset.restype = ctypes.c_uint64
        lib.rayt_shm_arena_offset.argtypes = [ctypes.c_void_p]
        for name in ("rayt_shm_create",):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                           ctypes.c_uint64,
                           ctypes.POINTER(ctypes.c_uint64)]
        lib.rayt_shm_get.restype = ctypes.c_int
        lib.rayt_shm_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.POINTER(ctypes.c_uint64),
                                     ctypes.POINTER(ctypes.c_uint64)]
        for name in ("rayt_shm_seal", "rayt_shm_release",
                     "rayt_shm_contains", "rayt_shm_delete"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        for name in ("rayt_shm_used", "rayt_shm_capacity",
                     "rayt_shm_num_objects", "rayt_shm_evictions"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_uint64
            fn.argtypes = [ctypes.c_void_p]
        lib.rayt_shm_close.restype = None
        lib.rayt_shm_close.argtypes = [ctypes.c_void_p]
        lib.rayt_shm_unlink.restype = ctypes.c_int
        lib.rayt_shm_unlink.argtypes = [ctypes.c_char_p]
        # release/acquire atomics for the SPSC channel seq words
        lib.rayt_atomic_store_release_u64.restype = None
        lib.rayt_atomic_store_release_u64.argtypes = [ctypes.c_void_p,
                                                      ctypes.c_uint64]
        lib.rayt_atomic_load_acquire_u64.restype = ctypes.c_uint64
        lib.rayt_atomic_load_acquire_u64.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_unavailable_reason() -> Optional[str]:
    return _lib_err


class NativeArenaStore:
    """ctypes wrapper over one node-scoped arena (plasma-client analog).

    Interface mirrors object_store.ShmObjectStore so the core worker and
    node manager can use either transparently.
    """

    DEFAULT_SLOTS = 1 << 16

    def __init__(self, name: str, capacity: int):
        lib = load_shm_lib()
        if lib is None:
            raise RuntimeError(
                f"native shm store unavailable: {native_unavailable_reason()}")
        self._lib = lib
        self._name = name.encode()
        self._handle = lib.rayt_shm_open(self._name, capacity,
                                         self.DEFAULT_SLOTS)
        if not self._handle:
            raise RuntimeError(f"rayt_shm_open({name!r}) failed")
        # map the same segment for zero-copy python-side reads/writes
        fd = os.open(f"/dev/shm/{name}", os.O_RDWR)
        try:
            total = os.fstat(fd).st_size
            self._map = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        self._mv = memoryview(self._map)
        self._arena_off = lib.rayt_shm_arena_offset(self._handle)
        self._held: dict[Any, int] = {}   # oid -> get-refcount
        self._pending: dict[Any, int] = {}  # unsealed oid -> abs offset
        # RLock: release() can re-enter on the SAME thread via a GC
        # firing ObjectRef.__del__ -> pin drain -> release while this
        # thread is already inside a locked section
        self._lock = threading.RLock()
        # fallback-to-disk allocation (ref: plasma_allocator.cc fallback
        # mmaps): objects that don't fit the arena land in per-node files,
        # named by object id so every worker process sees them
        self._fallback_dir = os.path.join(
            "/tmp", f"rayt_fallback_{name}")
        self._pending_fb: dict[Any, str] = {}  # unsealed oid -> tmp path

    # ------------------------------------------------------------- helpers
    def _payload(self, offset: int, size: int) -> memoryview:
        start = self._arena_off + offset
        return self._mv[start:start + size]

    # ----------------------------------------------------- store interface
    def create_and_seal(self, object_id, value) -> int:
        from ray_tpu._internal.serialization import serialize, serialized_size

        chunks = serialize(value)
        size = serialized_size(chunks)
        self._write_sealed(object_id, chunks, size)
        return size

    def create_from_bytes(self, object_id, data: bytes,
                          hold: bool = False) -> int:
        self._write_sealed(object_id, [data], len(data), hold=hold)
        return len(data)

    def create_from_chunks(self, object_id, chunks, size: int,
                           hold: bool = False) -> int:
        """Seal a payload assembled from transfer chunks without first
        joining them into one host buffer."""
        self._write_sealed(object_id, chunks, size, hold=hold)
        return size

    def _write_sealed(self, object_id, chunks, size: int,
                      hold: bool = False):
        if not self.create_unsealed(object_id, size):
            return  # already present (duplicate transfer): keep existing
        pos = 0
        for c in chunks:
            n = len(c) if isinstance(c, bytes) else c.nbytes
            self.write_at(object_id, pos,
                          bytes(c) if not isinstance(
                              c, (bytes, bytearray, memoryview)) else c)
            pos += n
        self.seal(object_id, hold=hold)

    # --------------------------------------------------- streaming creates
    # ------------------------------------------------- fallback-to-disk
    def _fb_path(self, object_id) -> str:
        return os.path.join(self._fallback_dir, object_id.hex())

    def _fb_exists(self, object_id) -> bool:
        return os.path.exists(self._fb_path(object_id))

    def create_unsealed(self, object_id, size: int) -> bool:
        """Allocate an entry to be filled by write_at + seal. The object
        is invisible to contains/get until sealed (state kCreating).
        False if it already exists. When the arena cannot fit it even
        after eviction, allocation FALLS BACK to a per-node file (ref:
        plasma fallback allocation) instead of raising."""
        if self._fb_exists(object_id):
            return False
        off = ctypes.c_uint64()
        rc = self._lib.rayt_shm_create(self._handle, object_id.binary(),
                                       size, ctypes.byref(off))
        if rc == -1:
            return False
        if rc != 0:
            # arena full: file-backed allocation, sealed via rename.
            # O_EXCL serializes concurrent creators across processes —
            # the loser sees the .creating file and treats the object as
            # already-in-progress (duplicate-transfer semantics).
            os.makedirs(self._fallback_dir, exist_ok=True)
            tmp = self._fb_path(object_id) + ".creating"
            try:
                fd = os.open(tmp, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                return False
            try:
                os.ftruncate(fd, size)
            finally:
                os.close(fd)
            with self._lock:
                self._pending_fb[object_id] = tmp
            return True
        with self._lock:
            self._pending[object_id] = self._arena_off + off.value
        return True

    def write_at(self, object_id, offset: int, data):
        with self._lock:
            base = self._pending.get(object_id)
            fb = self._pending_fb.get(object_id)
        if base is None and fb is not None:
            with open(fb, "r+b") as f:
                f.seek(offset)
                f.write(bytes(data) if not isinstance(
                    data, (bytes, bytearray)) else data)
            return
        n = len(data)
        self._mv[base + offset:base + offset + n] = data

    def seal(self, object_id, hold: bool = False):
        with self._lock:
            fb = self._pending_fb.pop(object_id, None)
        if fb is not None:
            os.replace(fb, self._fb_path(object_id))  # atomic seal
            return
        self._lib.rayt_shm_seal(self._handle, object_id.binary())
        with self._lock:
            self._pending.pop(object_id, None)
        if not hold:
            # with hold=True the creator keeps its create-ref so the LRU
            # can't evict the object before the node manager pins it;
            # the creator calls release_create_ref() afterwards
            self._lib.rayt_shm_release(self._handle, object_id.binary())

    def abort_unsealed(self, object_id):
        """Drop a half-written entry (failed/cancelled pull)."""
        with self._lock:
            fb = self._pending_fb.pop(object_id, None)
            self._pending.pop(object_id, None)
        if fb is not None:
            try:
                os.remove(fb)
            except OSError:
                pass
            return
        # creator still holds its create-ref: delete tombstones the entry,
        # release drops the last ref and frees the block
        self._lib.rayt_shm_delete(self._handle, object_id.binary())
        self._lib.rayt_shm_release(self._handle, object_id.binary())

    def contains_locally(self, object_id) -> bool:
        return bool(self._lib.rayt_shm_contains(
            self._handle, object_id.binary())) or self._fb_exists(object_id)

    def _get_view(self, object_id, size: int) -> memoryview:
        off = ctypes.c_uint64()
        sz = ctypes.c_uint64()
        rc = self._lib.rayt_shm_get(self._handle, object_id.binary(),
                                    ctypes.byref(off), ctypes.byref(sz))
        if rc != 0:
            if self._fb_exists(object_id):
                with open(self._fb_path(object_id), "rb") as f:
                    return memoryview(f.read())
            raise KeyError(f"object {object_id} not in shm store (rc={rc})")
        with self._lock:
            self._held[object_id] = self._held.get(object_id, 0) + 1
        return self._payload(off.value, sz.value)

    def get_view(self, object_id, size: int) -> memoryview:
        """Zero-copy view of the sealed payload. Takes a get-ref (the
        pin: LRU eviction cannot reclaim the block) that the caller must
        balance with release() once no deserialized view aliases it. The
        fallback-file branch returns an owned copy — release() is then a
        harmless no-op (no ref was taken)."""
        return self._get_view(object_id, size)

    def get(self, object_id, size: int):
        from ray_tpu._internal.serialization import deserialize

        return deserialize(self._get_view(object_id, size))

    def read_bytes(self, object_id, size: int) -> bytes:
        view = self._get_view(object_id, size)
        try:
            return bytes(view)
        finally:
            self.release(object_id)

    def read_range_view(self, object_id, size: int, offset: int,
                        length: int):
        """One transfer chunk: (view, release_cb) for the push side of
        chunked transfer (ref: object_buffer_pool chunked reads) — the
        chunk aliases the arena mapping with a get-ref held, zero copy.
        The caller MUST invoke release_cb (when not None) after the bytes
        have been handed to the transport, or the block stays pinned."""
        if not self._lib.rayt_shm_contains(self._handle,
                                           object_id.binary()) \
                and self._fb_exists(object_id):
            with open(self._fb_path(object_id), "rb") as f:
                f.seek(offset)
                return f.read(length), None
        view = self._get_view(object_id, size)
        return (view[offset:offset + length],
                lambda: self.release(object_id))

    def release(self, object_id):
        with self._lock:
            # NULL-handle guard: a zero-copy get-pin can drain AFTER
            # store close (an ObjectRef GC'd past rt.shutdown()); the C
            # side has no guard and would segfault on a NULL arena
            if self._handle is None:
                return
            n = self._held.get(object_id, 0)
            if n <= 0:
                return
            self._held[object_id] = n - 1
            if self._held[object_id] == 0:
                del self._held[object_id]
            # C call inside the lock: close() also nulls the handle
            # under it, so the handle can't be torn down mid-call
            self._lib.rayt_shm_release(self._handle, object_id.binary())

    def release_create_ref(self, object_id):
        """Drop the ref held by create_from_bytes(hold=True)."""
        self._lib.rayt_shm_release(self._handle, object_id.binary())

    def pin(self, object_id) -> bool:
        """Node-manager primary-copy pin (ref: plasma primary copies are
        pinned by the raylet; spilling is the only reclaim path)."""
        off = ctypes.c_uint64()
        sz = ctypes.c_uint64()
        return self._lib.rayt_shm_get(self._handle, object_id.binary(),
                                      ctypes.byref(off),
                                      ctypes.byref(sz)) == 0

    def unpin(self, object_id):
        self._lib.rayt_shm_release(self._handle, object_id.binary())

    def unlink(self, object_id):
        self._lib.rayt_shm_delete(self._handle, object_id.binary())
        if self._fb_exists(object_id):
            try:
                os.remove(self._fb_path(object_id))
            except OSError:
                pass

    def used(self) -> int:
        # NULL-handle guard: stats on a closed store must return 0, not
        # dereference a dangling arena pointer in C
        return self._lib.rayt_shm_used(self._handle) if self._handle else 0

    def capacity(self) -> int:
        return (self._lib.rayt_shm_capacity(self._handle)
                if self._handle else 0)

    def num_objects(self) -> int:
        return (self._lib.rayt_shm_num_objects(self._handle)
                if self._handle else 0)

    def evictions(self) -> int:
        return (self._lib.rayt_shm_evictions(self._handle)
                if self._handle else 0)

    # ------------------------------------------------------ observability
    def get_ref_counts(self) -> dict:
        """Outstanding zero-copy get-refs held by THIS process (the pins
        the leak watchdog inspects): oid -> refcount snapshot."""
        with self._lock:
            return dict(self._held)

    def stats(self) -> dict:
        """Arena snapshot for the rayt_object_store_* gauges and node
        object reports. Reads only the C getters (shared-header counters)
        plus a fallback-dir scan — no allocator lock taken, safe on the
        hot path. Mirrors ShmObjectStore.stats() keys; arena "zombies"
        are get-ref-held blocks whose entry was already deleted, which
        the C side frees on the last release — reported via held_refs."""
        fb_objects = 0
        fb_bytes = 0
        try:
            with os.scandir(self._fallback_dir) as it:
                for e in it:
                    if e.name.endswith(".creating"):
                        continue
                    try:
                        fb_bytes += e.stat().st_size
                        fb_objects += 1
                    except OSError:
                        pass
        except OSError:
            pass
        with self._lock:
            held = len(self._held)
            unsealed = len(self._pending) + len(self._pending_fb)
        return {
            "segments": 1,  # one node-scoped arena segment
            "unsealed": unsealed,
            "zombie_segments": 0,
            "zombie_bytes": 0,
            "zombies_parked_total": 0,
            "zombies_swept_total": 0,
            "fallback_objects": fb_objects,
            "fallback_bytes": fb_bytes,
            "arena_used_bytes": self.used(),
            "arena_capacity_bytes": self.capacity(),
            "arena_objects": self.num_objects(),
            "arena_evictions_total": self.evictions(),
            "held_refs": held,
        }

    def close(self):
        with self._lock:
            if self._handle:
                try:
                    self._mv.release()
                    self._map.close()
                except (BufferError, ValueError):
                    pass  # zero-copy views alive; mapping stays until exit
                else:
                    self._lib.rayt_shm_close(self._handle)
                    self._handle = None

    def destroy_self(self):
        """Unlink the arena segment (node-manager only, at shutdown)."""
        self.close()
        NativeArenaStore.destroy(self._name.decode())

    @staticmethod
    def destroy(name: str):
        lib = load_shm_lib()
        if lib is not None:
            lib.rayt_shm_unlink(name.encode())
        import shutil

        shutil.rmtree(os.path.join("/tmp", f"rayt_fallback_{name}"),
                      ignore_errors=True)
