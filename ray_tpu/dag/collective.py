"""Collective nodes for compiled DAGs (ref analog:
python/ray/dag/collective_node.py:19, experimental/collective/allreduce.py).

``allreduce.bind([n1, ..., nk])`` inserts one collective op per
participating actor: each actor contributes its upstream node's value and
receives the reduced result in-loop. On the channel fast path the
reduction runs over the out-of-band collective group
(util/collective, GCS-KV rendezvous — the NCCL-group analog); the
per-call fallback executor reduces via the object store on the driver.

For values living on a TPU mesh the right tool is usually an in-mesh
``psum`` inside one jit — DAG collectives are the MPMD-level reduction
between separate SPMD programs (e.g. pipeline stages exchanging host
scalars/metrics, or data-parallel actors averaging host gradients).
"""

from __future__ import annotations

import uuid

from ray_tpu.dag.node import ClassMethodNode


class _AllreduceBinder:
    def bind(self, nodes: list, op: str = "sum",
             group_name: str | None = None) -> list:
        if not nodes:
            raise ValueError("allreduce.bind needs at least one node")
        if not all(isinstance(n, ClassMethodNode) for n in nodes):
            raise TypeError("allreduce.bind takes actor-method nodes")
        actors = {id(n.actor) for n in nodes}
        if len(actors) != len(nodes):
            raise ValueError(
                "allreduce participants must be distinct actors")
        name = group_name or f"dag-ar-{uuid.uuid4().hex[:8]}"
        out = []
        for rank, n in enumerate(nodes):
            node = ClassMethodNode(n.actor, "__collective_allreduce__",
                                   (n,), {})
            node.collective = f"allreduce:{op}"
            node.collective_group = name
            node.collective_rank = rank
            node.collective_world = len(nodes)
            out.append(node)
        return out


allreduce = _AllreduceBinder()
