"""Runtime environments: per-task/actor env materialization (ref analog:
python/ray/_private/runtime_env/plugin.py + the runtime-env agent;
working_dir/py_modules URI packaging mirrors
_private/runtime_env/packaging.py's content-addressed zips in GCS KV).

Supported keys (anything else raises — silently dropping a
correctness-relevant option is worse than rejecting it):

* ``env_vars``:   {str: str} set in the worker before execution.
* ``working_dir``: local directory, zipped + content-addressed into GCS
  KV at submission; workers extract to a cache dir, chdir into it, and
  put it on sys.path.
* ``py_modules``: list of local module directories/files shipped the same
  way and prepended to sys.path.
* ``pip``: list of requirement strings (or {"packages": [...],
  "pip_install_options": [...]}). Workers build a content-addressed venv
  (``--system-site-packages`` so jax & friends stay visible) once per
  unique requirement set, then splice its site-packages ahead of
  sys.path for the task and export VIRTUAL_ENV/PATH so child processes
  resolve the venv's interpreter (ref: _private/runtime_env/pip.py —
  the reference launches dedicated workers from the venv interpreter;
  pooled workers here splice import paths instead and restore after).
* ``conda``: an existing env NAME (str) or an environment spec dict
  ({"dependencies": [...]}, the env.yaml shape). Spec dicts build a
  content-addressed env once per unique spec via the ``conda`` binary
  (override with RAYT_CONDA_EXE; clear error when absent); either form
  splices the env's site-packages ahead of sys.path and exports
  CONDA_PREFIX/PATH (ref: _private/runtime_env/conda.py — same splice
  model as pip above).
"""

from __future__ import annotations

import hashlib
import io
import os
import sys
import time
import zipfile

SUPPORTED_KEYS = {"env_vars", "working_dir", "py_modules", "pip",
                  "conda"}
KV_NAMESPACE = "runtime_env"

# -------------------------------------------------------------- plugin API
# Ref analog: _private/runtime_env/plugin.py RuntimeEnvPlugin — custom
# runtime_env keys handled by user-registered plugins. The plugin object
# itself rides the packaged spec (cloudpickled), so workers need no
# import-path coordination.
_PLUGINS: dict[str, "RuntimeEnvPlugin"] = {}


class RuntimeEnvPlugin:
    """Handle one custom runtime_env key.

    package(value, kv_put) runs on the DRIVER: validate + upload any
    payloads to GCS KV, return the wire value shipped in task specs.
    materialize(spec_value, kv_get) runs in the WORKER before the task:
    apply the env (sys.path, os.environ, files, ...).
    """

    def package(self, value, kv_put):
        return value

    def materialize(self, spec_value, kv_get) -> None:
        raise NotImplementedError


def register_runtime_env_plugin(key: str, plugin: RuntimeEnvPlugin):
    if key in SUPPORTED_KEYS:
        raise ValueError(f"{key!r} is a built-in runtime_env key")
    _PLUGINS[key] = plugin
_CACHE_ROOT = "/tmp/rayt_runtime_env"
_VENV_ROOT = os.path.join(_CACHE_ROOT, "venvs")
_CONDA_ROOT = os.path.join(_CACHE_ROOT, "conda")
# keep at most this many cached venvs (LRU by last-use mtime)
_VENV_GC_KEEP = 8
# skip bulky junk when zipping (ref: packaging.py excludes)
_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}
_MAX_PACKAGE_BYTES = 100 * 1024 * 1024


def validate(renv: dict) -> None:
    if not isinstance(renv, dict):
        raise TypeError(f"runtime_env must be a dict, got {type(renv)}")
    unsupported = set(renv) - SUPPORTED_KEYS - set(_PLUGINS)
    if unsupported:
        raise ValueError(
            f"unsupported runtime_env keys {sorted(unsupported)}; "
            f"supported: {sorted(SUPPORTED_KEYS | set(_PLUGINS))}")
    env_vars = renv.get("env_vars")
    if env_vars is not None:
        if not isinstance(env_vars, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in env_vars.items()):
            raise TypeError("runtime_env['env_vars'] must be {str: str}")
    wd = renv.get("working_dir")
    if wd is not None and not os.path.isdir(wd):
        raise ValueError(f"runtime_env['working_dir'] {wd!r} is not a "
                         "directory")
    for m in renv.get("py_modules") or []:
        if not os.path.exists(m):
            raise ValueError(f"runtime_env['py_modules'] entry {m!r} does "
                             "not exist")
    pip = renv.get("pip")
    if pip is not None:
        if isinstance(pip, dict):
            unknown = set(pip) - {"packages", "pip_install_options"}
            if unknown:
                raise ValueError(
                    f"unsupported runtime_env['pip'] keys {sorted(unknown)}")
            pkgs = pip.get("packages")
        else:
            pkgs = pip
        if not isinstance(pkgs, (list, tuple)) or not all(
                isinstance(p, str) for p in pkgs):
            raise TypeError("runtime_env['pip'] must be a list of "
                            "requirement strings or {'packages': [...]}")
    conda = renv.get("conda")
    if conda is not None:
        if isinstance(conda, dict):
            deps = conda.get("dependencies")
            if not isinstance(deps, (list, tuple)):
                raise TypeError("runtime_env['conda'] spec dict needs a "
                                "'dependencies' list (env.yaml shape)")
            for d in deps:
                if isinstance(d, dict):
                    for k, v in d.items():
                        if not isinstance(v, (list, tuple)) or not all(
                                isinstance(x, str) for x in v):
                            raise TypeError(
                                f"runtime_env['conda'] nested dependency "
                                f"{k!r} must map to a list of strings, "
                                f"got {v!r}")
                elif not isinstance(d, str):
                    raise TypeError(
                        "runtime_env['conda'] dependencies must be "
                        f"strings or dicts, got {d!r}")
        elif not isinstance(conda, str):
            raise TypeError("runtime_env['conda'] must be an env name or "
                            "an environment spec dict")
    if renv.get("conda") is not None and renv.get("pip") is not None:
        raise ValueError("runtime_env: 'conda' and 'pip' are mutually "
                         "exclusive (put pip packages inside the conda "
                         "spec's dependencies)")


def _zip_path(path: str) -> bytes:
    buf = io.BytesIO()
    path = os.path.abspath(path)
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        if os.path.isfile(path):
            zf.write(path, os.path.basename(path))
        else:
            for root, dirs, files in os.walk(path):
                dirs[:] = [d for d in dirs if d not in _EXCLUDE_DIRS]
                for f in files:
                    full = os.path.join(root, f)
                    rel = os.path.relpath(full, path)
                    zf.write(full, rel)
    data = buf.getvalue()
    if len(data) > _MAX_PACKAGE_BYTES:
        raise ValueError(
            f"runtime_env package {path!r} is {len(data)} bytes "
            f"(limit {_MAX_PACKAGE_BYTES})")
    return data


def package(renv: dict, kv_put) -> dict:
    """Driver side: upload working_dir/py_modules zips, return the spec
    shipped inside TaskSpecs. `kv_put(key, value_bytes)` stores to GCS KV.

    Content-addressed keys -> repeat submissions with the same code are
    deduplicated, and workers can cache extractions forever.
    """
    validate(renv)
    spec: dict = {}
    if renv.get("env_vars"):
        spec["env_vars"] = dict(renv["env_vars"])
    if renv.get("working_dir"):
        data = _zip_path(renv["working_dir"])
        key = "wd_" + hashlib.sha256(data).hexdigest()[:32]
        kv_put(key, data)
        spec["working_dir"] = key
    mods = []
    for m in renv.get("py_modules") or []:
        data = _zip_path(m)
        key = "mod_" + hashlib.sha256(data).hexdigest()[:32]
        kv_put(key, data)
        # single .py files extract flat; packages extract into a dir named
        # after the module so `import <name>` works
        name = os.path.basename(os.path.abspath(m))
        mods.append((key, name, os.path.isdir(m)))
    if mods:
        spec["py_modules"] = mods
    pip = renv.get("pip")
    if pip:
        if isinstance(pip, dict):
            pkgs = sorted(pip.get("packages") or [])
            opts = list(pip.get("pip_install_options") or [])
        else:
            pkgs, opts = sorted(pip), []
        tag = hashlib.sha256(
            repr((pkgs, opts, sys.version_info[:2])).encode()
        ).hexdigest()[:16]
        spec["pip"] = {"packages": pkgs, "options": opts, "hash": tag}
    conda = renv.get("conda")
    if conda:
        if isinstance(conda, str):
            spec["conda"] = {"name": conda}
        else:
            canon = _canon_conda(conda)
            tag = hashlib.sha256(repr(canon).encode()).hexdigest()[:16]
            spec["conda"] = {"spec": canon, "hash": tag}
    plugin_entries = []
    for key, plugin in _PLUGINS.items():
        if key in renv:
            import cloudpickle

            packaged = plugin.package(renv[key], kv_put)
            plugin_entries.append(
                (key, cloudpickle.dumps(plugin), packaged))
    if plugin_entries:
        spec["_plugins"] = plugin_entries
    return spec


# ------------------------------------------------------------------- conda
def _canon_conda(spec: dict) -> dict:
    """Canonical spec: dependency ORDER must not change the hash. Nested
    pip blocks ({"pip": [...]}) canonicalize too."""
    deps = []
    for d in spec.get("dependencies") or []:
        if isinstance(d, dict):
            deps.append({k: sorted(v) for k, v in sorted(d.items())})
        else:
            deps.append(d)
    deps.sort(key=repr)
    out = {"dependencies": deps}
    if spec.get("channels"):
        out["channels"] = list(spec["channels"])
    return out


_NAMED_PREFIX_CACHE: dict[tuple, str] = {}


def _conda_exe() -> str:
    import shutil

    exe = os.environ.get("RAYT_CONDA_EXE") or shutil.which("conda")
    if not exe:
        raise RuntimeError(
            "runtime_env['conda'] requires a conda binary on PATH "
            "(or RAYT_CONDA_EXE); none found on this node")
    return exe


def _spec_to_yaml(spec: dict) -> str:
    """Minimal env.yaml writer (no yaml dep): names, channels, deps,
    nested pip lists."""
    lines = ["name: rayt-env"]
    if spec.get("channels"):
        lines.append("channels:")
        lines += [f"  - {c}" for c in spec["channels"]]
    lines.append("dependencies:")
    for d in spec.get("dependencies") or []:
        if isinstance(d, dict):
            for k, vals in d.items():
                lines.append(f"  - {k}:")
                lines += [f"    - {v}" for v in vals]
        else:
            lines.append(f"  - {d}")
    return "\n".join(lines) + "\n"


def ensure_conda_env(conda_spec: dict) -> str:
    """Resolve a conda runtime env to its PREFIX directory.

    Named envs resolve through `conda run`; spec dicts build a
    content-addressed prefix once (same lock + .complete discipline as
    ensure_pip_venv). Ref: _private/runtime_env/conda.py get_or_create.
    """
    import fcntl
    import subprocess

    conda = _conda_exe()
    name = conda_spec.get("name")
    if name:
        # per-process cache: `conda run` costs seconds and the answer
        # never changes for a given name — pooled workers materialize
        # per TASK, not per process
        cached = _NAMED_PREFIX_CACHE.get((conda, name))
        if cached is not None:
            return cached
        r = subprocess.run(
            [conda, "run", "-n", name, "python", "-c",
             "import sys; print(sys.prefix)"],
            capture_output=True, text=True)
        if r.returncode != 0:
            raise RuntimeError(
                f"conda env {name!r} not usable: {r.stderr[-1000:]}")
        prefix = r.stdout.strip().splitlines()[-1]
        _NAMED_PREFIX_CACHE[(conda, name)] = prefix
        return prefix
    prefix = os.path.join(_CONDA_ROOT, conda_spec["hash"])
    marker = os.path.join(prefix, ".complete")
    if os.path.exists(marker):
        try:
            os.utime(prefix)
            return prefix
        except OSError:
            pass
    os.makedirs(_CONDA_ROOT, exist_ok=True)
    lock_path = prefix + ".lock"
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if os.path.exists(marker):
                return prefix
            yaml_path = prefix + ".yaml"
            with open(yaml_path, "w") as f:
                f.write(_spec_to_yaml(conda_spec["spec"]))
            r = subprocess.run(
                [conda, "env", "create", "-p", prefix, "-f", yaml_path],
                capture_output=True, text=True)
            if r.returncode != 0:
                import shutil

                shutil.rmtree(prefix, ignore_errors=True)
                raise RuntimeError(
                    f"conda env create failed: {r.stderr[-2000:]}")
            with open(marker, "w") as f:
                f.write("ok")
            return prefix
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def _conda_site_packages(prefix: str) -> str:
    lib = os.path.join(prefix, "lib")
    try:
        pys = sorted(d for d in os.listdir(lib)
                     if d.startswith("python"))
    except OSError:
        pys = []
    if pys:
        return os.path.join(lib, pys[-1], "site-packages")
    ver = f"python{sys.version_info[0]}.{sys.version_info[1]}"
    return os.path.join(lib, ver, "site-packages")


# ------------------------------------------------------------------ pip/venv
def _venv_site_packages(venv_dir: str) -> str:
    ver = f"python{sys.version_info[0]}.{sys.version_info[1]}"
    return os.path.join(venv_dir, "lib", ver, "site-packages")


def ensure_pip_venv(pip_spec: dict) -> str:
    """Build (or reuse) the cached venv for a pip spec; returns its path.

    Content-addressed by (sorted requirements, options, py version); an
    fcntl lock serializes concurrent workers building the same env, and a
    ``.complete`` marker makes reuse O(1) (ref: pip.py's URI cache + GC).
    """
    import fcntl
    import subprocess

    venv_dir = os.path.join(_VENV_ROOT, pip_spec["hash"])
    marker = os.path.join(venv_dir, ".complete")
    if os.path.exists(marker):
        try:
            os.utime(venv_dir)  # LRU touch + GC grace-window refresh
            return venv_dir
        except OSError:
            pass  # lost a GC race: fall through to the locked build path
    os.makedirs(_VENV_ROOT, exist_ok=True)
    lock_path = venv_dir + ".lock"
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if os.path.exists(marker):
                return venv_dir
            _gc_venvs(keep=_VENV_GC_KEEP - 1)
            subprocess.run(
                [sys.executable, "-m", "venv", "--system-site-packages",
                 venv_dir],
                check=True, capture_output=True, text=True)
            py = os.path.join(venv_dir, "bin", "python")
            cmd = ([py, "-m", "pip", "install", "--quiet",
                    "--disable-pip-version-check"]
                   + list(pip_spec.get("options") or [])
                   + list(pip_spec["packages"]))
            r = subprocess.run(cmd, capture_output=True, text=True)
            if r.returncode != 0:
                import shutil

                shutil.rmtree(venv_dir, ignore_errors=True)
                raise RuntimeError(
                    f"pip install failed for runtime_env: {r.stderr[-2000:]}")
            with open(marker, "w") as f:
                f.write("ok")
            return venv_dir
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def mark_pip_venv_in_use(venv_dir: str):
    """Pin a venv against GC while this process has it on sys.path: a
    pid file under <venv>.inuse/ (liveness-checked by the collector, so
    a crashed worker can't pin forever)."""
    d = venv_dir + ".inuse"
    try:
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, str(os.getpid())), "w"):
            pass
    except OSError:
        pass


def release_pip_venv(pip_spec: dict):
    venv_dir = os.path.join(_VENV_ROOT, pip_spec["hash"])
    try:
        os.remove(os.path.join(venv_dir + ".inuse", str(os.getpid())))
    except OSError:
        pass


def _venv_in_use(venv_dir: str) -> bool:
    d = venv_dir + ".inuse"
    try:
        pids = os.listdir(d)
    except OSError:
        return False
    alive = False
    for p in pids:
        try:
            os.kill(int(p), 0)
            alive = True
        except (ProcessLookupError, ValueError):
            try:
                os.remove(os.path.join(d, p))  # stale pin: crashed worker
            except OSError:
                pass
        except OSError:
            alive = True
    return alive


def _gc_venvs(keep: int):
    """Drop the oldest cached venvs beyond `keep` (LRU by mtime), never
    collecting one a LIVE worker still has spliced into sys.path."""
    import shutil

    try:
        entries = [os.path.join(_VENV_ROOT, e) for e in os.listdir(_VENV_ROOT)
                   if os.path.isdir(os.path.join(_VENV_ROOT, e))
                   and not e.endswith(".inuse")]
    except OSError:
        return
    entries.sort(key=lambda p: os.path.getmtime(p), reverse=True)
    for stale in entries[keep:]:
        if _venv_in_use(stale):
            continue
        # Grace window: ensure_pip_venv's marker fast path utime()s the dir
        # before returning, but the caller pins .inuse only afterwards — a
        # recently-touched venv may be on a reader's sys.path already.
        try:
            if time.time() - os.path.getmtime(stale) < 600.0:
                continue
        except OSError:
            continue
        # A mid-build venv has no .complete marker and no .inuse pins yet;
        # the builder holds LOCK_EX on <venv>.lock for the whole build, so
        # only delete if we can take the lock ourselves (non-blocking).
        import fcntl
        try:
            lock = open(stale + ".lock", "w")
        except OSError:
            continue
        try:
            try:
                fcntl.flock(lock, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                continue  # builder active: skip this round
            try:
                # re-validate under the lock: a reader's utime or a fresh
                # .inuse pin may have landed since the pre-lock checks
                try:
                    if time.time() - os.path.getmtime(stale) < 600.0:
                        continue
                except OSError:
                    continue
                if _venv_in_use(stale):
                    continue
                shutil.rmtree(stale, ignore_errors=True)
                shutil.rmtree(stale + ".inuse", ignore_errors=True)
            finally:
                fcntl.flock(lock, fcntl.LOCK_UN)
        finally:
            lock.close()


def _extract(key: str, data: bytes, subdir: str | None) -> str:
    dest = os.path.join(_CACHE_ROOT, key)
    target = os.path.join(dest, subdir) if subdir else dest
    marker = os.path.join(dest, ".complete")
    if not os.path.exists(marker):
        os.makedirs(target, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(data)) as zf:
            zf.extractall(target)
        with open(marker, "w") as f:
            f.write("ok")
    return dest


def materialize(spec: dict, kv_get) -> None:
    """Worker side: apply a packaged runtime env to this process.
    `kv_get(key)` fetches from GCS KV."""
    for k, v in (spec.get("env_vars") or {}).items():
        os.environ[k] = v
    for key, name, is_dir in spec.get("py_modules") or []:
        data = kv_get(key)
        if data is None:
            raise RuntimeError(f"runtime_env package {key} missing from GCS")
        root = _extract(key, data, name if is_dir else None)
        if root not in sys.path:
            sys.path.insert(0, root)
    wd_key = spec.get("working_dir")
    if wd_key:
        data = kv_get(wd_key)
        if data is None:
            raise RuntimeError(f"runtime_env package {wd_key} missing")
        root = _extract(wd_key, data, None)
        os.chdir(root)
        if root not in sys.path:
            sys.path.insert(0, root)
    pip_spec = spec.get("pip")
    if pip_spec:
        venv_dir = ensure_pip_venv(pip_spec)
        mark_pip_venv_in_use(venv_dir)
        site = _venv_site_packages(venv_dir)
        if site not in sys.path:
            sys.path.insert(0, site)
        # child processes of the task resolve the venv interpreter
        os.environ["VIRTUAL_ENV"] = venv_dir
        os.environ["PATH"] = (os.path.join(venv_dir, "bin") + os.pathsep
                              + os.environ.get("PATH", ""))
        # a module imported under a previous env must not satisfy this
        # env's import of the same distribution
        import importlib

        importlib.invalidate_caches()
    conda_spec = spec.get("conda")
    if conda_spec:
        prefix = ensure_conda_env(conda_spec)
        site = _conda_site_packages(prefix)
        if site not in sys.path:
            sys.path.insert(0, site)
        os.environ["CONDA_PREFIX"] = prefix
        os.environ["PATH"] = (os.path.join(prefix, "bin") + os.pathsep
                              + os.environ.get("PATH", ""))
        import importlib

        importlib.invalidate_caches()
    for key, plugin_blob, packaged in spec.get("_plugins") or []:
        import cloudpickle

        cloudpickle.loads(plugin_blob).materialize(packaged, kv_get)
