"""Model zoo: TPU-first implementations (the reference delegates models to
torch; here the model layer is co-designed with sharding, see
models/llama.py docstring)."""

from ray_tpu.models import llama, lora  # noqa: F401
from ray_tpu.models.lora import (LoraConfig, init_lora_params,  # noqa: F401
                                 lora_logical_axes, merge_lora)
from ray_tpu.models.mlp import MLPConfig, mlp_forward, mlp_init, mlp_loss  # noqa: F401
