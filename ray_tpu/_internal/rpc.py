"""RPC substrate: length-prefixed msgpack frames over asyncio streams.

TPU-native analog of the reference's L0 (ref: src/ray/rpc/ — gRPC services,
retryable clients, and rpc_chaos fault injection). Design decisions:

* One protocol for everything: ``[u32 length][msgpack [msgid, kind, method,
  payload]]`` where payload is a pickle-5 blob (see serialization.py). This
  replaces the reference's per-service protobufs — the control plane here is
  a single-digit number of services, and pickled dataclasses keep the
  schemas in one language while staying introspectable.
* Server-push NOTIFY frames on long-lived connections replace the
  reference's long-poll pubsub (ref: src/ray/pubsub/publisher.h:297) — an
  asyncio stream is already a persistent channel, so the publisher just
  writes frames.
* Chaos hooks (drop request / drop reply with configured probability)
  mirror RAY_testing_rpc_failure (ref: src/ray/rpc/rpc_chaos.h:23).
"""

from __future__ import annotations

import asyncio
import itertools
import random
import struct
import sys
import threading
import traceback
from typing import Any, Awaitable, Callable

import msgpack

from ray_tpu._internal.config import get_config
from ray_tpu._internal.logging_utils import setup_logger

logger = setup_logger("rpc")
from ray_tpu._internal.serialization import (chunks_to_bytes, deserialize,
                                             serialize, serialized_size)

REQUEST, RESPONSE, ERROR, NOTIFY = 0, 1, 2, 3
_LEN = struct.Struct("<I")
MAX_FRAME = 1 << 31
# Stream buffer limit for asyncio readers: the default 64 KiB causes
# transport pause/resume thrash on multi-MiB frames (each readexactly
# wakes dozens of times), collapsing pipelined bulk-transfer throughput.
STREAM_LIMIT = 32 * 1024 * 1024


class RpcError(Exception):
    pass


class RemoteError(RpcError):
    """An exception raised inside a remote handler, re-raised locally."""

    def __init__(self, message: str, remote_traceback: str = ""):
        super().__init__(message)
        self.remote_traceback = remote_traceback

    def __str__(self):
        s = super().__str__()
        if self.remote_traceback:
            s += "\n--- remote traceback ---\n" + self.remote_traceback
        return s


class ConnectionLost(RpcError):
    pass


class _Chaos:
    """Probabilistic request/reply dropping for chaos tests."""

    def __init__(self):
        cfg = get_config()
        self.prob = cfg.testing_rpc_failure_prob
        self.rng = random.Random(cfg.testing_chaos_seed or None)

    def should_drop(self) -> bool:
        return self.prob > 0 and self.rng.random() < self.prob


async def _read_frame(reader: asyncio.StreamReader):
    """Returns (msgid, kind, method, value, is_raw). A 5-element header
    marks an out-of-band payload of `rawlen` bytes: when the tag (4th
    element) is None the bytes are the value verbatim (RAW bulk-transfer
    fast path, is_raw=True); when the tag is truthy the bytes are a
    serialized payload the sender handed to the transport as the raw
    serialize() chunk list — semantically identical to a 4-element
    pickled frame, so is_raw=False and callers deserialize."""
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise RpcError(f"frame too large: {length}")
    data = await reader.readexactly(length)
    frame = msgpack.unpackb(data, raw=False, use_list=True)
    if len(frame) == 5:
        msgid, kind, method, tag, rawlen = frame
        if rawlen > MAX_FRAME:
            raise RpcError(f"raw frame too large: {rawlen}")
        raw = await reader.readexactly(rawlen)
        return msgid, kind, method, raw, tag is None
    msgid, kind, method, payload = frame
    return msgid, kind, method, payload, False


# bytes values at least this large skip pickle+msgpack re-framing and go
# on the wire verbatim (object-transfer chunks are the main rider); the
# receiver hands the bytes straight to the caller. Serialized payloads
# whose total size crosses the same threshold ride out-of-band too, as
# the raw serialize() chunk list: the pickle header and each pickle-5
# buffer reach the transport as separate buffers, cutting the copy count
# to the transport's single writelines join (a sendmsg-capable transport
# would make it true writev) — vs. the joined blob being copied AGAIN
# into the msgpack body on the old path.
RAW_THRESHOLD = 256 * 1024

# tag marking an out-of-band SERIALIZED payload (vs None = verbatim raw)
_SG_TAG = 1

# Pre-3.12 selector transports JOIN writelines buffers (a userspace copy),
# so once writelines returns, the caller's memoryviews are no longer
# referenced and a RawView's mapping pin can drop after drain(). 3.12+
# writelines is sendmsg-based zero-copy: the transport may queue the view
# itself, so releasing the pin after drain() could let eviction overwrite
# bytes still in flight — materialize RawView payloads to bytes there
# (one copy, exactly what the pre-3.12 join costs anyway).
_WRITELINES_JOINS = sys.version_info < (3, 12)


class RawView:
    """A raw response payload that aliases long-lived memory (e.g. a shm
    mapping) plus a completion callback. The rpc layer sends ``data``
    verbatim on the RAW path regardless of size and invokes ``on_sent``
    once the buffer has been handed to the transport — the push side of
    object transfer uses this to keep the source mapping pinned until
    the write drains, then drop its get-ref (no ``bytes()`` copy)."""

    __slots__ = ("data", "on_sent")

    def __init__(self, data, on_sent=None):
        self.data = data
        self.on_sent = on_sent

    def done(self):
        cb, self.on_sent = self.on_sent, None
        if cb is not None:
            try:
                cb()
            except Exception:
                pass


class Serialized:
    """A value the CALLER already passed through serialize(): the rpc
    layer frames the chunk list verbatim instead of re-serializing —
    large payloads ride the scatter-gather path (each pickle-5 buffer
    reaches the transport as its own buffer), small ones join once into
    an inline frame. The DCN channel uses this to serialize on the
    producer's tick thread and keep the event loop to pure framing."""

    __slots__ = ("chunks", "total")

    def __init__(self, chunks: list):
        self.chunks = chunks
        self.total = serialized_size(chunks)


# Coalesced small-frame writes flush once the per-tick buffer holds this
# many bytes (bounds the latency/copy cost of the join for bursty ticks).
COALESCE_MAX_BYTES = 256 * 1024


def _frames(msgid: int, kind: int, method: str, value) -> list:
    """Encode one message as a list of wire buffers (header [+ payload
    chunks]), handed to ``writer.writelines`` verbatim — at most one
    copy (the transport's join) between the value's buffers and the
    socket."""
    if isinstance(value, RawView):
        data = value.data
        if not _WRITELINES_JOINS and not isinstance(data, bytes):
            data = bytes(data)  # see _WRITELINES_JOINS
        head = msgpack.packb([msgid, kind, method, None, len(data)],
                             use_bin_type=True)
        return [_LEN.pack(len(head)) + head, data]
    if isinstance(value, (bytes, bytearray, memoryview)) \
            and len(value) >= RAW_THRESHOLD:
        head = msgpack.packb([msgid, kind, method, None, len(value)],
                             use_bin_type=True)
        return [_LEN.pack(len(head)) + head, value]
    if isinstance(value, Serialized):
        chunks, total = value.chunks, value.total
    else:
        chunks = serialize(value)
        total = serialized_size(chunks)
    if total >= RAW_THRESHOLD:
        head = msgpack.packb([msgid, kind, method, _SG_TAG, total],
                             use_bin_type=True)
        return [_LEN.pack(len(head)) + head, *chunks]
    body = msgpack.packb([msgid, kind, method, chunks_to_bytes(chunks)],
                         use_bin_type=True)
    return [_LEN.pack(len(body)) + body]


class Connection:
    """One live peer connection (either direction). Thread-unsafe; use from
    the owning event loop only."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        sock = writer.get_extra_info("socket")
        if sock is not None:
            try:
                import socket as _socket

                sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            except OSError:
                pass
        try:
            # default write high-water mark is 64 KiB: concurrent bulk
            # responses then thrash drain()/resume cycles; let multi-MiB
            # frames buffer before back-pressuring the writers
            writer.transport.set_write_buffer_limits(high=STREAM_LIMIT)
        except Exception:
            pass
        self._msgid = itertools.count(1)
        self.close_reason = ""
        self._pending: dict[int, asyncio.Future] = {}
        self._notify_handlers: dict[str, Callable[[Any], None]] = {}
        self._closed = asyncio.Event()
        self._chaos = _Chaos()
        self._read_task: asyncio.Task | None = None
        # small-frame coalescing: control frames queued in the same
        # event-loop tick are flushed with ONE writelines (one transport
        # join + one send syscall) instead of a syscall per message
        self._wbuf: list = []
        self._wbuf_bytes = 0
        self._flush_scheduled = False
        # Set by RpcServer for inbound connections:
        self.server_handlers: dict[str, Callable] | None = None
        self.on_close: list[Callable[["Connection"], None]] = []

    def start(self):
        self._read_task = asyncio.ensure_future(self._read_loop())

    @property
    def closed(self) -> bool:
        return self._closed.is_set()

    def peername(self):
        try:
            return self.writer.get_extra_info("peername")
        except Exception:
            return None

    # ------------------------------------------------- coalesced writes
    def _send_frames(self, frames: list):
        """Queue one encoded message for the wire. Small control frames
        (single pre-joined buffer from _frames) coalesce into a per-tick
        batch; multi-buffer messages (RAW / scatter-gather payloads) keep
        the immediate writelines path — their buffers may alias shm
        mappings whose pin lifetime is tied to the write (see RawView),
        and they are exactly the frames big enough that batching buys
        nothing."""
        if len(frames) == 1:
            buf = frames[0]
            self._wbuf.append(buf)
            self._wbuf_bytes += len(buf)
            if self._wbuf_bytes >= COALESCE_MAX_BYTES:
                self._flush_wbuf()
            elif not self._flush_scheduled:
                self._flush_scheduled = True
                asyncio.get_running_loop().call_soon(self._flush_wbuf)
            return
        # large path: pending small frames first (wire order), then the
        # scatter-gather chunk list verbatim
        if self._wbuf:
            self._flush_wbuf()
        self.writer.writelines(frames)

    def _flush_wbuf(self):
        self._flush_scheduled = False
        if not self._wbuf:
            return
        buf, self._wbuf = self._wbuf, []
        self._wbuf_bytes = 0
        if self.closed:
            return  # pending futures already failed by _teardown
        try:
            self.writer.writelines(buf)
        except Exception:
            pass  # the read loop notices the dead transport

    async def _maybe_drain(self):
        """Back-pressure check: only await drain() once the transport's
        buffer is past its high-water mark — the common small-frame case
        never blocks (the reply/ack the caller awaits paces it)."""
        try:
            if self.writer.transport.get_write_buffer_size() > STREAM_LIMIT:
                await self.writer.drain()
        except (ConnectionError, OSError, AttributeError):
            pass

    async def _read_loop(self):
        try:
            while True:
                msgid, kind, method, payload, is_raw = \
                    await _read_frame(self.reader)
                if kind == REQUEST:
                    self._dispatch_request(msgid, method, payload, is_raw)
                elif kind in (RESPONSE, ERROR):
                    fut = self._pending.pop(msgid, None)
                    if fut is not None and not fut.done():
                        if kind == RESPONSE:
                            fut.set_result(
                                payload if is_raw else deserialize(payload))
                        else:
                            msg, tb = deserialize(payload)
                            fut.set_exception(RemoteError(msg, tb))
                elif kind == NOTIFY:
                    handler = self._notify_handlers.get(method)
                    if handler is not None:
                        try:
                            res = handler(
                                payload if is_raw else deserialize(payload))
                            if asyncio.iscoroutine(res):
                                asyncio.ensure_future(res)
                        except Exception:
                            traceback.print_exc()
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
            self.close_reason = self.close_reason or repr(e)
        except asyncio.CancelledError:
            self.close_reason = self.close_reason or "cancelled"
        except BaseException as e:  # diagnosis: NEVER silently drop a conn
            self.close_reason = f"unexpected {type(e).__name__}: {e}"
            logger.warning("rpc read loop died (%s): %s",
                           self.peername(), self.close_reason)
            if isinstance(e, (KeyboardInterrupt, SystemExit)):
                self._teardown()
                raise
        finally:
            self._teardown()

    def _teardown(self):
        if self._closed.is_set():
            return
        # last-gasp flush: messages buffered this tick (e.g. a notify
        # right before close) still reach the transport, which flushes
        # queued bytes before the FIN
        if self._wbuf:
            try:
                self._flush_wbuf()
            except Exception:
                pass
        self._closed.set()
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionLost("connection closed"))
        self._pending.clear()
        try:
            self.writer.close()
        except Exception:
            pass
        for cb in self.on_close:
            try:
                cb(self)
            except Exception:
                traceback.print_exc()

    def _dispatch_request(self, msgid: int, method: str, payload,
                          is_raw: bool):
        """Run a request handler. Sync handlers returning a plain value
        reply inline — no Task object, no scheduling round-trip; only
        handlers that return an awaitable pay for a Task."""
        result = None
        try:
            handler = (self.server_handlers or {}).get(method)
            if handler is None:
                raise RpcError(f"no handler for method {method!r}")
            arg = payload if is_raw else deserialize(payload)
            result = handler(self, arg)
        except Exception as e:
            self._reply(msgid, ERROR, method,
                        (f"{type(e).__name__}: {e}", traceback.format_exc()))
            return
        if asyncio.iscoroutine(result) or isinstance(result, Awaitable):
            asyncio.ensure_future(self._finish_request(msgid, method, result))
            return
        self._reply_result(msgid, method, result)

    async def _finish_request(self, msgid: int, method: str, awaitable):
        result = None
        try:
            try:
                result = await awaitable
            except Exception as e:
                self._reply(msgid, ERROR, method,
                            (f"{type(e).__name__}: {e}",
                             traceback.format_exc()))
                return
            self._reply_result(msgid, method, result)
            await self._maybe_drain()
        finally:
            # after writelines the transport owns the bytes (pre-3.12 it
            # joins; on 3.12+ _frames materialized the view — see
            # _WRITELINES_JOINS); release the handler's mapping pin on
            # every exit path, including chaos drops and encode errors
            if isinstance(result, RawView):
                result.done()

    def _reply_result(self, msgid: int, method: str, result):
        try:
            try:
                if self._chaos.should_drop():
                    return  # drop the reply: client sees a timeout
                self._reply(msgid, RESPONSE, method, result)
            except Exception as e:
                self._reply(msgid, ERROR, method,
                            (f"{type(e).__name__}: {e}",
                             traceback.format_exc()))
        finally:
            if isinstance(result, RawView):
                result.done()

    def _reply(self, msgid: int, kind: int, method: str, value):
        if self.closed:
            return
        try:
            self._send_frames(_frames(msgid, kind, method, value))
        except (ConnectionError, OSError):
            pass

    async def call(self, method: str, arg: Any = None, timeout: float | None = None) -> Any:
        if self.closed:
            raise ConnectionLost("connection closed")
        if timeout is None:
            timeout = get_config().rpc_request_timeout_s
        loop = asyncio.get_running_loop()
        msgid = next(self._msgid)
        fut: asyncio.Future = loop.create_future()
        self._pending[msgid] = fut
        if self._chaos.should_drop():
            pass  # drop the request on the floor: client sees a timeout
        else:
            self._send_frames(_frames(msgid, REQUEST, method, arg))
            await self._maybe_drain()
        # timeout via a plain timer handle on the reply future — cheaper
        # than asyncio.wait_for's wrapper coroutine + waiter future per
        # RPC (this is every control-plane round-trip's hot path)
        timer = loop.call_later(timeout, self._expire_call, msgid, method,
                                timeout)
        try:
            return await fut
        except asyncio.CancelledError:
            self._pending.pop(msgid, None)
            raise
        finally:
            timer.cancel()

    def _expire_call(self, msgid: int, method: str, timeout: float):
        fut = self._pending.pop(msgid, None)
        if fut is not None and not fut.done():
            fut.set_exception(RpcError(
                f"rpc {method!r} timed out after {timeout}s"))

    async def notify(self, method: str, arg: Any = None):
        """One-way message (used for pubsub pushes and fire-and-forget)."""
        if self.closed:
            raise ConnectionLost("connection closed")
        self._send_frames(_frames(0, NOTIFY, method, arg))
        await self._maybe_drain()

    def on_notify(self, method: str, handler: Callable[[Any], None]):
        self._notify_handlers[method] = handler

    async def close(self):
        if not self.close_reason:
            self.close_reason = "closed by:" + "|".join(
                f"{f.name}@{f.filename.rsplit('/', 1)[-1]}:{f.lineno}"
                for f in traceback.extract_stack(limit=6)[:-1])
        if self._read_task is not None:
            self._read_task.cancel()
        self._teardown()

    async def wait_closed(self):
        await self._closed.wait()


class RpcServer:
    """Serves a handler table. Handlers: ``(conn, arg) -> result | awaitable``."""

    def __init__(self, handlers: dict[str, Callable] | None = None):
        self.handlers: dict[str, Callable] = dict(handlers or {})
        self.connections: set[Connection] = set()
        self._server: asyncio.AbstractServer | None = None
        self.port: int | None = None

    def add_handler(self, method: str, fn: Callable):
        self.handlers[method] = fn

    def add_service(self, obj: Any, prefix: str = ""):
        """Register every ``rpc_*`` method of obj as ``<prefix><name>``."""
        for name in dir(obj):
            if name.startswith("rpc_"):
                self.handlers[prefix + name[4:]] = getattr(obj, name)

    async def _on_client(self, reader, writer):
        conn = Connection(reader, writer)
        conn.server_handlers = self.handlers
        conn.on_close.append(lambda c: self.connections.discard(c))
        self.connections.add(conn)
        conn.start()

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._server = await asyncio.start_server(
            self._on_client, host, port, limit=STREAM_LIMIT)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self):
        for conn in list(self.connections):
            await conn.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()


async def connect(
    host: str, port: int, *, handlers: dict[str, Callable] | None = None,
    retries: int | None = None,
) -> Connection:
    """Dial a peer with retry/backoff (ref analog: retryable_grpc_client)."""
    cfg = get_config()
    if retries is None:
        retries = cfg.rpc_max_retries
    delay = cfg.rpc_retry_delay_s
    last: Exception | None = None
    for _ in range(retries + 1):
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host, port, limit=STREAM_LIMIT),
                cfg.rpc_connect_timeout_s)
            conn = Connection(reader, writer)
            if handlers is not None:
                conn.server_handlers = handlers
            conn.start()
            return conn
        except (ConnectionError, OSError, asyncio.TimeoutError) as e:
            last = e
            await asyncio.sleep(delay)
            delay = min(delay * 2, 2.0)
    raise ConnectionLost(f"could not connect to {host}:{port}: {last}")


class EventLoopThread:
    """A dedicated asyncio loop on a daemon thread.

    The driver and workers are synchronous Python; all their RPC runs on
    this loop (ref analog: the C++ io_service threads under core_worker).
    """

    def __init__(self, name: str = "rayt-io"):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run(self, coro, timeout: float | None = None):
        """Run a coroutine on the loop from a foreign thread, blocking."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def spawn(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self):
        self.loop.call_soon_threadsafe(self.loop.stop)
        self._thread.join(timeout=5)
