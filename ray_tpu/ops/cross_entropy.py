"""Softmax cross entropy over large vocabularies.

Computed in fp32 without materializing [batch*seq, vocab] probabilities
twice: logsumexp + gather, which XLA fuses tightly. Supports masking
(ignore index) for padded batches.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array,
                          ignore_index: int = -100
                          ) -> tuple[jax.Array, jax.Array]:
    """logits: [..., vocab] (any dtype, accumulated fp32); labels: [...]
    int32. Returns (mean_loss, num_valid_tokens)."""
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    lse = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(
        logits, safe_labels[..., None], axis=-1)[..., 0]
    nll = (lse - picked) * valid
    n = jnp.maximum(valid.sum(), 1)
    return nll.sum() / n, valid.sum()


def fused_lm_head_cross_entropy(x: jax.Array, head: jax.Array,
                                labels: jax.Array,
                                ignore_index: int = -100,
                                chunk_size: int = 1024
                                ) -> tuple[jax.Array, jax.Array]:
    """lm-head projection + cross entropy WITHOUT materializing the full
    [tokens, vocab] logits tensor.

    x: [b, s, d] final hidden states; head: [d, vocab]; labels: [b, s].
    The token axis is scanned in chunks: each step projects one chunk,
    reduces it to (nll_sum, count), and the backward recomputes that
    chunk's logits — peak memory O(chunk_size * vocab) instead of
    O(b * s * vocab) f32 (2 GiB+ for 8x2048x32k). This is the usual TPU
    fused-xent recipe; the matmul still hits the MXU at full tile size.
    """
    b, s, d = x.shape
    n_tok = b * s
    x2 = x.reshape(n_tok, d)
    labels2 = labels.reshape(n_tok)
    chunk_size = min(chunk_size, n_tok)
    if n_tok % chunk_size != 0:
        # fall back: odd shapes are CI-sized, the dense path is fine there
        logits = (x @ head).astype(jnp.float32)
        return softmax_cross_entropy(logits, labels, ignore_index)
    n_chunks = n_tok // chunk_size

    def body(carry, idx):
        nll_acc, cnt_acc = carry
        xs = jax.lax.dynamic_slice_in_dim(x2, idx * chunk_size, chunk_size)
        ls = jax.lax.dynamic_slice_in_dim(labels2, idx * chunk_size,
                                          chunk_size)
        logits = (xs @ head).astype(jnp.float32)      # [chunk, vocab]
        valid = ls != ignore_index
        safe = jnp.where(valid, ls, 0)
        lse = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
        nll = (lse - picked) * valid
        return (nll_acc + nll.sum(), cnt_acc + valid.sum()), None

    (nll_sum, count), _ = jax.lax.scan(
        jax.checkpoint(body), (jnp.zeros((), jnp.float32),
                               jnp.zeros((), jnp.int32)),
        jnp.arange(n_chunks))
    n = jnp.maximum(count, 1)
    return nll_sum / n, count
