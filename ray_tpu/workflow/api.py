"""Workflow DAG construction + durable executor (ref analogs:
python/ray/workflow/workflow_executor.py:32 — step scheduling loop;
workflow_state_from_dag.py — DAG -> steps; storage/ — checkpoint layout).

Storage layout (one dir per workflow under the workflow root):
  <root>/<workflow_id>/
    meta.json                  {"status": ..., "output_step": id}
    steps/<step_id>.pkl        checkpointed step result
    steps/<step_id>.json       {"name", "upstream": [...]}
    events/<name>.pkl          durable delivered-event payloads
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

DEFAULT_ROOT = os.path.expanduser(
    os.environ.get("RAYT_WORKFLOW_ROOT", "/tmp/rayt_workflows"))


@dataclass
class StepNode:
    fn: Callable
    args: tuple
    kwargs: dict
    name: str
    max_retries: int = 3
    num_cpus: float = 1.0
    _step_id: Optional[str] = field(default=None, repr=False)

    def options(self, *, name: Optional[str] = None,
                max_retries: Optional[int] = None,
                num_cpus: Optional[float] = None) -> "StepNode":
        if name is not None:
            self.name = name
        if max_retries is not None:
            self.max_retries = max_retries
        if num_cpus is not None:
            self.num_cpus = num_cpus
        return self

    # ------------------------------------------------------------ identity
    def step_id(self) -> str:
        """Content-derived id: function name + plain-arg repr + upstream
        step ids, so editing a step invalidates its own and downstream
        checkpoints only (ref: workflow step id semantics)."""
        if self._step_id is None:
            h = hashlib.sha256()
            h.update(self.name.encode())
            for a in list(self.args) + sorted(
                    self.kwargs.items(), key=lambda kv: kv[0]):
                if isinstance(a, tuple):  # kwargs item
                    h.update(repr(a[0]).encode())
                    a = a[1]
                if isinstance(a, StepNode):
                    h.update(a.step_id().encode())
                else:
                    h.update(repr(a).encode())
            self._step_id = f"{self.name}-{h.hexdigest()[:16]}"
        return self._step_id

    def upstream(self) -> list["StepNode"]:
        out = [a for a in self.args if isinstance(a, StepNode)]
        out += [v for v in self.kwargs.values() if isinstance(v, StepNode)]
        return out


class EventNode(StepNode):
    """A step satisfied by an EXTERNAL event instead of a task (ref
    analog: ray.workflow event system / wait_for_event): the workflow
    parks until ``send_event(workflow_id, name, payload)`` lands; the
    payload is checkpointed like any step result, so resume after a
    crash replays it without waiting again."""

    def __init__(self, name: str, timeout_s: Optional[float] = None):
        super().__init__(fn=None, args=(), kwargs={},
                         name=f"event:{name}")
        self.event_name = name
        self.timeout_s = timeout_s


def wait_for_event(name: str,
                   timeout_s: Optional[float] = None) -> EventNode:
    return EventNode(name, timeout_s)


def send_event(workflow_id: str, name: str, payload: Any = None, *,
               storage: Optional[str] = None) -> None:
    """Deliver an event to a (possibly running) workflow. Durable: the
    payload is written into the workflow's storage, so it survives both
    sender and workflow restarts."""
    store = _Store(workflow_id, storage)
    store.save_event(name, payload)


class Continuation:
    """Returned BY a step to hand control to a sub-workflow: the step's
    durable result becomes the continuation DAG's result (ref analog:
    ray.workflow.continuation — nested workflows)."""

    def __init__(self, node: StepNode):
        if not isinstance(node, StepNode):
            raise TypeError("continuation() takes a bound step")
        self.node = node


def continuation(node: StepNode) -> Continuation:
    return Continuation(node)


def step(fn: Callable = None, **opts):
    """Decorator: `fn.bind(*args)` builds a StepNode DAG."""
    def wrap(f):
        class _Builder:
            def __init__(self):
                self.__name__ = f.__name__

            def bind(self, *args, **kwargs) -> StepNode:
                node = StepNode(f, args, kwargs, name=f.__name__)
                return node.options(**opts) if opts else node

            def __call__(self, *args, **kwargs):
                return f(*args, **kwargs)

        return _Builder()
    return wrap(fn) if fn is not None else wrap


# ----------------------------------------------------------------- storage
def _wf_dir(workflow_id: str, root: Optional[str]) -> str:
    return os.path.join(root or DEFAULT_ROOT, workflow_id)


def _write_json(path: str, data: dict):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f)
    os.replace(tmp, path)


class _Store:
    def __init__(self, workflow_id: str, root: Optional[str]):
        self.dir = _wf_dir(workflow_id, root)
        self.steps_dir = os.path.join(self.dir, "steps")

    def _ensure(self):
        os.makedirs(self.steps_dir, exist_ok=True)

    def has(self, step_id: str) -> bool:
        return os.path.exists(os.path.join(self.steps_dir,
                                           step_id + ".pkl"))

    def load(self, step_id: str) -> Any:
        with open(os.path.join(self.steps_dir, step_id + ".pkl"),
                  "rb") as f:
            return pickle.load(f)

    def save(self, step_id: str, value: Any, meta: dict):
        self._ensure()
        path = os.path.join(self.steps_dir, step_id + ".pkl")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f, protocol=4)
        os.replace(tmp, path)
        _write_json(os.path.join(self.steps_dir, step_id + ".json"), meta)

    # ------------------------------------------------------------- events
    def _event_path(self, name: str) -> str:
        return os.path.join(self.dir, "events", name + ".pkl")

    def save_event(self, name: str, payload: Any):
        path = self._event_path(name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(payload, f, protocol=4)
        os.replace(tmp, path)

    def has_event(self, name: str) -> bool:
        return os.path.exists(self._event_path(name))

    def load_event(self, name: str) -> Any:
        with open(self._event_path(name), "rb") as f:
            return pickle.load(f)

    def set_meta(self, **kv):
        self._ensure()
        path = os.path.join(self.dir, "meta.json")
        meta = self.meta()
        meta.update(kv)
        _write_json(path, meta)

    def meta(self) -> dict:
        try:
            with open(os.path.join(self.dir, "meta.json")) as f:
                return json.load(f)
        except OSError:
            return {}


# ---------------------------------------------------------------- executor
def _topo(final: StepNode) -> list[StepNode]:
    order: list[StepNode] = []
    seen: set[str] = set()

    def visit(node: StepNode):
        if node.step_id() in seen:
            return
        seen.add(node.step_id())
        for up in node.upstream():
            visit(up)
        order.append(node)

    visit(final)
    return order


def _execute(final: StepNode, store: _Store) -> Any:
    """Run the DAG over cluster tasks, checkpointing every step result.
    Independent branches execute concurrently: every step whose upstreams
    are resolved is submitted immediately, and results are checkpointed as
    they arrive (ref: workflow_executor.py step scheduling loop)."""
    import ray_tpu as rt

    nodes = {n.step_id(): n for n in _topo(final)}
    results: dict[str, Any] = {}
    for sid in nodes:
        if store.has(sid):
            results[sid] = store.load(sid)
    submitted: set[str] = set(results)
    inflight: dict[Any, str] = {}  # ObjectRef -> step_id

    def resolve(a):
        return results[a.step_id()] if isinstance(a, StepNode) else a

    event_started: dict[str, float] = {}

    def submit_ready():
        for sid, node in nodes.items():
            if sid in submitted:
                continue
            if any(u.step_id() not in results for u in node.upstream()):
                continue
            if isinstance(node, EventNode):
                event_started.setdefault(sid, time.monotonic())
                if store.has_event(node.event_name):
                    payload = store.load_event(node.event_name)
                    store.save(sid, payload, {
                        "name": node.name, "upstream": [],
                        "finished_at": time.time()})
                    results[sid] = payload
                    submitted.add(sid)
                elif node.timeout_s is not None and (
                        time.monotonic() - event_started[sid]
                        > node.timeout_s):
                    raise TimeoutError(
                        f"event {node.event_name!r} not delivered within "
                        f"{node.timeout_s}s")
                continue   # parked until the event lands
            args = [resolve(a) for a in node.args]
            kwargs = {k: resolve(v) for k, v in node.kwargs.items()}
            task = rt.remote(num_cpus=node.num_cpus,
                             max_retries=node.max_retries)(node.fn)
            inflight[task.remote(*args, **kwargs)] = sid
            submitted.add(sid)

    def harvest(ref, draining: bool = False) -> Exception | None:
        """Checkpoint one finished ref; return its error instead of raising
        so a failing branch can't discard completed siblings' results. A
        step returning a Continuation hands control to its sub-workflow:
        the sub-DAG executes against the SAME store (its steps checkpoint
        and resume individually) and its result becomes the step's.
        While DRAINING after a failure, continuations are NOT started
        (no new work after first_error) — the step stays un-checkpointed
        and resume re-runs it."""
        sid = inflight.pop(ref)
        try:
            value = rt.get(ref)
            if isinstance(value, Continuation):
                if draining:
                    return None
                value = _execute(value.node, store)
            node = nodes[sid]
            store.save(sid, value, {
                "name": node.name,
                "upstream": [u.step_id() for u in node.upstream()],
                "finished_at": time.time()})
        except Exception as e:  # incl. save errors (ENOSPC, ...): the
            return e            # drain loop must never lose first_error
        results[sid] = value
        return None

    first_error: Exception | None = None
    submit_ready()  # nothing in flight yet: a submit error may propagate
    while final.step_id() not in results:
        if not inflight:
            parked = [n for sid, n in nodes.items()
                      if isinstance(n, EventNode) and sid not in results]
            if parked:
                time.sleep(0.1)      # waiting on external events
                submit_ready()
                continue
            raise RuntimeError("workflow has unrunnable steps (cycle?)")
        has_parked_events = any(
            isinstance(n, EventNode) and sid not in results
            for sid, n in nodes.items())
        done, _ = rt.wait(list(inflight), num_returns=1,
                          timeout=0.2 if has_parked_events else None)
        for ref in done:
            first_error = first_error or harvest(ref)
        if first_error is None:
            try:
                submit_ready()
            except Exception as e:  # submission failure: drain like a
                first_error = e     # failed step so siblings checkpoint
        if first_error is not None:
            # drain still-running siblings so their work is checkpointed
            # before the failure propagates (resume won't redo it)
            while inflight:
                done, _ = rt.wait(list(inflight),
                                  num_returns=len(inflight), timeout=300.0)
                if not done:
                    break
                for ref in done:
                    harvest(ref, draining=True)
            raise first_error
    return results[final.step_id()]


def run(final: StepNode, *, workflow_id: Optional[str] = None,
        storage: Optional[str] = None) -> Any:
    """Execute a workflow durably; returns the final step's result."""
    workflow_id = workflow_id or f"wf-{int(time.time() * 1000):x}"
    store = _Store(workflow_id, storage)
    store.set_meta(status="RUNNING", workflow_id=workflow_id,
                   output_step=final.step_id(), started_at=time.time())
    try:
        out = _execute(final, store)
    except Exception as e:
        store.set_meta(status="FAILED", error=repr(e))
        raise
    store.set_meta(status="SUCCESSFUL", finished_at=time.time())
    return out


def resume(workflow_id: str, final: StepNode, *,
           storage: Optional[str] = None) -> Any:
    """Re-run an interrupted workflow: checkpointed steps are loaded,
    the rest execute (ref: workflow resume semantics)."""
    store = _Store(workflow_id, storage)
    store.set_meta(status="RUNNING")
    try:
        out = _execute(final, store)
    except Exception as e:
        store.set_meta(status="FAILED", error=repr(e))
        raise
    store.set_meta(status="SUCCESSFUL", finished_at=time.time())
    return out


def get_output(workflow_id: str, *, storage: Optional[str] = None) -> Any:
    store = _Store(workflow_id, storage)
    meta = store.meta()
    if meta.get("status") != "SUCCESSFUL":
        raise RuntimeError(
            f"workflow {workflow_id} is {meta.get('status', 'UNKNOWN')}")
    return store.load(meta["output_step"])


def list_workflows(*, storage: Optional[str] = None) -> list[dict]:
    root = storage or DEFAULT_ROOT
    out = []
    try:
        ids = os.listdir(root)
    except OSError:
        return out
    for wid in sorted(ids):
        meta = _Store(wid, storage).meta()
        if meta:
            out.append(meta)
    return out
