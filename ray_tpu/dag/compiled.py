"""Per-call compiled DAG execution — the FALLBACK executor.

Eligible DAGs compile onto pre-allocated channels (shm rings node-local,
DCN ring channels cross-node, device channels for jax.Array edges) with
frozen per-actor schedules instead (dag/channel_exec.py — the fast
path, ref analog: python/ray/dag/compiled_dag_node.py:757 +
dag_node_operation.py); this module handles the rest: graphs with
function nodes.

compile() topologically sorts the graph once and freezes the submission
plan; execute() replays it with object refs wired producer→consumer, so
intermediate values move directly worker-to-worker through the object
store (the driver only submits).

Device edges: a node marked `.with_tensor_transport()` keeps its
jax.Array output in the producing actor's device memory (HBM) and the
consumer fetches raw shard bytes directly from that actor, rebuilding
the array on its own devices — no host pickle bounce through the
object store (ref analog: torch_tensor_nccl_channel.py NCCL channels;
see core/device_objects.py). For TPU the *intra-mesh* device plane is
still the mesh itself (XLA collectives inside one jit); device edges
are the MPMD-level transport between SPMD programs.

Pipeline parallelism: execute_async() overlaps successive executions —
each call submits immediately without waiting for prior results, so
microbatch k+1's stage-1 runs while microbatch k is in stage 2 (the
actors' ordered queues form the pipeline).
"""

from __future__ import annotations

from typing import Any

from ray_tpu.dag.node import (ClassMethodNode, DAGNode, FunctionNode,
                              InputAttributeNode, InputNode, MultiOutputNode)


def _collective_apply_fallback(self, gname: str, world: int, rank: int,
                               spec: str, value):
    """Runs on the member actor via __rayt_apply__: one-shot out-of-band
    reduction for the per-call executor (the channel executor keeps a
    long-lived group instead, and lowers in-mesh when the participants
    share one device mesh)."""
    from ray_tpu.util.collective import init_collective_group

    kind, op = spec.split(":")
    assert kind in ("allreduce", "allgather"), spec
    group = init_collective_group(world, rank, group_name=gname)
    try:
        if kind == "allreduce":
            return group.allreduce(value, op=op)
        return group.allgather(value)
    finally:
        try:
            group.destroy()
        except Exception:
            pass


class CompiledDAGRef:
    """Future for one execute(); resolves to the output node's value(s)."""

    def __init__(self, refs, multi: bool):
        self._refs = refs
        self._multi = multi

    def get(self, timeout: float | None = None):
        import ray_tpu as rt

        values = rt.get(self._refs, timeout=timeout)
        return values if self._multi else values[0]


class CompiledDAG:
    # per-call submissions already get per-call fault tolerance (task
    # retries + lineage), so the recovery engine (dag/recovery.py)
    # treats this executor as never having dead-ring failures: epoch
    # stays 0 and failed_peers() is always empty.
    epoch = 0

    def __init__(self, output_node: DAGNode):
        self.output_node = output_node
        self.topo = self._topo_sort(output_node)
        self.input_node = None
        for node in self.topo:
            if isinstance(node, InputNode):
                if self.input_node is not None and \
                        self.input_node is not node:
                    raise ValueError("a DAG may have only one InputNode")
                self.input_node = node

    @staticmethod
    def _topo_sort(root: DAGNode) -> list[DAGNode]:
        order: list[DAGNode] = []
        seen: set[int] = set()

        def visit(node: DAGNode):
            if id(node) in seen:
                return
            seen.add(id(node))
            for up in node._upstream():
                visit(up)
            order.append(node)

        visit(root)
        return order

    # ------------------------------------------------------------- execution
    def execute_async(self, *args, **kwargs) -> CompiledDAGRef:
        """Submit one pass through the DAG; returns immediately (pipeline
        microbatches by calling repeatedly)."""
        import uuid

        # unique per execution: collective members of THIS pass rendezvous
        # under it, so overlapping/repeated executions never collide
        exec_tag = uuid.uuid4().hex[:8]
        values: dict[int, Any] = {}
        for node in self.topo:
            if isinstance(node, InputNode):
                if len(args) == 1 and not kwargs:
                    values[id(node)] = args[0]
                else:
                    values[id(node)] = (args, kwargs)
            elif isinstance(node, InputAttributeNode):
                parent_val = values[id(node.parent)]
                if isinstance(parent_val, tuple) and len(parent_val) == 2 \
                        and isinstance(parent_val[1], dict):
                    a, kw = parent_val
                    values[id(node)] = (kw[node.key] if node.by_attr
                                        else a[node.key])
                elif node.by_attr:
                    values[id(node)] = getattr(parent_val, node.key)
                else:
                    values[id(node)] = parent_val[node.key]
            elif isinstance(node, ClassMethodNode) and \
                    getattr(node, "collective", None):
                # per-call fallback for collective nodes: each member actor
                # joins a per-tick out-of-band group and reduces (slow path
                # — the channel executor keeps one long-lived group)
                from ray_tpu.api import ActorMethod

                gname = f"{node.collective_group}-{exec_tag}"
                val = self._resolve(node.args[0], values)
                m = ActorMethod(node.actor, "__rayt_apply__")
                values[id(node)] = m.remote(
                    _collective_apply_fallback, gname,
                    node.collective_world, node.collective_rank,
                    node.collective, val)
            elif isinstance(node, ClassMethodNode):
                call_args = tuple(self._resolve(a, values)
                                  for a in node.args)
                call_kwargs = {k: self._resolve(v, values)
                               for k, v in node.kwargs.items()}
                method = getattr(node.actor, node.method_name)
                if getattr(node, "tensor_transport", False):
                    method = method.options(tensor_transport=True)
                values[id(node)] = method.remote(*call_args, **call_kwargs)
            elif isinstance(node, FunctionNode):
                call_args = tuple(self._resolve(a, values)
                                  for a in node.args)
                call_kwargs = {k: self._resolve(v, values)
                               for k, v in node.kwargs.items()}
                values[id(node)] = node.remote_fn.remote(*call_args,
                                                         **call_kwargs)
            elif isinstance(node, MultiOutputNode):
                values[id(node)] = [self._to_ref(values[id(o)])
                                    for o in node.outputs]
        out = values[id(self.output_node)]
        if isinstance(self.output_node, MultiOutputNode):
            return CompiledDAGRef(out, multi=True)
        return CompiledDAGRef([self._to_ref(out)], multi=False)

    def execute(self, *args, **kwargs):
        """Submit and return a CompiledDAGRef (call .get() for values)."""
        return self.execute_async(*args, **kwargs)

    @staticmethod
    def _resolve(arg: Any, values: dict):
        if isinstance(arg, DAGNode):
            return values[id(arg)]
        return arg

    @staticmethod
    def _to_ref(value: Any):
        import ray_tpu as rt
        from ray_tpu.core.object_ref import ObjectRef

        if isinstance(value, ObjectRef):
            return value
        return rt.put(value)

    def failed_peers(self) -> dict:
        return {}  # per-call path: retries handle actor death already

    def teardown(self):
        pass  # per-call path holds no persistent resources
