"""Small MLP (MNIST-class) — BASELINE.json config #2's model.

Used by the JaxTrainer DDP path and tests; trivially shardable on the
``data`` axis (pure DP: params replicated, batch sharded).
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp

from ray_tpu.ops.cross_entropy import softmax_cross_entropy


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    in_dim: int = 784
    hidden: tuple = (512, 512)
    n_classes: int = 10
    dtype: object = jnp.float32


def mlp_init(cfg: MLPConfig, key: jax.Array) -> list[dict]:
    dims = (cfg.in_dim,) + tuple(cfg.hidden) + (cfg.n_classes,)
    keys = jax.random.split(key, len(dims) - 1)
    return [
        {"w": (jax.random.normal(k, (a, b)) / math.sqrt(a)).astype(cfg.dtype),
         "b": jnp.zeros((b,), cfg.dtype)}
        for k, a, b in zip(keys, dims[:-1], dims[1:])
    ]


def mlp_forward(params: list[dict], x: jax.Array) -> jax.Array:
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jax.nn.relu(x)
    return x


def mlp_loss(params: list[dict], batch: dict):
    logits = mlp_forward(params, batch["x"])
    loss, n = softmax_cross_entropy(logits, batch["y"])
    acc = jnp.mean(jnp.argmax(logits, -1) == batch["y"])
    return loss, {"loss": loss, "accuracy": acc}
