"""ray_tpu.train — distributed training on TPU slices (ref analog:
python/ray/train; architecture per train/v2, SURVEY.md §2.3/§3.4)."""

from ray_tpu.train.checkpoint import (AsyncSave, Checkpoint,  # noqa: F401
                                      CheckpointManager, load_pytree,
                                      save_pytree, save_pytree_async)
from ray_tpu.train.config import (CheckpointConfig, FailureConfig,  # noqa: F401
                                  Result, RunConfig, ScalingConfig)
from ray_tpu.train.controller import (ElasticScalingPolicy,  # noqa: F401
                                      FailurePolicy, ScalingPolicy,
                                      TrainController, TrainingFailedError)
from ray_tpu.train.ingest import (CorpusIngestIterator,  # noqa: F401
                                  IngestSpec)
from ray_tpu.train.recipes import (corpus_pretrain_loop,  # noqa: F401
                                   lora_finetune_loop)
from ray_tpu.train.session import (get_checkpoint, get_context,  # noqa: F401
                                   get_ingest, report)
from ray_tpu.train.telemetry import StepRecorder  # noqa: F401
from ray_tpu.train.trainer import DataParallelTrainer, JaxTrainer  # noqa: F401
