"""Streaming resource delta sync (VERDICT r5 item #10; ref analog:
src/ray/common/ray_syncer/ray_syncer.h:83 — delta broadcast instead of
full-view polling). Unit-level: 100 virtual nodes against the GcsServer
handlers directly (no processes), asserting sync payloads scale with
CHANGES, not cluster size. Integration: the live multi-node path is
exercised by tests/test_multi_node.py through spillback."""

import pickle

import pytest

from ray_tpu._internal.ids import NodeID
from ray_tpu.core.common import Address, NodeInfo


@pytest.fixture
def gcs_with_nodes():
    import asyncio

    from ray_tpu.core.gcs import GcsServer

    gcs = GcsServer()

    class _Conn:
        on_close: list = []

        async def close(self):
            pass

    nids = []
    loop = asyncio.new_event_loop()
    try:
        for i in range(100):
            nid = NodeID.random()
            nids.append(nid)
            info = NodeInfo(node_id=nid,
                            address=Address("127.0.0.1", 20000 + i),
                            resources_total={"CPU": 8.0})
            loop.run_until_complete(
                gcs.rpc_register_node(_Conn(), info))
    finally:
        loop.close()
    yield gcs, nids


def _payload_size(obj) -> int:
    return len(pickle.dumps(obj))


def test_delta_pull_scales_with_changes(gcs_with_nodes):
    gcs, nids = gcs_with_nodes
    # first pull: a fresh consumer gets all 100 nodes (as a full view or
    # as 100 changed entries — equivalent)
    first = gcs.rpc_get_cluster_resources_delta(None, 0)
    view = first["full"] if first["full"] is not None else first["changed"]
    assert len(view) == 100
    v = first["version"]

    # steady state, nothing changed: the response is O(1)
    idle = gcs.rpc_get_cluster_resources_delta(None, v)
    assert idle["full"] is None and idle["changed"] == {}
    assert _payload_size(idle) < 200

    # one node's availability changes -> exactly one entry travels
    gcs.rpc_heartbeat(None, (nids[7], {"CPU": 3.0}, False))
    delta = gcs.rpc_get_cluster_resources_delta(None, v)
    assert list(delta["changed"]) == [nids[7].hex()]
    assert delta["changed"][nids[7].hex()]["available"] == {"CPU": 3.0}
    # the one-change payload is ~100x smaller than the full view
    assert _payload_size(delta) * 20 < _payload_size(first)

    # an unchanged-value heartbeat does NOT bump the version
    v2 = delta["version"]
    gcs.rpc_heartbeat(None, (nids[7], {"CPU": 3.0}, False))
    assert gcs.resource_version == v2


def test_delta_heartbeat_merges_and_deletes(gcs_with_nodes):
    gcs, nids = gcs_with_nodes
    nid = nids[0]
    gcs.rpc_heartbeat(None, (nid, {"CPU": 2.0, "pg_0": 1.0}, False))
    assert gcs.node_resources_available[nid] == {"CPU": 2.0, "pg_0": 1.0}
    # None deletes a key (placement-group bundle released)
    gcs.rpc_heartbeat(None, (nid, {"pg_0": None}, False))
    assert gcs.node_resources_available[nid] == {"CPU": 2.0}
    # legacy 2-tuple form still replaces the whole view
    gcs.rpc_heartbeat(None, (nid, {"CPU": 8.0}))
    assert gcs.node_resources_available[nid] == {"CPU": 8.0}


def test_delta_pull_survives_log_eviction(gcs_with_nodes):
    gcs, nids = gcs_with_nodes
    v = gcs.rpc_get_cluster_resources_delta(None, 0)["version"]
    # push the change log far past its horizon
    for i in range(5000):
        gcs.rpc_heartbeat(None,
                          (nids[i % 100], {"CPU": float(i % 7)}, False))
    resp = gcs.rpc_get_cluster_resources_delta(None, v)
    # horizon lost -> full view, never a silently-partial delta
    assert resp["full"] is not None and len(resp["full"]) == 100


def test_delta_pull_handles_gcs_restart_version_reset(gcs_with_nodes):
    gcs, _ = gcs_with_nodes
    # consumer's version is from a previous GCS incarnation (larger than
    # the fresh server's counter): must get a full view, not "no change"
    resp = gcs.rpc_get_cluster_resources_delta(
        None, gcs.resource_version + 1000)
    assert resp["full"] is not None
