"""JaxTrainer — the data-parallel-and-beyond trainer (ref analogs:
train/base_trainer.py:111/567 `BaseTrainer.fit`,
train/data_parallel_trainer.py:25; architecture follows train v2: the
controller runs in the driver, NOT wrapped in a Tune trial).

The torch-backend process-group bootstrap (train/torch/config.py:66) is
replaced by mesh construction: each worker is one TPU host; the user loop
asks the session for its mesh (`train.get_context().get_mesh()`) and
builds a GSPMD train step (ray_tpu.parallel.spmd). Host-plane rendezvous
(the NCCLUniqueId analog) rides the collective group the WorkerGroup sets
up over GCS KV.
"""

from __future__ import annotations

from typing import Callable, Optional

from ray_tpu.train.config import Result, RunConfig, ScalingConfig
from ray_tpu.train.controller import TrainController


class JaxTrainer:
    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[dict] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 scaling_policy=None):
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.scaling_policy = scaling_policy

    def fit(self) -> Result:
        controller = TrainController(
            self.train_loop_per_worker, self.train_loop_config,
            self.scaling_config, self.run_config,
            scaling_policy=self.scaling_policy)
        return controller.run()


# Alias matching the reference's naming for the DP trainer family.
DataParallelTrainer = JaxTrainer
