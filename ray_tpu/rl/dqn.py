"""DQN — value-based off-policy algorithm (ref analogs:
rllib/algorithms/dqn/dqn.py + dqn_rainbow_learner.py: replay-buffer
training loop, target network, double-Q; the learner math is an
independent jitted JAX implementation).

Dataflow: DQNRunner actors step envs with epsilon-greedy over Q =
module logits -> transitions into a ReplayBuffer actor -> driver samples
minibatches -> jitted double-DQN Huber TD update -> periodic hard target
sync -> weights broadcast to runners (same weight-sync pattern as PPO).
"""

from __future__ import annotations

import dataclasses
import time

import cloudpickle
import numpy as np

import ray_tpu as rt
from ray_tpu.rl.actor_manager import FaultTolerantActorManager
from ray_tpu.rl.env import make_vector_env, require_discrete
from ray_tpu.rl.module import MLPModuleConfig
from ray_tpu.rl.replay import ReplayBuffer, ReplayRolloutMixin


class DQNRunner(ReplayRolloutMixin):
    """Epsilon-greedy rollout actor producing replay transitions."""

    def __init__(self, env_name: str, num_envs: int, seed: int,
                 module_cfg_blob: bytes):
        from ray_tpu._internal.spawn import wait_site_ready

        wait_site_ready()
        import jax

        jax.config.update("jax_platforms", "cpu")
        self.env = make_vector_env(env_name, num_envs, seed)
        self.module_cfg = cloudpickle.loads(module_cfg_blob)
        self._rng = np.random.default_rng(seed)
        self._obs = self.env.reset(seed)
        self._params = None
        self._ep_return = np.zeros(num_envs, np.float32)
        self._completed: list[float] = []

    def set_weights(self, params) -> bool:
        self._params = params
        return True

    def sample(self, num_steps: int, epsilon: float) -> dict:
        """[T*N] flat transition arrays + completed episode returns."""
        import jax.numpy as jnp

        from ray_tpu.rl import module as rlm

        assert self._params is not None, "set_weights first"
        N = self.env.num_envs

        def select(obs):
            q, _ = rlm.forward(self._params, jnp.asarray(obs))
            greedy = np.asarray(jnp.argmax(q, axis=-1))
            explore = self._rng.random(N) < epsilon
            return np.where(
                explore,
                self._rng.integers(0, self.module_cfg.num_actions, N),
                greedy).astype(np.int32)

        return self._rollout(num_steps, select)

    def ping(self) -> bool:
        return True


@dataclasses.dataclass
class DQNConfig:
    env: str = "CartPole-v1"
    num_env_runners: int = 2
    num_envs_per_runner: int = 8
    rollout_fragment_length: int = 32
    hidden: tuple = (64, 64)
    lr: float = 1e-3
    gamma: float = 0.99
    buffer_capacity: int = 50_000
    learning_starts: int = 1_000
    train_batch_size: int = 128
    updates_per_iteration: int = 16
    target_update_freq: int = 100       # updates between hard target syncs
    epsilon_initial: float = 1.0
    epsilon_final: float = 0.05
    epsilon_decay_steps: int = 10_000
    double_q: bool = True
    seed: int = 0

    def build(self) -> "DQN":
        return DQN(self)


class DQN:
    def __init__(self, config: DQNConfig):
        import jax
        import jax.numpy as jnp
        import optax

        self.config = config
        probe = make_vector_env(config.env, 1, config.seed)
        require_discrete(probe, "DQN")
        self.module_cfg = MLPModuleConfig(
            observation_size=probe.observation_size,
            num_actions=probe.num_actions, hidden=config.hidden)
        from ray_tpu.rl import module as rlm

        self.params = rlm.init_params(
            self.module_cfg, jax.random.PRNGKey(config.seed))
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self._opt = optax.adam(config.lr)
        self._opt_state = self._opt.init(self.params)
        gamma, double_q = config.gamma, config.double_q

        def td_loss(params, target_params, batch):
            q, _ = rlm.forward(params, batch["obs"])
            q_sa = q[jnp.arange(q.shape[0]), batch["actions"]]
            q_next_target, _ = rlm.forward(target_params, batch["next_obs"])
            if double_q:
                q_next_online, _ = rlm.forward(params, batch["next_obs"])
                next_a = jnp.argmax(q_next_online, axis=-1)
            else:
                next_a = jnp.argmax(q_next_target, axis=-1)
            q_next = q_next_target[jnp.arange(q.shape[0]), next_a]
            target = batch["rewards"] + gamma * q_next * (
                1.0 - batch["dones"].astype(jnp.float32))
            target = jax.lax.stop_gradient(target)
            return optax.huber_loss(q_sa, target).mean()

        def update(params, target_params, opt_state, batch):
            loss, grads = jax.value_and_grad(td_loss)(
                params, target_params, batch)
            updates, opt_state = self._opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        self._update = jax.jit(update)

        blob = cloudpickle.dumps(self.module_cfg)
        runner_cls = rt.remote(num_cpus=1)(DQNRunner)
        self._runners = FaultTolerantActorManager([
            runner_cls.remote(config.env, config.num_envs_per_runner,
                              config.seed + 1 + i, blob)
            for i in range(config.num_env_runners)])
        self._buffer = rt.remote(num_cpus=0)(ReplayBuffer).remote(
            config.buffer_capacity, config.seed)
        self._broadcast_weights()
        self._iteration = 0
        self._env_steps = 0
        self._updates = 0
        self._last_returns: list[float] = []

    # ------------------------------------------------------------------ api
    def _broadcast_weights(self):
        ref = rt.put(self.params)
        self._runners.foreach(lambda a: a.set_weights.remote(ref))

    def _epsilon(self) -> float:
        c = self.config
        frac = min(1.0, self._env_steps / max(1, c.epsilon_decay_steps))
        return c.epsilon_initial + frac * (c.epsilon_final
                                           - c.epsilon_initial)

    def train(self) -> dict:
        import jax.numpy as jnp

        c = self.config
        t0 = time.monotonic()
        eps = self._epsilon()
        samples = self._runners.foreach(
            lambda a: a.sample.remote(c.rollout_fragment_length, eps))
        returns = []
        for s in samples:
            self._env_steps += s["steps"]
            returns.extend(s["episode_returns"])
            rt.get(self._buffer.add.remote(s["transitions"]), timeout=60)
        losses = []
        if self._env_steps >= c.learning_starts:
            for _ in range(c.updates_per_iteration):
                batch = rt.get(
                    self._buffer.sample.remote(c.train_batch_size),
                    timeout=60)
                if batch is None:
                    break
                batch = {k: jnp.asarray(v) for k, v in batch.items()}
                self.params, self._opt_state, loss = self._update(
                    self.params, self.target_params, self._opt_state, batch)
                losses.append(float(loss))
                self._updates += 1
                if self._updates % c.target_update_freq == 0:
                    import jax

                    self.target_params = jax.tree.map(
                        lambda x: x, self.params)
            self._broadcast_weights()
        self._iteration += 1
        self._last_returns = (self._last_returns + returns)[-100:]
        mean_ret = (float(np.mean(self._last_returns))
                    if self._last_returns else None)
        return {
            "training_iteration": self._iteration,
            "env_steps": self._env_steps,
            "num_updates": self._updates,
            "epsilon": eps,
            "episode_return_mean": mean_ret,
            "loss": float(np.mean(losses)) if losses else None,
            "time_s": time.monotonic() - t0,
        }

    def stop(self):
        for a, _kill in [(self._buffer, None)] + [
                (r, None) for r in self._runners._actors]:
            try:
                rt.kill(a)
            except Exception:
                pass
