"""TrainController — the v2-style run state machine (ref analog:
train/v2/_internal/execution/controller.py:74 `TrainController` +
failure_handling/failure_policy.py:14).

Loop: start worker group → poll run futures + drain reported results →
on worker death consult the FailurePolicy → either restart the whole
group from the latest checkpoint (TPU slices restart gang-wise; there is
no single-worker recovery inside an SPMD program) or surface the error.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Optional

import ray_tpu as rt
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import Result, RunConfig, ScalingConfig
from ray_tpu.train.worker_group import WorkerGroup


class TrainingFailedError(RuntimeError):
    pass


class FailurePolicy:
    """Decide RETRY vs RAISE after a worker-group failure."""

    RETRY = "retry"
    RAISE = "raise"

    def __init__(self, max_failures: int):
        self.max_failures = max_failures
        self.failures = 0

    def decide(self, error: BaseException) -> str:
        self.failures += 1
        if self.max_failures < 0 or self.failures <= self.max_failures:
            return self.RETRY
        return self.RAISE


class ScalingPolicy:
    """Elasticity hook (ref: scaling_policy.py:26): called before each
    (re)start with the requested config; may return a resized one. Slice
    granularity is the caller's responsibility — you can't drop one host
    of a slice."""

    def on_start(self, scaling: ScalingConfig) -> ScalingConfig:
        return scaling


class ElasticScalingPolicy(ScalingPolicy):
    """Re-mesh at worker (slice) granularity on restart: size the group
    to what the ALIVE cluster can hold right now, clamped to
    [min_workers, max_workers]. On a node death the failure path
    checkpoints, this policy shrinks the group, the surviving hosts
    rebuild the collective group + mesh at the new world size, and the
    user loop resumes from the latest checkpoint; when capacity returns a
    later (re)start grows the group back (ref:
    train/v2/_internal/execution/scaling_policy/scaling_policy.py:26).

    One worker == one TPU host of a slice, so shrinking by whole workers
    IS slice-granular — a worker never holds a fraction of a slice's
    chips (ScalingConfig.worker_resources carries the per-host bundle).
    """

    def __init__(self, min_workers: int = 1,
                 max_workers: Optional[int] = None,
                 settle_timeout_s: float = 15.0):
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.settle_timeout_s = settle_timeout_s

    def _capacity(self, per_worker: dict) -> int:
        cluster = rt.cluster_resources()  # alive nodes only
        cap = None
        for r, amt in per_worker.items():
            if amt <= 0:
                continue
            fit = int(cluster.get(r, 0.0) // amt)
            cap = fit if cap is None else min(cap, fit)
        return cap if cap is not None else 0

    def on_start(self, scaling: ScalingConfig) -> ScalingConfig:
        import dataclasses as _dc

        want = self.max_workers or scaling.num_workers
        per = scaling.worker_resources()
        deadline = time.monotonic() + self.settle_timeout_s
        cap = self._capacity(per)
        # brief settle: right after a crash the dead node may not be
        # reaped from the view yet (or a replacement may be mid-register)
        while cap < self.min_workers and time.monotonic() < deadline:
            time.sleep(0.5)
            cap = self._capacity(per)
        n = max(self.min_workers, min(want, cap))
        if n == scaling.num_workers:
            return scaling
        return _dc.replace(scaling, num_workers=n)


class TrainController:
    def __init__(self, train_fn: Callable, config: Optional[dict],
                 scaling: ScalingConfig, run_config: RunConfig,
                 scaling_policy: Optional[ScalingPolicy] = None):
        self.train_fn = train_fn
        self.config = config
        self.scaling = scaling
        self.run_config = run_config
        name = run_config.name or f"train_{int(time.time())}"
        self.experiment_name = name
        self.experiment_path = os.path.join(
            run_config.resolved_storage_path(), name)
        os.makedirs(self.experiment_path, exist_ok=True)
        cc = run_config.checkpoint_config
        self.checkpoint_manager = CheckpointManager(
            cc.num_to_keep, cc.checkpoint_score_attribute,
            cc.checkpoint_score_order)
        self.failure_policy = FailurePolicy(
            run_config.failure_config.max_failures)
        self.scaling_policy = scaling_policy or ScalingPolicy()
        self.latest_metrics: Optional[dict] = None
        self._group_seq = 0
        self._last_world_size = scaling.num_workers
        self._seen_checkpoints: set[str] = set()
        # train-plane observability: the run id keys every step record /
        # compile event / memory snapshot this run's workers publish
        # (core/gcs_train_manager); minted here, threaded through
        # WorkerGroup.setup into each worker's session
        from ray_tpu.train.telemetry import mint_run_id

        self.run_id = mint_run_id()

    def _publish_run_state(self, state: str, world_size: int):
        """Best-effort run lifecycle record onto the train_state
        channel (RUNNING at group start, FINISHED/FAILED at the end) —
        carries the job id so the GCS purges the run on job finish."""
        import time as _time

        from ray_tpu.train.telemetry import publish_record

        job_hex = ""
        try:
            from ray_tpu.core.object_ref import get_core_worker

            cw = get_core_worker()
            if cw is not None and cw.job_id is not None:
                job_hex = cw.job_id.hex()
        except Exception:
            pass
        publish_record({"kind": "run", "run_id": self.run_id,
                        "experiment": self.experiment_name,
                        "job_id": job_hex, "world_size": world_size,
                        "state": state, "ts": _time.time()})

    # ------------------------------------------------------------------ run
    def run(self) -> Result:
        error: Optional[BaseException] = None
        while True:
            sized = self.scaling_policy.on_start(self.scaling)
            self._last_world_size = sized.num_workers
            group = WorkerGroup(
                sized, self.run_config,
                self.experiment_path, self.experiment_name, self._group_seq,
                run_id=self.run_id)
            self._group_seq += 1
            latest = (self.checkpoint_manager.latest.path
                      if self.checkpoint_manager.latest else None)
            try:
                group.start(latest)
                self._publish_run_state("RUNNING", sized.num_workers)
                run_refs = group.run_async(self.train_fn, self.config)
                self._poll(group, run_refs)
                self._ingest(group.drain_results())
                group.shutdown()
                self._publish_run_state("FINISHED", sized.num_workers)
                return self._result(None)
            except (rt.ActorDiedError, rt.WorkerCrashedError, rt.TaskError,
                    rt.RayTpuError, TimeoutError) as e:
                self._ingest_safe(group)
                self._recover_checkpoints_from_storage()
                group.shutdown()
                if self.failure_policy.decide(e) == FailurePolicy.RETRY:
                    continue
                error = e
                self._publish_run_state("FAILED", sized.num_workers)
                return self._result(error)

    def _poll(self, group: WorkerGroup, run_refs: list):
        pending = list(run_refs)
        while pending:
            done, pending = rt.wait(pending, num_returns=len(pending),
                                    timeout=0.25)
            self._ingest(group.drain_results())
            for ref in done:
                rt.get(ref)  # raises worker/user errors

    def _recover_checkpoints_from_storage(self):
        """After a crash, reported-but-undrained checkpoints exist only as
        directories with per-rank `.complete-rank_*` markers — pick up any
        complete ones (all ranks reported) the manager hasn't seen."""
        import glob

        n = self._last_world_size
        for step_dir in sorted(glob.glob(
                os.path.join(self.experiment_path, "checkpoint_*"))):
            if step_dir in self._seen_checkpoints:
                continue
            markers = glob.glob(os.path.join(step_dir, ".complete-rank_*"))
            if len(markers) >= n:
                self._seen_checkpoints.add(step_dir)
                self.checkpoint_manager.register(Checkpoint(step_dir), {})

    def _ingest_safe(self, group: WorkerGroup):
        try:
            self._ingest(group.drain_results())
        except Exception:
            pass

    def _ingest(self, entries: list[dict]):
        # metrics: rank-0 rows are canonical (ref: v1 session semantics);
        # checkpoints: first sighting of a step dir registers it.
        for e in sorted(entries, key=lambda e: (e["index"], e["rank"])):
            if e["rank"] == 0:
                self.latest_metrics = e["metrics"]
            ckpt_dir = e.get("checkpoint_dir")
            if ckpt_dir and ckpt_dir not in self._seen_checkpoints:
                self._seen_checkpoints.add(ckpt_dir)
                self.checkpoint_manager.register(
                    Checkpoint(ckpt_dir), e["metrics"])

    def _result(self, error: Optional[BaseException]) -> Result:
        result = Result(
            metrics=self.latest_metrics,
            checkpoint=self.checkpoint_manager.latest,
            error=error,
            path=self.experiment_path)
        result._best_checkpoints = self.checkpoint_manager.best_with_metrics
        if error is not None:
            raise TrainingFailedError(
                f"training failed after {self.failure_policy.failures - 1} "
                f"restarts") from error
        return result
