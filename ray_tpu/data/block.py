"""Block primitives. A Block is a row-major list of dicts; batch formats
convert to columnar numpy / pandas on demand (ref analog:
python/ray/data/_internal/arrow_block.py — the reference is Arrow-first;
here rows keep the executor simple and numpy is the TPU-adjacent batch
format fed to jax)."""

from __future__ import annotations

from typing import Any, Iterable, Iterator

import numpy as np

Block = list  # list[dict[str, Any]] | list[Any] for simple datasets


def is_record_block(block: Block) -> bool:
    return bool(block) and isinstance(block[0], dict)


def to_batch(block: Block, batch_format: str = "numpy") -> Any:
    if batch_format == "rows":
        return block
    if not block:
        return {} if batch_format == "numpy" else None
    if not is_record_block(block):
        arr = np.asarray(block)
        if batch_format == "numpy":
            return {"item": arr}
        import pandas as pd

        return pd.DataFrame({"item": arr})
    keys = block[0].keys()
    cols = {k: np.asarray([row[k] for row in block]) for k in keys}
    if batch_format == "numpy":
        return cols
    import pandas as pd

    return pd.DataFrame(cols)


def from_batch(batch: Any) -> Block:
    if batch is None:
        return []
    if isinstance(batch, list):
        return batch
    if isinstance(batch, dict):
        if not batch:
            return []
        keys = list(batch)
        n = len(batch[keys[0]])
        return [{k: _item(batch[k][i]) for k in keys} for i in range(n)]
    # pandas
    return batch.to_dict("records")


def _item(x):
    if isinstance(x, np.generic):
        return x.item()
    return x


def batch_iter(block: Block, batch_size: int | None) -> Iterator[Block]:
    if batch_size is None or batch_size <= 0:
        yield block
        return
    for i in range(0, len(block), batch_size):
        yield block[i:i + batch_size]


def split_block(block: Block, n: int) -> list[Block]:
    out = []
    size, rem = divmod(len(block), n)
    start = 0
    for i in range(n):
        end = start + size + (1 if i < rem else 0)
        out.append(block[start:end])
        start = end
    return out


def concat_blocks(blocks: Iterable[Block]) -> Block:
    out: Block = []
    for b in blocks:
        out.extend(b)
    return out
