"""Worker-node process: one NodeManager joining an existing GCS (ref
analog: `ray start --address=...` spawning a raylet that registers with
the head's GCS — python/ray/scripts/scripts.py `start`, raylet main).

Prints one JSON line {"nm_port", "node_id"} on stdout, then serves until
SIGTERM. Used by cluster_utils.Cluster to stand up in-process multi-node
clusters for tests (ref: python/ray/cluster_utils.py:135).
"""

from __future__ import annotations

import argparse
import asyncio
import json


async def run(args):
    from ray_tpu._internal.ids import NodeID
    from ray_tpu.core.common import Address
    from ray_tpu.core.node_manager import NodeManager

    gcs_host, gcs_port = args.gcs_address.split(":")
    resources = json.loads(args.resources)
    labels = json.loads(args.labels)
    nm = NodeManager(
        node_id=NodeID.random(), resources=resources,
        gcs_address=Address(gcs_host, int(gcs_port)),
        labels=labels)
    addr = await nm.start()
    print(json.dumps({"nm_port": addr.port, "node_id": nm.node_id.hex()}),
          flush=True)
    import signal

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass
    try:
        await stop.wait()
    finally:
        await nm.stop()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--gcs-address", type=str, required=True)
    p.add_argument("--resources", type=str, default="{}")
    p.add_argument("--labels", type=str, default="{}")
    args = p.parse_args()
    try:
        asyncio.run(run(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
