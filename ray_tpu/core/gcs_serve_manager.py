"""GCS serve manager — the per-request serve-path observability store
(ref analog: the Serve data plane's request-level telemetry; same
store contract as gcs_task_manager.h: coalesce, memory bound with
per-key eviction + dropped accounting, server-side filtered queries).

The ingress proxies and replicas publish PARTIAL request records on the
``serve_state`` channel, keyed by the request id the proxy minted
(echoed to clients as ``X-Rayt-Request-Id``): the proxy side carries
the top-level latency waterfall (admission wait, router capacity-gate
park, dispatch, stream) whose stages TILE the end-to-end wall time by
construction; the replica side nests its own queue/service split and —
for LLM deployments — the engine phase breakdown (prefill time + chunk
count, TTFT, per-token decode time, decode-batch occupancy). Partials
from the two processes arrive in either order on independent flush
cadences; this module coalesces them by request id.

Retention is TAIL-BIASED and decided at finalize time (when the
outcome and e2e latency are known): errors, sheds, and stream aborts
are always retained, the slowest decile (per-app rolling p90) is
always retained, and the happy path is sampled at
``RAYT_SERVE_REQUEST_SAMPLE``. Prometheus derivation happens BEFORE
the sampling drop, from every finalized record, so the
``rayt_serve_{ttft_s,tpot_s,queue_wait_s,prefill_s}`` histograms are
unskewed by sampling. Replicas additionally publish cumulative engine
counter reports; the manager differences consecutive reports into
``rayt_serve_engine_*_total`` counters and the
``rayt_serve_decode_batch_occupancy`` gauge (the GCS process has no
core worker, so — like the dag/event managers — it builds raw records
and feeds its own metrics store via drain_metric_records()).
"""

from __future__ import annotations

import collections
import random
import time
from typing import Optional

from ray_tpu.util.builtin_metrics import (serve_data_plane_metric_records,
                                          serve_engine_metric_records,
                                          serve_request_metric_records)

# channel convention: the owning manager defines its channel name and
# gcs.py re-exports it next to its siblings (CH_DAGS, CH_EVENTS, ...)
CH_SERVE = "serve_state"

# the waterfall stages whose record keys summarize() rolls p50/p99 for,
# in render order: proxy-side tiling first, then the nested replica /
# engine breakdowns (not part of the tiling sum — cross-process clocks
# don't line up, so they nest under the record instead)
WATERFALL_STAGES = ("admission_s", "router_s", "dispatch_s", "stream_s")
NESTED_STAGES = ("replica_queue_s", "replica_service_s",
                 "engine_queue_s", "engine_prefill_s", "engine_decode_s")

# outcomes that are never sampled out (the tail the store exists for)
_ALWAYS_KEEP = ("error", "shed", "timeout", "queue_full", "no_replicas",
                "stream_aborted")

# per-app rolling e2e window backing the slowest-decile threshold
_E2E_WINDOW = 200
# finalized-then-sampled-out ids remembered so a late replica partial
# doesn't resurrect a dropped record as a phantom pending entry
_RECENT_FINAL = 512


def _pct(values: list, q: float) -> Optional[float]:
    if not values:
        return None
    vs = sorted(values)
    i = min(len(vs) - 1, max(0, int(q * (len(vs) - 1) + 0.5)))
    return vs[i]


class GcsServeManager:
    def __init__(self, max_requests: int = 2000, sample: float = 1.0):
        self.max_requests = max_requests
        self.sample = sample
        # request_id -> coalesced FINALIZED record; insertion-ordered so
        # the oldest record of an app is cheap to find via the app index
        self._requests: dict[str, dict] = {}
        # app -> insertion-ordered set of its request_ids
        self._by_app: dict[str, dict[str, None]] = {}
        # store-side eviction accounting (memory cap), per app
        self._dropped_per_app: collections.Counter = collections.Counter()
        # finalize-time sampling drops (distinct from eviction: these
        # were deliberately not retained; their metrics still emitted)
        self._sampled_per_app: collections.Counter = collections.Counter()
        # partials awaiting their proxy-final sibling, FIFO-bounded
        # (a crashed proxy's orphan partial must not leak forever)
        self._pending: dict[str, dict] = {}
        # finalized-but-dropped ids (bounded): late partials for these
        # are discarded instead of re-opening a pending entry
        self._recent_final: collections.OrderedDict = \
            collections.OrderedDict()
        # per-app rolling e2e window for the slowest-decile threshold
        self._e2e: dict[str, collections.deque] = {}
        # (app, deployment, replica) -> last cumulative engine counters
        self._engine_last: dict[tuple, dict] = {}
        self._metric_buf: list[dict] = []
        self._finalized = 0

    # ------------------------------------------------------------ ingest
    def ingest(self, message):
        """One pubsub payload: a record dict or a batched list of them
        (proxies/replicas flush lists on the metrics cadence)."""
        if isinstance(message, dict):
            message = [message]
        for m in message or ():
            try:
                kind = m.get("kind")
                if kind == "request":
                    self._apply_request(m)
                elif kind == "engine":
                    self._apply_engine(m)
                elif kind == "app_deleted":
                    self.on_app_deleted(m.get("app") or "")
            except Exception:
                continue  # observability must not take down the GCS

    @staticmethod
    def _merge(rec: dict, part: dict):
        """Coalesce one partial into a record: nested stage dicts merge
        key-wise, scalars last-write-win (None never overwrites)."""
        for k, v in part.items():
            if k in ("kind", "side", "final"):
                continue
            if isinstance(v, dict):
                # key-wise, None never overwrites — a disagg request's
                # decode partial (prefill_s: None) and prefill partial
                # (decode keys absent) coalesce into ONE engine
                # waterfall whichever flush lands first
                dst = rec.setdefault(k, {})
                for kk, vv in v.items():
                    if vv is not None:
                        dst[kk] = vv
            elif v is not None:
                rec[k] = v

    def _apply_request(self, part: dict):
        rid = part.get("request_id") or ""
        if not rid:
            return
        rec = self._requests.get(rid)
        if rec is not None:           # late partial for a retained record
            self._merge(rec, part)
            if part.get("side") == "replica":
                self._emit_replica_metrics(rec, part)
            return
        if rid in self._recent_final:  # late partial, record sampled out
            if part.get("side") == "replica":
                self._emit_replica_metrics(part, part)
            return
        pend = self._pending.get(rid)
        if pend is None:
            pend = self._pending[rid] = {"request_id": rid}
            # orphan bound: drop the OLDEST pending partial beyond 2x
            # the retained cap (proxies crash; replicas outlive calls)
            while len(self._pending) > max(256, 2 * self.max_requests):
                self._pending.pop(next(iter(self._pending)))
        self._merge(pend, part)
        if part.get("side") == "replica":
            self._emit_replica_metrics(pend, part)
        if part.get("final"):
            self._pending.pop(rid, None)
            self._finalize(pend)

    # ----------------------------------------------------- finalize path
    def _finalize(self, rec: dict):
        self._finalized += 1
        app = rec.get("app") or ""
        e2e = float(rec.get("e2e_s") or 0.0)
        outcome = rec.get("outcome") or "ok"
        ts = float(rec.get("start_ts") or time.time())
        # Prometheus derivation from EVERY finalized record, before any
        # sampling decision — retention shapes the store, not the series
        self._metric_buf.extend(serve_request_metric_records(
            app,
            queue_wait_s=(float((rec.get("stages") or {})
                                .get("admission_s") or 0.0)
                          + float((rec.get("stages") or {})
                                  .get("router_s") or 0.0)),
            ttft_s=rec.get("ttft_s"), tpot_s=rec.get("tpot_s"), ts=ts))
        eng = rec.get("engine") or {}
        # data-plane counters: router-level prefix classification
        # (hit|spill|cold — the engine's own hit/cold is the fallback
        # when the record predates the router stamp) and per-proxy
        # admission attribution (sheds never held a window slot). KV
        # handoff bytes derive at replica-partial INGEST instead
        # (_emit_replica_metrics) — a disagg replica's flush may land
        # after the proxy final
        self._metric_buf.extend(serve_data_plane_metric_records(
            app,
            prefix_outcome=(rec.get("prefix_cache")
                            or eng.get("prefix_cache")),
            proxy=(rec.get("proxy") if outcome != "shed" else None),
            ts=ts))
        win = self._e2e.get(app)
        if win is None:
            win = self._e2e[app] = collections.deque(maxlen=_E2E_WINDOW)
        win.append(e2e)
        if not self._retain(outcome, e2e, win):
            self._sampled_per_app[app] += 1
            self._recent_final[rec["request_id"]] = None
            while len(self._recent_final) > _RECENT_FINAL:
                self._recent_final.popitem(last=False)
            return
        self._requests[rec["request_id"]] = rec
        self._by_app.setdefault(app, {})[rec["request_id"]] = None
        self._maybe_evict()

    def _retain(self, outcome: str, e2e: float,
                win: collections.deque) -> bool:
        if outcome in _ALWAYS_KEEP:
            return True
        if len(win) < 20:
            return True       # window warming up: keep everything
        p90 = _pct(list(win), 0.9)
        if p90 is not None and e2e >= p90:
            return True       # slowest decile always kept
        if self.sample >= 1.0:
            return True
        return random.random() < max(0.0, self.sample)

    def _maybe_evict(self):
        """Per-app eviction under the global cap: the app holding the
        most records gives up its OLDEST one (one flood app can't evict
        every other app's history)."""
        while len(self._requests) > self.max_requests:
            victim = max(self._by_app, key=lambda a: len(self._by_app[a]))
            ids = self._by_app[victim]
            rid = next(iter(ids))
            del ids[rid]
            if not ids:
                del self._by_app[victim]
            self._requests.pop(rid, None)
            self._dropped_per_app[victim] += 1

    # --------------------------------------------- engine report deltas
    def _emit_replica_metrics(self, rec: dict, part: dict):
        """Per-request engine-phase histograms, derived from the replica
        partial at ITS ingest (ordering vs the proxy final doesn't
        matter — the series never waits on coalescing)."""
        eng = part.get("engine") or {}
        if not eng:
            return
        app = rec.get("app") or part.get("app") or ""
        ts = float(part.get("ts") or time.time())
        self._metric_buf.extend(serve_request_metric_records(
            app, prefill_s=eng.get("prefill_s"), ts=ts))
        # KV handoff volume (disagg): only the prefill pool's partial
        # carries the bytes, so ingest-time derivation counts each
        # handoff exactly once whatever the flush order
        self._metric_buf.extend(serve_data_plane_metric_records(
            app, kv_bytes=int(eng.get("kv_handoff_bytes") or 0),
            edge_kind=str(eng.get("kv_handoff_edge") or ""), ts=ts))

    def _apply_engine(self, m: dict):
        """Cumulative engine counters from a replica report → deltas
        into the rayt_serve_engine_* family (counter records carry
        DELTAS; the metrics store sums them). A counter that went
        BACKWARD means the replica restarted its engine — treat the new
        cumulative value as the delta."""
        app = m.get("app") or ""
        dep = m.get("deployment") or ""
        rep = m.get("replica") or ""
        cur = {k: int(m.get(k) or 0)
               for k in ("prefills", "prefill_chunks", "decode_steps")}
        key = (app, dep, rep)
        last = self._engine_last.get(key) or {}
        deltas = {k: (v - last.get(k, 0) if v >= last.get(k, 0) else v)
                  for k, v in cur.items()}
        self._engine_last[key] = cur
        self._metric_buf.extend(serve_engine_metric_records(
            app, dep, rep,
            prefills=deltas["prefills"],
            prefill_chunks=deltas["prefill_chunks"],
            decode_steps=deltas["decode_steps"],
            occupancy=m.get("occupancy"),
            ts=float(m.get("ts") or time.time())))

    def drain_metric_records(self) -> list[dict]:
        out, self._metric_buf = self._metric_buf, []
        return out

    # -------------------------------------------------------- app purge
    def on_app_deleted(self, app: str):
        """serve.delete() purge: the app's retained records, pending
        partials, windows, engine baselines, and dropped accounting all
        go — a redeployed app starts with a clean ledger."""
        for rid in list(self._by_app.pop(app, ())):
            self._requests.pop(rid, None)
        for rid in [r for r, p in self._pending.items()
                    if (p.get("app") or "") == app]:
            self._pending.pop(rid, None)
        self._e2e.pop(app, None)
        self._dropped_per_app.pop(app, None)
        self._sampled_per_app.pop(app, None)
        for key in [k for k in self._engine_last if k[0] == app]:
            self._engine_last.pop(key, None)

    # ------------------------------------------------------------ queries
    def get(self, request_id: str) -> Optional[dict]:
        """One record by request id (hex prefix accepted, like the other
        id-taking CLI surfaces)."""
        rec = self._requests.get(request_id)
        if rec is None and request_id:
            rec = next((r for rid, r in self._requests.items()
                        if rid.startswith(request_id)), None)
        if rec is None:
            return None
        return self._snap(rec)

    @staticmethod
    def _snap(rec: dict) -> dict:
        # snapshot the mutable sub-dicts: consumers serialize off the
        # GCS loop while live records keep coalescing late partials
        out = dict(rec)
        for k in ("stages", "replica_stages", "engine"):
            if isinstance(out.get(k), dict):
                out[k] = dict(out[k])
        return out

    def _iter_filtered(self, app=None, outcome=None, model_id=None,
                       errors_only=False, min_e2e_s=None):
        if app is not None:
            source = (self._requests[r]
                      for r in self._by_app.get(app, ()))
        else:
            source = iter(self._requests.values())
        for rec in source:
            oc = rec.get("outcome") or "ok"
            if outcome is not None and oc != outcome:
                continue
            if errors_only and oc == "ok":
                continue
            if model_id is not None and \
                    (rec.get("model_id") or "") != model_id:
                continue
            if min_e2e_s is not None and \
                    float(rec.get("e2e_s") or 0.0) < min_e2e_s:
                continue
            yield rec

    def list(self, *, app: Optional[str] = None,
             outcome: Optional[str] = None,
             model_id: Optional[str] = None, errors_only: bool = False,
             min_e2e_s: Optional[float] = None, slow: bool = False,
             limit: int = 100) -> dict:
        """Filtered request records with truncation + per-app dropped /
        sampled accounting. Newest first; ``slow=True`` orders by e2e
        descending instead (the `rayt list requests --slow` view)."""
        matched = list(self._iter_filtered(app, outcome, model_id,
                                           errors_only, min_e2e_s))
        if slow:
            matched.sort(key=lambda r: float(r.get("e2e_s") or 0.0),
                         reverse=True)
        else:
            matched.reverse()  # insertion order -> newest first
        limit = max(0, limit or 0)  # <= 0 means unlimited
        truncated = max(0, len(matched) - limit) if limit else 0
        return {
            "requests": [self._snap(r)
                         for r in (matched[:limit] if limit else matched)],
            "total": len(matched),
            "truncated": truncated,
            "dropped": self.dropped_counts(app),
            "sampled_out": self.sampled_counts(app),
        }

    def summarize(self, *, app: Optional[str] = None) -> dict:
        """Per-app rollup: request/outcome counts plus p50/p99/mean per
        waterfall stage and for ttft/tpot/e2e — the `rayt serve status`
        table and the dashboard Serve tab's data source."""
        apps: dict[str, dict] = {}
        for rec in self._iter_filtered(app):
            a = rec.get("app") or ""
            e = apps.get(a)
            if e is None:
                e = apps[a] = {"count": 0,
                               "outcomes": collections.Counter(),
                               "stages": collections.defaultdict(list),
                               "e2e": [], "ttft": [], "tpot": []}
            e["count"] += 1
            e["outcomes"][rec.get("outcome") or "ok"] += 1
            e["e2e"].append(float(rec.get("e2e_s") or 0.0))
            if rec.get("ttft_s") is not None:
                e["ttft"].append(float(rec["ttft_s"]))
            if rec.get("tpot_s") is not None:
                e["tpot"].append(float(rec["tpot_s"]))
            stages = rec.get("stages") or {}
            for k in WATERFALL_STAGES:
                if stages.get(k) is not None:
                    e["stages"][k].append(float(stages[k]))
            rs = rec.get("replica_stages") or {}
            eng = rec.get("engine") or {}
            for k, src, kk in (("replica_queue_s", rs, "queue_s"),
                               ("replica_service_s", rs, "service_s"),
                               ("engine_queue_s", eng, "queue_s"),
                               ("engine_prefill_s", eng, "prefill_s"),
                               ("engine_decode_s", eng, "decode_s")):
                if src.get(kk) is not None:
                    e["stages"][k].append(float(src[kk]))
        out = {}
        for a, e in sorted(apps.items()):
            def roll(vals):
                return {"p50": _pct(vals, 0.5), "p99": _pct(vals, 0.99),
                        "mean": (sum(vals) / len(vals)) if vals else None,
                        "n": len(vals)}
            out[a] = {
                "count": e["count"],
                "outcomes": dict(e["outcomes"]),
                "e2e": roll(e["e2e"]),
                "ttft": roll(e["ttft"]),
                "tpot": roll(e["tpot"]),
                "stages": {k: roll(v) for k, v in e["stages"].items()},
            }
        return {
            "apps": out,
            "total_requests": sum(e["count"] for e in out.values())
            if out else 0,
            "finalized_total": self._finalized,
            "dropped": self.dropped_counts(app),
            "sampled_out": self.sampled_counts(app),
        }

    def dropped_counts(self, app: Optional[str] = None) -> dict:
        if app is not None:
            return {app: self._dropped_per_app.get(app, 0)}
        return dict(self._dropped_per_app)

    def sampled_counts(self, app: Optional[str] = None) -> dict:
        if app is not None:
            return {app: self._sampled_per_app.get(app, 0)}
        return dict(self._sampled_per_app)

    def num_requests(self) -> int:
        return len(self._requests)
