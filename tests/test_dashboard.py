"""Dashboard head: Prometheus metrics export + job submission API (ref
analogs: dashboard/modules/job tests, metrics_agent Prometheus export)."""

import json
import time
import urllib.request

import pytest

import ray_tpu as rt
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def dash_cluster():
    cluster = Cluster(head_resources={"CPU": 4.0}, dashboard_port=0)
    cluster.connect()
    assert cluster.dashboard_port and cluster.dashboard_port > 0
    try:
        yield cluster
    finally:
        cluster.shutdown()


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as r:
        return r.read().decode()


def test_metrics_prometheus_export(dash_cluster):
    from ray_tpu.util.metrics import Counter, Gauge

    c = Counter("test_requests_total", tag_keys=("route",))
    c.inc(3.0, tags={"route": "a"})
    c.inc(2.0, tags={"route": "a"})
    g = Gauge("test_queue_depth")
    g.set(7.0)
    time.sleep(0.5)  # async publish to GCS

    body = _get(dash_cluster.dashboard_port, "/metrics")
    assert "# TYPE test_requests_total counter" in body
    assert 'test_requests_total{route="a"} 5.0' in body
    assert "test_queue_depth 7.0" in body


def test_state_endpoints(dash_cluster):
    """State endpoints + the new /api/events (filtered cluster event
    log) and /api/cluster (enriched status: node table with heartbeat
    age + pending leases, per-shape pending demand, scheduling rollup,
    recent WARNING+ events — the Cluster tab feed). One cluster boot
    serves all of them."""
    @rt.remote(num_cpus=0)
    class Marker:
        def ping(self):
            return 1

    m = Marker.remote()
    rt.get(m.ping.remote(), timeout=30)

    nodes = json.loads(_get(dash_cluster.dashboard_port, "/api/nodes"))
    assert any(n["alive"] for n in nodes)
    actors = json.loads(_get(dash_cluster.dashboard_port, "/api/actors"))
    assert any(a["class_name"] == "Marker" for a in actors)
    status = json.loads(
        _get(dash_cluster.dashboard_port, "/api/cluster_status"))
    assert status["num_nodes"] >= 1

    @rt.remote
    def ping(x):
        return x

    assert rt.get([ping.remote(i) for i in range(4)]) == [0, 1, 2, 3]
    port = dash_cluster.dashboard_port

    deadline = time.monotonic() + 30
    events = []
    while time.monotonic() < deadline:
        out = json.loads(_get(port, "/api/events?limit=0"))
        events = out["events"]
        if any(e["kind"] == "worker_started" for e in events):
            break
        time.sleep(0.3)
    kinds = {e["kind"] for e in events}
    assert "node_registered" in kinds
    assert "worker_started" in kinds
    assert all({"ts", "severity", "source", "kind", "message"}
               <= set(e) for e in events)
    # severity filter is a minimum: INFO events drop out at WARNING
    warn = json.loads(_get(port, "/api/events?severity=WARNING&limit=0"))
    assert all(e["severity"] in ("WARNING", "ERROR")
               for e in warn["events"])
    # source + kind filters hit AND miss
    src = json.loads(_get(port, "/api/events?source=gcs&limit=0"))
    assert src["total"] >= 1
    assert all(e["source"] == "gcs" for e in src["events"])
    none = json.loads(_get(port, "/api/events?kind=no_such_kind"))
    assert none["total"] == 0

    cstat = json.loads(_get(port, "/api/cluster"))
    assert len(cstat["nodes"]) == 1
    n = cstat["nodes"][0]
    assert n["alive"] and n["heartbeat_age_s"] is not None
    assert "pending_leases" in n and "resources_available" in n
    assert "pending_demand" in cstat and "scheduling" in cstat
    assert "recent_events" in cstat
    # the decision traces flowed: granted leases for the CPU:1 shape
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        cstat = json.loads(_get(port, "/api/cluster"))
        if cstat["scheduling"].get("granted", 0) >= 1:
            break
        time.sleep(0.3)
    assert cstat["scheduling"]["granted"] >= 1


def test_job_submission_lifecycle(dash_cluster, tmp_path):
    script = tmp_path / "job_script.py"
    script.write_text(
        "import os\n"
        "import ray_tpu as rt\n"
        "rt.init(address=os.environ['RAYT_ADDRESS'])\n"
        "@rt.remote\n"
        "def f(x):\n"
        "    return x * 2\n"
        "print('job result:', rt.get(f.remote(21)))\n"
        "rt.shutdown()\n")
    port = dash_cluster.dashboard_port
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/jobs",
        data=json.dumps(
            {"entrypoint": f"python {script}",
             "env": {"PYTHONPATH": "/root/repo"}}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        sub_id = json.loads(r.read())["submission_id"]

    deadline = time.monotonic() + 90
    status = None
    while time.monotonic() < deadline:
        status = json.loads(_get(port, f"/api/jobs/{sub_id}"))
        if status["status"] in ("SUCCEEDED", "FAILED"):
            break
        time.sleep(0.5)
    logs = _get(port, f"/api/jobs/{sub_id}/logs")
    assert status["status"] == "SUCCEEDED", (status, logs)
    assert "job result: 42" in logs


def _post(port, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read().decode())


def test_job_submit_with_runtime_env(dash_cluster, tmp_path):
    """Submitted jobs run through the runtime-env machinery (VERDICT r3
    #10): working_dir becomes the driver cwd + import root, env_vars
    apply, and logs stream incrementally via the offset endpoint."""
    wd = tmp_path / "jobwd"
    wd.mkdir()
    (wd / "jobmod.py").write_text("MAGIC = 'wd-import-ok'\n")
    port = dash_cluster.dashboard_port
    out = _post(port, "/api/jobs", {
        "entrypoint": ("python -c \"import os, jobmod; "
                       "print(jobmod.MAGIC, os.environ['JOBVAR'], "
                       "os.path.basename(os.getcwd()))\""),
        "runtime_env": {"working_dir": str(wd),
                        "env_vars": {"JOBVAR": "v-42"}},
    })
    sub_id = out["submission_id"]
    deadline = time.monotonic() + 60
    status = None
    while time.monotonic() < deadline:
        status = json.loads(_get(port, f"/api/jobs/{sub_id}"))
        if status["status"] in ("SUCCEEDED", "FAILED"):
            break
        time.sleep(0.3)
    logs = _get(port, f"/api/jobs/{sub_id}/logs")
    assert status["status"] == "SUCCEEDED", logs
    assert "wd-import-ok v-42 jobwd" in logs
    # incremental tail endpoint (follow-mode streaming)
    tail = json.loads(_get(port, f"/api/jobs/{sub_id}/logs?offset=0"))
    assert "wd-import-ok" in tail["data"]
    assert tail["offset"] > 0 and tail["running"] is False
    rest = json.loads(_get(port,
                           f"/api/jobs/{sub_id}/logs?offset={tail['offset']}"))
    assert rest["data"] == ""


def test_index_page_serves_spa(dash_cluster):
    """`/` serves the operator SPA (ref: dashboard web client): one
    static page with a rendered view for EVERY JSON endpoint the head
    exposes — cluster tables, serve overview, metrics charts, job log
    tail, timeline."""
    html = _get(dash_cluster.dashboard_port, "/")
    assert html.lstrip().startswith("<!DOCTYPE html>")
    for endpoint in ("/api/nodes", "/api/actors", "/api/jobs",
                     "/api/serve", "/api/data", "/api/cluster_status",
                     "/api/cluster", "/api/events",
                     "/api/tasks", "/api/tasks/summary",
                     "/api/objects", "/api/objects/summary",
                     "/api/dags", "/api/train",
                     "/api/metrics/names", "/api/metrics/query",
                     "/api/timeline", "/metrics"):
        assert endpoint in html, endpoint
    # the SPA's interactive pieces: tab views, sparkline canvas charts,
    # incremental log tailing, task failure drill-down, object rollups,
    # DAG edge tables with occupancy/throughput sparklines, the
    # Cluster tab's event stream + pending-demand table + per-node
    # heartbeat sparklines
    for marker in ("view-metrics", "view-serve", "view-timeline",
                   "view-tasks", "task-summary", "task-err",
                   "view-objects", "object-summary", "view-data",
                   "data-exchanges", "view-dags", "dag-list",
                   "dag-edges", "view-train", "train-runs",
                   "train-steps", "sparkline", "offset=",
                   "cluster-events", "pending-demand", "event-warn",
                   "rayt_node_heartbeat_gap_s"):
        assert marker in html, marker
    # one <script> block = one top-level scope: a duplicate const/let/
    # function declaration is a parse-time SyntaxError that kills the
    # WHOLE dashboard (no handler ever runs), and no JS engine runs in
    # CI to catch it — so guard at the text level
    import collections
    import re

    script = html.split("<script>")[1].split("</script>")[0]
    decls = re.findall(r"^(?:const|let|function)\s+([A-Za-z_$][\w$]*)",
                       script, flags=re.M)
    dupes = [n for n, c in collections.Counter(decls).items() if c > 1]
    assert not dupes, f"duplicate top-level JS declarations: {dupes}"


def test_objects_endpoint_and_summary(dash_cluster):
    """/api/objects serves coalesced object-plane records (size,
    callsite, refs, pins) and /api/objects/summary the per-callsite /
    per-node rollups — the Objects tab feed."""
    import numpy as np

    ref = rt.put(np.zeros(300_000, np.uint8))
    port = dash_cluster.dashboard_port
    deadline = time.monotonic() + 30
    rec = None
    while time.monotonic() < deadline:
        out = json.loads(_get(port, "/api/objects?limit=50"))
        rec = next((o for o in out["objects"]
                    if o["object_id"] == ref.id.hex()), None)
        if rec is not None and rec.get("refs"):
            break
        time.sleep(0.3)
    assert rec is not None, "put object never reached /api/objects"
    assert rec["size"] >= 300_000
    assert "test_dashboard.py:" in rec["callsite"]
    assert rec["refs"]["local"] >= 1
    summary = json.loads(_get(port, "/api/objects/summary"))
    assert summary["totals"]["objects"] >= 1
    assert any("test_dashboard.py:" in site
               for site in summary["by_callsite"])
    assert summary["by_node"]  # node entry with store stats attached
    # filters run server-side: the matching record comes back, and a
    # non-matching callsite returns nothing
    filtered = json.loads(_get(
        port, "/api/objects?callsite=" + rec["callsite"].replace(
            "/", "%2F").replace(":", "%3A")))
    assert any(o["object_id"] == rec["object_id"]
               for o in filtered["objects"])
    assert all(o["callsite"] == rec["callsite"]
               for o in filtered["objects"])
    miss = json.loads(_get(port, "/api/objects?callsite=no%2Fsuch.py%3A1"))
    assert miss["objects"] == [] and miss["total"] == 0
    del ref


@pytest.fixture
def dag_dash_cluster(monkeypatch):
    """Dashboard cluster with a fast DAG report cadence + short stall
    grace (the head inherits the driver's config via RAYT_CONFIG_JSON)."""
    monkeypatch.setenv("RAYT_DAG_STALL_GRACE_S", "1.0")
    monkeypatch.setenv("RAYT_DAG_STATE_REPORT_INTERVAL_S", "0.25")
    from ray_tpu._internal import config as cfg_mod

    old = cfg_mod._config
    cfg_mod.set_config(cfg_mod.load_config())
    cluster = Cluster(head_resources={"CPU": 4.0}, dashboard_port=0)
    cluster.connect()
    try:
        yield cluster
    finally:
        cluster.shutdown()
        cfg_mod._config = old


def test_dags_endpoint_and_stall_badge(dag_dash_cluster):
    """/api/dags serves compiled-DAG records (edge topology + per-edge
    rollups + history) with a summary attached — and after an actor is
    killed mid-DAG, the SAME surface names the stalled edge and dead
    peer the GCS watchdog attributed (the DAGs tab badge feed)."""
    from ray_tpu.dag import InputNode

    @rt.remote(num_cpus=0)
    class DashRunner:
        def produce(self, x):
            return x * 2

    @rt.remote(num_cpus=0)
    class DashSink:
        def consume(self, x):
            return x + 1

    runner, sink = DashRunner.remote(), DashSink.remote()
    with InputNode() as inp:
        out = sink.consume.bind(runner.produce.bind(inp))
    dag = out.experimental_compile(channels=True)
    for i in range(5):
        assert dag.execute(i).get(timeout=60) == 2 * i + 1

    port = dag_dash_cluster.dashboard_port
    deadline = time.monotonic() + 30
    rec = None
    while time.monotonic() < deadline:
        body = json.loads(_get(port, "/api/dags?limit=10"))
        rec = next((d for d in body["dags"]
                    if d["dag_id"] == dag.dag_id), None)
        if rec is not None and rec["ticks"] >= 5:
            break
        time.sleep(0.3)
    assert rec is not None and rec["state"] == "RUNNING"
    assert rec["num_edges"] == 3
    edge = next(e for e in rec["edges"] if e["role"] == "edge")
    assert edge["producer"]["label"].startswith("DashRunner:")
    assert edge["history"], "sparkline history never populated"
    assert body["summary"]["totals"]["dags"] >= 1

    # kill the producer: /api/dags surfaces the watchdog's attribution
    runner_hex = runner._actor_id.hex()
    rt.kill(runner)
    stalled = None
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        body = json.loads(_get(port, "/api/dags?stalled=1"))
        for d in body["dags"]:
            for e in d["edges"]:
                s = e.get("stall")
                if s and s.get("dead_peer") == runner_hex:
                    stalled = (d, e, s)
        if stalled:
            break
        time.sleep(0.3)
    assert stalled is not None, "stall never surfaced on /api/dags"
    d, e, s = stalled
    assert s["blocked"] == "read"
    assert s["culprit"].startswith("DashRunner:")
    assert e["edge"] in d["stalled_edges"]
    assert body["summary"]["totals"]["stalled_edges"] >= 1

    dag.teardown()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        body = json.loads(_get(port, "/api/dags?limit=10"))
        rec = next((x for x in body["dags"]
                    if x["dag_id"] == dag.dag_id), None)
        if rec and rec["state"] == "TORN_DOWN" \
                and not rec["stalled_edges"]:
            break
        time.sleep(0.3)
    assert rec and rec["state"] == "TORN_DOWN"
    assert rec["stalled_edges"] == []
    rt.kill(sink)


def test_tasks_endpoint_and_summary(dash_cluster):
    """/api/tasks serves filtered task lifecycle records (job + state)
    and /api/tasks/summary the per-name state counts + latency split —
    the dashboard feed for the Tasks tab."""
    @rt.remote
    def dash_ok(x):
        return x

    @rt.remote(max_retries=0)
    def dash_fail():
        raise RuntimeError("dashboard drill-down error")

    assert rt.get([dash_ok.remote(i) for i in range(3)],
                  timeout=60) == [0, 1, 2]
    with pytest.raises(Exception):
        rt.get(dash_fail.remote(), timeout=60)

    port = dash_cluster.dashboard_port
    deadline = time.monotonic() + 30
    out = {}
    while time.monotonic() < deadline:
        out = json.loads(_get(port, "/api/tasks?limit=50"))
        states = {t["name"]: t["state"] for t in out["tasks"]}
        ok_done = sum(1 for t in out["tasks"]
                      if t["name"] == "dash_ok"
                      and t["state"] == "FINISHED")
        # wait for ALL terminal events, not just the first: the three
        # dash_ok tasks may run on different workers whose event
        # buffers flush on independent 1s timers
        if states.get("dash_fail") == "FAILED" and ok_done == 3:
            break
        time.sleep(0.3)
    by_name = {t["name"]: t for t in out["tasks"]}
    assert by_name["dash_ok"]["state"] == "FINISHED"
    failed = by_name["dash_fail"]
    assert failed["state"] == "FAILED"
    # failure drill-down payload: type + message + truncated traceback
    assert failed["error"]["type"] == "RuntimeError"
    assert "dashboard drill-down error" in failed["error"]["message"]

    # server-side state filter
    out = json.loads(_get(port, "/api/tasks?state=FAILED"))
    assert {t["name"] for t in out["tasks"]} == {"dash_fail"}
    # server-side job filter: the real job id matches, a bogus one is empty
    job = failed["job_id"]
    out = json.loads(_get(port, f"/api/tasks?job={job}&state=FAILED"))
    assert out["total"] == 1
    assert json.loads(_get(port, "/api/tasks?job=nope"))["total"] == 0

    summary = json.loads(_get(port, "/api/tasks/summary"))
    e = summary["by_name"]["dash_ok"]
    assert e["count"] == 3 and e["states"] == {"FINISHED": 3}
    assert e["sched_delay_mean_s"] is not None
    assert e["exec_time_mean_s"] is not None
    assert summary["by_name"]["dash_fail"]["failed"] == 1
    assert json.loads(
        _get(port, "/api/tasks/summary?job=nope"))["by_name"] == {}

    # timeline renders the lifecycle store with nested phase slices
    evs = json.loads(_get(port, f"/api/timeline?job={job}"))["traceEvents"]
    assert any(e["name"] == "dash_ok" for e in evs)
    assert any("[execution]" in e["name"] for e in evs)


def _query(port, name, **params):
    qs = "&".join([f"name={name}"] +
                  [f"{k}={v}" for k, v in params.items()])
    return json.loads(_get(port, f"/api/metrics/query?{qs}"))


def _wait_for_metrics(port, wanted, timeout=30.0):
    deadline = time.monotonic() + timeout
    names: list = []
    while time.monotonic() < deadline:
        names = [n["name"]
                 for n in json.loads(_get(port, "/api/metrics/names"))]
        if all(w in names for w in wanted):
            return names
        time.sleep(0.3)
    raise AssertionError(f"metrics {wanted} never appeared; saw {names}")


def test_metrics_timeseries_pipeline(dash_cluster, tmp_path):
    """End-to-end acceptance: emit → GCS channel → time-series store →
    /api/metrics/query, with correct counter→rate math, at least one
    built-in core metric and one train metric after a smoke workload."""
    from ray_tpu.train.session import TrainContext, set_context
    from ray_tpu.util.metrics import Counter

    # smoke workload: the built-in core instrumentation fires
    @rt.remote
    def f(x):
        return x + 1

    assert rt.get([f.remote(i) for i in range(20)],
                  timeout=60) == list(range(1, 21))

    # user counter with known increments for exact rate verification
    c = Counter("pipeline_test_total")
    for _ in range(5):
        c.inc(2.0)

    # train metrics via the real session.report path
    ctx = TrainContext(rank=0, world_size=1,
                       experiment_path=str(tmp_path),
                       experiment_name="exp", latest_checkpoint=None)
    set_context(ctx)
    try:
        ctx.report({"loss": 1.0, "tokens": 512, "mfu": 0.33})
        time.sleep(0.2)
        ctx.report({"loss": 0.9, "tokens": 512, "mfu": 0.35})
    finally:
        set_context(None)
        ctx.drain_results()

    port = dash_cluster.dashboard_port
    names = _wait_for_metrics(port, [
        "pipeline_test_total", "rayt_tasks_submitted_total",
        "rayt_task_sched_latency_s", "rayt_train_tokens_per_s",
        "rayt_train_mfu"])
    # node gauges ride the node manager heartbeat
    assert any(n.startswith("rayt_node_resource") for n in names)

    # exact counter→rate math: sum(rate * step) == total increments
    out = _query(port, "pipeline_test_total", window=600, step=60)
    assert out["kind"] == "counter" and out["agg"] == "rate"
    total = sum(v * out["step_s"] for s in out["series"]
                for _, v in s["points"] if v is not None)
    assert abs(total - 10.0) < 1e-6, out

    # built-in core metric: non-empty submission counter + scheduling
    # latency histogram with observations
    out = _query(port, "rayt_tasks_submitted_total", window=600,
                 step=60)
    subs = sum(v * out["step_s"] for s in out["series"]
               for _, v in s["points"] if v is not None)
    assert subs >= 20.0, out
    out = _query(port, "rayt_task_sched_latency_s", window=600,
                 step=60, agg="count", merge=1)
    obs = sum(v * out["step_s"] for s in out["series"]
              for _, v in s["points"] if v is not None)
    assert obs >= 20.0, out
    # percentile agg renders a plausible latency
    out = _query(port, "rayt_task_sched_latency_s", window=600,
                 step=60, agg="p50", merge=1)
    p50s = [v for s in out["series"] for _, v in s["points"]
            if v is not None]
    assert p50s and all(0.0 <= v <= 60.0 for v in p50s)

    # train metrics: tokens/sec computed from tokens/dt, MFU passthrough
    out = _query(port, "rayt_train_tokens_per_s", window=600, step=60)
    tps = [v for s in out["series"] for _, v in s["points"]
           if v is not None]
    assert tps and tps[-1] > 0, out
    out = _query(port, "rayt_train_mfu", window=600, step=60)
    mfus = [v for s in out["series"] for _, v in s["points"]
            if v is not None]
    assert mfus and abs(mfus[-1] - 0.35) < 1e-6, out

    # tag filtering narrows to one series
    out = json.loads(_get(
        port, "/api/metrics/query?name=rayt_train_metric&tag.key=loss"))
    assert len(out["series"]) == 1
    assert out["series"][0]["tags"].get("key") == "loss"

    # /metrics Prometheus scrape now carries the aggregated series,
    # histogram buckets included
    prom = _get(port, "/metrics")
    assert "pipeline_test_total 10.0" in prom
    assert "# TYPE rayt_task_sched_latency_s histogram" in prom
    assert 'rayt_task_sched_latency_s_bucket{le="+Inf"}' in prom

    # bad queries are 400s, not 500s
    import urllib.error
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(port, "/api/metrics/query")
    assert ei.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(port, "/api/metrics/query?name=pipeline_test_total&agg=p99")
    assert ei.value.code == 400


def test_serve_view_and_timeline_endpoints(dash_cluster):
    """/api/serve summarizes deployment QPS/latency from the metrics
    pipeline; /api/timeline exposes the task-event ring as a Chrome
    trace."""
    from ray_tpu.util import builtin_metrics as bm

    @rt.remote
    def g():
        return 1

    assert rt.get([g.remote() for _ in range(4)], timeout=60) == [1] * 4

    # serve replica telemetry (emitted here exactly as a ReplicaActor
    # would — same metric objects, same tags)
    tags = {"app": "demo", "deployment": "echo"}
    for _ in range(3):
        bm.serve_requests.inc(tags=tags)
        bm.serve_request_latency.observe(0.02, tags=tags)

    port = dash_cluster.dashboard_port
    _wait_for_metrics(port, ["rayt_serve_requests_total"])
    serve = json.loads(_get(port, "/api/serve"))
    deps = {(d["app"], d["deployment"]): d for d in serve["deployments"]}
    row = deps[("demo", "echo")]
    assert row["requests_total"] == 3.0
    assert row["latency_p50_s"] is None or row["latency_p50_s"] <= 0.05
    assert "replicas_alive" in serve

    # timeline: the task-event flush loop ships within ~1s
    deadline = time.monotonic() + 30
    events = []
    while time.monotonic() < deadline:
        events = json.loads(_get(port, "/api/timeline"))["traceEvents"]
        if any(e["name"] == "g" for e in events):
            break
        time.sleep(0.3)
    assert any(e["name"] == "g" for e in events)
    assert all("ts" in e and "dur" in e and e["ph"] == "X"
               for e in events)
    # cheap count-only form (what the SPA polls)
    count = json.loads(_get(port, "/api/timeline?count=1"))
    assert count["events"] >= len(events)


def test_train_endpoint_runs_steps_and_summary(dash_cluster):
    """/api/train (the SPA Train tab feed): filtered train-run records
    with per-worker rollups, recent step waterfalls, and the per-run
    summary — fed by the GCS train manager off the train_state
    channel. Bad query params are 400s, not 500s."""
    from ray_tpu.core.gcs_train_manager import CH_TRAIN, TRAIN_STAGES
    from ray_tpu.core.object_ref import get_core_worker

    cw = get_core_worker()
    recs = [{"kind": "run", "run_id": "t" * 32, "experiment": "dash",
             "job_id": "", "world_size": 1, "state": "RUNNING",
             "ts": time.time()}]
    for i in range(3):
        recs.append({
            "kind": "step", "run_id": "t" * 32, "experiment": "dash",
            "rank": 0, "step": i, "wall_s": 0.010 * (i + 1),
            "stages": {"data_wait_s": 0.002, "h2d_s": 0.001,
                       "step_s": 0.006 * (i + 1), "ckpt_block_s": 0.0},
            "loss": 1.0 - 0.1 * i, "ts": time.time()})
    recs.append({"kind": "compile", "run_id": "t" * 32, "rank": 0,
                 "fn": "f", "event": "compile", "compile_s": 0.2,
                 "shape": "(f32[8])", "prev_shape": "",
                 "ts": time.time()})
    cw.io.run(cw.gcs.publish(CH_TRAIN, recs))

    port = dash_cluster.dashboard_port
    deadline = time.monotonic() + 30
    out = {}
    while time.monotonic() < deadline:
        out = json.loads(_get(port, "/api/train?slow=1"))
        if any(r["run_id"] == "t" * 32 for r in out.get("runs", ())):
            break
        time.sleep(0.3)
    run = next(r for r in out["runs"] if r["run_id"] == "t" * 32)
    assert run["experiment"] == "dash" and run["state"] == "RUNNING"
    assert run["compile_count"] == 1
    # workers key by rank; history carries the sparkline waterfall
    w = run["workers"]["0"] if "0" in run["workers"] \
        else run["workers"][0]
    assert w["steps_total"] == 3
    # steps ride along, slowest first under ?slow=1
    walls = [s["wall_s"] for s in out["steps"]
             if s["run_id"] == "t" * 32]
    assert walls == sorted(walls, reverse=True) and len(walls) == 3
    assert all(set(s["stages"]) == set(TRAIN_STAGES)
               for s in out["steps"])
    # summary rollup attached
    e = out["summary"]["runs"]["t" * 32]
    assert e["steps"] == 3 and e["wall"]["n"] == 3
    assert e["stages"]["step_s"]["p50"] is not None
    # run filter narrows the steps; bad limit is a 400
    narrowed = json.loads(_get(port, f"/api/train?run={'t' * 8}"))
    assert len(narrowed["steps"]) == 3
    import urllib.error
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(port, "/api/train?limit=bogus")
    assert ei.value.code == 400


def test_data_endpoint_reports_exchange_counters(dash_cluster):
    """/api/data (the SPA Data tab feed): per-op exchange totals from
    the rayt_data_exchange_* counters land in the metrics store and
    surface with bytes/partitions/reduce-wait fields."""
    import numpy as np

    from ray_tpu.data.block import NumpyBlock
    from ray_tpu.data.executor import StreamingExecutor

    execu = StreamingExecutor()
    refs = [rt.put(NumpyBlock({"x": np.arange(5000)})) for _ in range(3)]
    out = execu.random_shuffle(refs, seed=2)
    rt.wait(out, num_returns=len(out), timeout=60)

    port = dash_cluster.dashboard_port
    deadline = time.monotonic() + 30
    ops = {}
    while time.monotonic() < deadline:
        data = json.loads(_get(port, "/api/data"))
        ops = {x["op"]: x for x in data["exchanges"]}
        if "shuffle" in ops:
            break
        time.sleep(0.3)  # batched publish flushes on a ~200ms cadence
    assert "shuffle" in ops, data
    row = ops["shuffle"]
    assert row["partitions_total"] == 3.0
    assert row.get("bytes_total", 0) > 0
    assert "ingest_tokens_per_s" in data
