"""Tune tests: search-space expansion, ASHA early stopping, PBT exploit,
fit/restore (ref analogs: python/ray/tune/tests/)."""

import os

import pytest

from ray_tpu.tune.search import BasicVariantGenerator, choice, grid_search, \
    loguniform, uniform


def test_variant_expansion():
    space = {
        "lr": {"grid_search": [0.1, 0.01]},
        "wd": uniform(0.0, 1.0),
        "opt": choice(["adam", "sgd"]),
        "nested": {"depth": grid_search([2, 4])},
    }
    variants = BasicVariantGenerator(space, num_samples=2, seed=0).variants()
    assert len(variants) == 2 * 2 * 2  # grid(2) x grid(2) x samples(2)
    assert {v["lr"] for v in variants} == {0.1, 0.01}
    assert {v["nested"]["depth"] for v in variants} == {2, 4}
    assert all(0.0 <= v["wd"] <= 1.0 for v in variants)
    assert all(v["opt"] in ("adam", "sgd") for v in variants)


def test_loguniform_range():
    vs = [loguniform(1e-4, 1e-1).sample(__import__("random").Random(i))
          for i in range(50)]
    assert all(1e-4 <= v <= 1e-1 for v in vs)


def _trainable(config):
    """Converges at a rate set by `lr`; reports loss each iteration."""
    import tempfile

    from ray_tpu import tune
    from ray_tpu.train.checkpoint import Checkpoint, save_pytree

    x = 10.0
    start = 0
    ckpt = tune.get_checkpoint()
    if ckpt is not None:
        from ray_tpu.train.checkpoint import load_pytree

        restored = load_pytree(ckpt.subdir("rank_0").path)
        x = float(restored["x"])
        start = int(restored["it"]) + 1
    import time

    for it in range(start, config.get("iters", 6)):
        time.sleep(config.get("sleep", 0.0))  # let the controller interleave
        x = x * (1.0 - config["lr"])
        with tempfile.TemporaryDirectory() as d:
            save_pytree({"x": x, "it": it}, d)
            tune.report({"loss": abs(x), "it": it},
                        checkpoint=Checkpoint(d))


def test_tuner_grid_fit(local_cluster, tmp_path):
    from ray_tpu import tune
    from ray_tpu.train.config import RunConfig

    tuner = tune.Tuner(
        _trainable,
        param_space={"lr": tune.grid_search([0.1, 0.5, 0.9]), "iters": 4},
        tune_config=tune.TuneConfig(metric="loss", mode="min"),
        run_config=RunConfig(name="grid", storage_path=str(tmp_path)))
    grid = tuner.fit()
    assert len(grid) == 3
    assert grid.num_errors == 0
    best = grid.get_best_result()
    assert best.config["lr"] == 0.9
    assert best.checkpoint is not None
    # state file persisted for restore
    assert os.path.exists(str(tmp_path / "grid" / "tuner_state.json"))


def test_tuner_asha_stops_bad_trials(local_cluster, tmp_path):
    from ray_tpu import tune
    from ray_tpu.train.config import RunConfig

    # good trials run first (wave 1) so their rung records deterministically
    # stop the bad trials in wave 2 at the first rung
    tuner = tune.Tuner(
        _trainable,
        param_space={"lr": tune.grid_search([0.9, 0.8, 0.02, 0.01]),
                     "iters": 12},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", max_concurrent_trials=2,
            scheduler=tune.ASHAScheduler(
                metric="loss", mode="min", time_attr="training_iteration",
                grace_period=2, reduction_factor=2, max_t=12)),
        run_config=RunConfig(name="asha", storage_path=str(tmp_path)))
    grid = tuner.fit()
    best = grid.get_best_result()
    assert best.config["lr"] in (0.8, 0.9)
    by_lr = {t.config["lr"]: t.iteration for t in grid._trials}
    assert by_lr[0.01] < 12 and by_lr[0.02] < 12  # stopped early
    assert by_lr[0.9] == 12  # survivors ran to completion


def test_tuner_restore(local_cluster, tmp_path):
    from ray_tpu import tune
    from ray_tpu.train.config import RunConfig
    from ray_tpu.tune.trial import TrialStatus

    tuner = tune.Tuner(
        _trainable,
        param_space={"lr": tune.grid_search([0.3, 0.6]), "iters": 3},
        tune_config=tune.TuneConfig(metric="loss", mode="min"),
        run_config=RunConfig(name="resume", storage_path=str(tmp_path)))
    grid = tuner.fit()
    assert grid.num_terminated == 2

    restored = tune.Tuner.restore(str(tmp_path / "resume"), _trainable)
    grid2 = restored.fit()  # everything terminated: no re-run needed
    assert grid2.num_terminated == 2
    assert grid2.get_best_result("loss", "min").config["lr"] == 0.6


def test_pbt_exploits(local_cluster, tmp_path):
    from ray_tpu import tune
    from ray_tpu.train.config import RunConfig

    scheduler = tune.PopulationBasedTraining(
        metric="loss", mode="min", perturbation_interval=3,
        hyperparam_mutations={"lr": [0.5, 0.7, 0.9]}, seed=0,
        quantile_fraction=0.34)
    tuner = tune.Tuner(
        _trainable,
        param_space={"lr": tune.grid_search([0.01, 0.5, 0.9]), "iters": 9,
                     "sleep": 0.08},
        tune_config=tune.TuneConfig(metric="loss", mode="min",
                                    scheduler=scheduler),
        run_config=RunConfig(name="pbt", storage_path=str(tmp_path)))
    grid = tuner.fit()
    assert grid.num_errors == 0
    # without exploitation the lr=0.01 trial ends at loss ~9.1; PBT must
    # have cloned it onto a good trial's checkpoint + mutated lr
    final_losses = [t.metric("loss") for t in grid._trials]
    assert max(final_losses) < 5.0, final_losses


def test_multi_worker_trials(local_cluster, tmp_path):
    """A ScalingConfig makes each trial a 2-worker training run inside a
    placement group (VERDICT r2 weak #9; ref analog:
    tune/execution/placement_groups.py trial resources)."""
    from ray_tpu import train, tune
    from ray_tpu.train.config import RunConfig, ScalingConfig

    def trainable(config):
        ctx = train.get_context()
        train.report({"score": config["x"] * 10 + ctx.get_world_size(),
                      "world": ctx.get_world_size(),
                      "rank": ctx.get_world_rank()})

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="mw", storage_path=str(tmp_path)),
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1}))
    grid = tuner.fit()
    best = grid.get_best_result()
    assert best.metrics["world"] == 2     # trials really ran world_size=2
    assert best.metrics["rank"] == 0      # rank-0 reports drive tune
    assert best.metrics["score"] == 22
    assert len([r for r in grid]) == 2


def test_tpe_searcher_beats_random_on_quadratic(local_cluster, tmp_path):
    """Native TPE (ref analog: tune/search/hyperopt, optuna TPESampler):
    sequential suggestions concentrate near the optimum."""
    from ray_tpu import tune

    def objective(config):
        from ray_tpu import train

        x = config["x"]
        train.report({"loss": (x - 0.7) ** 2})

    space = {"x": tune.uniform(0.0, 10.0)}
    searcher = tune.TPESearcher(space, metric="loss", mode="min",
                                n_startup_trials=6, seed=0)
    tuner = tune.Tuner(
        objective, param_space=space,
        tune_config=tune.TuneConfig(metric="loss", mode="min",
                                    num_samples=24, search_alg=searcher,
                                    max_concurrent_trials=1),
        run_config=__import__(
            "ray_tpu.train.config", fromlist=["RunConfig"]).RunConfig(
                name="tpe", storage_path=str(tmp_path)))
    grid = tuner.fit()
    best = grid.get_best_result()
    assert best.metrics["loss"] < 0.5, best.metrics
    # adaptivity check: the post-startup suggestions cluster toward the
    # optimum vs the uniform startup phase
    xs = [t.config["x"] for t in grid._trials]
    startup, guided = xs[:6], xs[6:]
    import statistics

    assert (statistics.median([abs(x - 0.7) for x in guided])
            < statistics.median([abs(x - 0.7) for x in startup]))


# -------------------------------------------------- BOHB + searcher state (r5)
def test_bohb_models_highest_informative_budget():
    """BOHB picks its TPE observations from the highest budget with
    enough points; intermediate results feed the model before any trial
    completes (ref: TuneBOHB + HyperBand pairing)."""
    from ray_tpu import tune
    from ray_tpu.tune import BOHBSearcher

    space = {"lr": tune.uniform(0.0, 1.0)}
    s = BOHBSearcher(space, metric="score", mode="max",
                     min_points_per_budget=3, n_startup_trials=50,
                     seed=0)
    # 3 intermediate results at budget 1, 3 at budget 2: good lr is high
    for i, lr in enumerate((0.1, 0.5, 0.9)):
        tid = f"t{i}"
        s._pending[tid] = {("lr",): lr}
        s.on_trial_result(tid, {"score": lr, "training_iteration": 1})
        s.on_trial_result(tid, {"score": lr * 2, "training_iteration": 2})
    assert s._has_model()  # warmed from partial evaluations alone
    assert s._model_obs() == s._budget_obs[2.0]
    cfgs = [s.suggest(f"m{i}")["lr"] for i in range(12)]
    # the model leans toward the good region (high lr)
    assert sum(c > 0.5 for c in cfgs) > 6, cfgs


def test_searcher_state_roundtrip_resumes_exactly():
    """Searcher checkpoint fidelity: a restored searcher continues the
    exact suggestion stream of the original (same RNG, same model)."""
    import cloudpickle

    from ray_tpu import tune
    from ray_tpu.tune import TPESearcher

    space = {"x": tune.uniform(0.0, 1.0)}

    def advance(s, n, start=0):
        out = []
        for i in range(start, start + n):
            cfg = s.suggest(f"t{i}")
            s.on_trial_complete(f"t{i}", {"m": cfg["x"]})
            out.append(cfg["x"])
        return out

    a = TPESearcher(space, metric="m", mode="max", n_startup_trials=3,
                    seed=7)
    advance(a, 6)
    blob = cloudpickle.dumps(a)  # what the controller checkpoints
    b = cloudpickle.loads(blob)
    assert advance(a, 5, start=6) == advance(b, 5, start=6)


def test_tuner_restore_resumes_searcher(local_cluster, tmp_path):
    """Tuner.restore picks up the persisted searcher: the resumed run's
    suggestions are model-informed, not from-scratch random."""
    from ray_tpu import train, tune
    from ray_tpu.tune import TPESearcher, Tuner, TuneConfig

    def trainable(config):
        train.report({"loss": (config["x"] - 0.25) ** 2})

    tc = TuneConfig(metric="loss", mode="min", num_samples=6,
                    search_alg=TPESearcher({"x": tune.uniform(0, 1)},
                                           metric="loss", mode="min",
                                           n_startup_trials=2, seed=3))
    t = Tuner(trainable, tune_config=tc,
              run_config=train.RunConfig(name="bohb_resume",
                                         storage_path=str(tmp_path)))
    t.fit()
    restored = Tuner.restore(str(tmp_path / "bohb_resume"), trainable)
    sa = restored.tune_config.search_alg
    assert sa is not None and len(sa._obs) > 0  # model state survived
