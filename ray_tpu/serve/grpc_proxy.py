"""gRPC ingress proxy (ref analog: python/ray/serve/_private/proxy.py
gRPC data plane + grpc_util/: the reference serves user-defined proto
services; this ingress exposes a generic byte-level service so callers
don't need generated stubs).

Service (full method names):
  /rayt.serve.Serve/Predict        unary-unary
  /rayt.serve.Serve/PredictStream  unary-stream

Request bytes: JSON {"app": <name>, "payload": <json value>,
"model_id": <optional>}; response bytes: JSON value per result (one per
stream message for PredictStream). Runs inside an async actor next to
the HTTP proxy, sharing the same DeploymentHandle routing path.

Mirrors the HTTP proxy's admission control and status split (see
serve/admission.py): shed / replica queue-full / timeout abort with
RESOURCE_EXHAUSTED or UNAVAILABLE (retry semantics), replica user-code
exceptions with INTERNAL.
"""

from __future__ import annotations

import json
import time
from typing import Any

from ray_tpu.serve.admission import (AdmissionWindow, count_admitted,
                                     count_shed, is_overload_error,
                                     request_timeout_s, retry_after_s)

_SERVICE = "rayt.serve.Serve"


class GrpcProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 request_timeout_s: float | None = None,
                 admission_headroom: float | None = None,
                 proxy_id: str = "grpc-0"):
        self.host = host
        self.port = port
        self.proxy_id = proxy_id
        self._handles: dict[str, Any] = {}
        self._ingress: dict[str, str] = {}
        self._server = None
        self._timeout_override = request_timeout_s
        self._admission = AdmissionWindow(admission_headroom, proxy_id)
        self._hb_thread = None

    # ------------------------------------------------------------- control
    def register_app(self, app_name: str, ingress_deployment: str) -> bool:
        self._ingress[app_name] = ingress_deployment
        self._handles.pop(app_name, None)
        return True

    def unregister_app(self, app_name: str) -> bool:
        self._ingress.pop(app_name, None)
        self._handles.pop(app_name, None)
        return True

    def admission_snapshot(self) -> dict:
        return {**self._admission.snapshot(),
                **self._admission.fleet_snapshot()}

    async def start(self) -> int:
        import grpc

        proxy = self

        class _Generic(grpc.GenericRpcHandler):
            def service(self, details):
                if details.method == f"/{_SERVICE}/Predict":
                    return grpc.unary_unary_rpc_method_handler(
                        proxy._predict)
                if details.method == f"/{_SERVICE}/PredictStream":
                    return grpc.unary_stream_rpc_method_handler(
                        proxy._predict_stream)
                return None

        self._server = grpc.server(
            __import__("concurrent.futures", fromlist=["f"])
            .ThreadPoolExecutor(max_workers=8),
            options=[("grpc.so_reuseport", 0)])
        self._server.add_generic_rpc_handlers((_Generic(),))
        self.port = self._server.add_insecure_port(
            f"{self.host}:{self.port}")
        self._server.start()
        self._start_heartbeat()
        return self.port

    def _start_heartbeat(self):
        """Daemon-thread controller heartbeat (the gRPC server runs on
        plain threads, no event loop): same fleet-membership beat as the
        HTTP proxy, so the gRPC ingress counts toward live_proxies and
        admits its share of the shared cluster window."""
        import threading

        from ray_tpu.serve.proxy import HEARTBEAT_PERIOD_S

        def _loop():
            import ray_tpu as rt
            from ray_tpu.serve.controller import CONTROLLER_NAME

            while True:
                try:
                    controller = rt.get_actor(CONTROLLER_NAME)
                    rt.get(controller.proxy_heartbeat.remote(
                        self.proxy_id, "grpc", self.port), timeout=5)
                except Exception:
                    pass  # controller bouncing: keep serving
                time.sleep(HEARTBEAT_PERIOD_S)

        self._hb_thread = threading.Thread(
            target=_loop, name="grpc-proxy-heartbeat", daemon=True)
        self._hb_thread.start()

    async def stop(self):
        if self._server is not None:
            self._server.stop(grace=1.0)

    # --------------------------------------------------------------- data
    def _resolve(self, request_bytes: bytes):
        import grpc

        req = json.loads(request_bytes)
        app_name = req.get("app")
        ingress = self._ingress.get(app_name)
        if ingress is None:
            raise _Abort(grpc.StatusCode.NOT_FOUND,
                         f"no app {app_name!r}")
        handle = self._handles.get(app_name)
        if handle is None:
            from ray_tpu.serve.handle import DeploymentHandle

            handle = DeploymentHandle(ingress, app_name)
            self._handles[app_name] = handle
        model_id = req.get("model_id") or ""
        from ray_tpu.serve.admission import queue_timeout_s
        from ray_tpu.serve.handle import derive_prefix_key

        payload = req.get("payload")
        # bound the capacity-gate park by the request timeout (shed as
        # backpressure instead of queueing into a deadline); prefix key
        # mirrors the HTTP proxy's prefix-cache-aware routing
        handle = handle.options(
            multiplexed_model_id=model_id or None,
            queue_timeout_s=min(queue_timeout_s(),
                                self._request_timeout()),
            prefix_key=derive_prefix_key(payload) or None)
        return app_name, handle, payload, model_id

    # --------------------------------------- request-path observability
    def _new_context(self, context) -> dict:
        """Mint the request id (parity with the HTTP proxy's
        X-Rayt-Request-Id: echoed to the caller as initial metadata,
        alongside x-rayt-proxy-id naming the fleet member that served
        it) and start the request context that rides the handle
        envelope."""
        from ray_tpu.serve.request_context import mint_request_id

        rid = mint_request_id()
        try:
            context.send_initial_metadata(
                (("x-rayt-request-id", rid),
                 ("x-rayt-proxy-id", self.proxy_id)))
        except Exception:
            pass
        return {"request_id": rid, "start_ts": time.time(),
                "proxy": self.proxy_id}

    @staticmethod
    def _record(ctx: dict, app_name: str, outcome: str, **kw):
        """Same record shape as the HTTP side — one assembly path, so
        `rayt list requests` / summaries treat both protos uniformly."""
        from ray_tpu.serve.proxy import ProxyActor

        ProxyActor._finish_record(ctx, app_name, outcome, proto="grpc",
                                  **kw)

    def _request_timeout(self) -> float:
        if self._timeout_override is not None:
            return float(self._timeout_override)
        return request_timeout_s()

    def _admit(self, app_name: str, handle):
        """Admission gate; raises _Abort(RESOURCE_EXHAUSTED) on shed.
        Returns once this request holds a window slot."""
        import grpc

        try:
            replicas, max_ongoing, live = handle.capacity_info()
        except Exception:
            replicas, max_ongoing, live = 1, 16, 1
        if not self._admission.try_acquire(app_name, replicas,
                                           max_ongoing, live):
            count_shed(app_name, self.proxy_id, "shed")
            raise _Abort(
                grpc.StatusCode.RESOURCE_EXHAUSTED,
                f"admission window full for app {app_name!r}; "
                f"retry after {retry_after_s()}s")
        count_admitted(app_name, self.proxy_id)

    def _abort_for(self, app_name: str, e: Exception) -> "_Abort":
        """Mirror the HTTP 503/500 split onto gRPC codes."""
        import grpc

        from ray_tpu.core.common import GetTimeoutError

        if isinstance(e, GetTimeoutError):
            count_shed(app_name, self.proxy_id, "timeout")
            return _Abort(
                grpc.StatusCode.UNAVAILABLE,
                f"request exceeded {self._request_timeout():.0f}s "
                f"(RAYT_SERVE_REQUEST_TIMEOUT_S); retry after "
                f"{retry_after_s()}s")
        if is_overload_error(e):
            count_shed(app_name, self.proxy_id, "queue_full")
            return _Abort(
                grpc.StatusCode.RESOURCE_EXHAUSTED,
                f"replicas at capacity: {e!r}; retry after "
                f"{retry_after_s()}s")
        if isinstance(e, RuntimeError) and "no replicas" in str(e):
            count_shed(app_name, self.proxy_id, "no_replicas")
            return _Abort(grpc.StatusCode.UNAVAILABLE, repr(e))
        return _Abort(grpc.StatusCode.INTERNAL, repr(e))

    def _predict(self, request_bytes: bytes, context) -> bytes:
        from ray_tpu._internal.otel import (current_context_carrier,
                                            submit_span)

        t0 = time.perf_counter()
        try:
            app_name, handle, payload, model_id = \
                self._resolve(request_bytes)
        except _Abort as e:
            context.abort(e.code, e.detail)
            return
        ctx = self._new_context(context)
        handle = handle.options(request_context=ctx)
        with submit_span("serve.proxy.request", app=app_name,
                         request_id=ctx["request_id"], proto="grpc"):
            try:
                ctx["trace"] = current_context_carrier()
            except Exception:
                pass
            try:
                self._admit(app_name, handle)
            except _Abort as e:
                self._record(ctx, app_name, "shed", t0=t0)
                context.abort(e.code, e.detail)
                return
            t1 = time.perf_counter()
            try:
                result = handle.remote(payload).result(
                    timeout=self._request_timeout())
                self._record(ctx, app_name, "ok", t0=t0, t1=t1,
                             model_id=model_id)
                return json.dumps(result, default=str).encode()
            except Exception as e:
                from ray_tpu.serve.proxy import ProxyActor

                self._record(ctx, app_name, ProxyActor._outcome_for(e),
                             t0=t0, t1=t1, model_id=model_id)
                a = self._abort_for(app_name, e)
                context.abort(a.code, a.detail)
            finally:
                self._admission.release(app_name)

    def _predict_stream(self, request_bytes: bytes, context):
        from ray_tpu._internal.otel import (current_context_carrier,
                                            submit_span)

        t0 = time.perf_counter()
        try:
            app_name, handle, payload, model_id = \
                self._resolve(request_bytes)
        except _Abort as e:
            context.abort(e.code, e.detail)
            return
        ctx = self._new_context(context)
        handle = handle.options(request_context=ctx)
        with submit_span("serve.proxy.request", app=app_name,
                         request_id=ctx["request_id"], proto="grpc"):
            try:
                ctx["trace"] = current_context_carrier()
            except Exception:
                pass
            try:
                self._admit(app_name, handle)
            except _Abort as e:
                self._record(ctx, app_name, "shed", t0=t0)
                context.abort(e.code, e.detail)
                return
            t1 = time.perf_counter()
            t_first = None
            chunks = 0
            try:
                for item in handle.options(stream=True).remote(payload):
                    if t_first is None:
                        t_first = time.perf_counter()
                    chunks += 1
                    yield json.dumps(item, default=str).encode()
                t_end = time.perf_counter()
                self._record(
                    ctx, app_name, "ok", t0=t0, t1=t1, t_first=t_first,
                    t_end=t_end, model_id=model_id,
                    ttft_s=(t_first - t0) if t_first is not None
                    else None,
                    tpot_s=((t_end - t_first) / (chunks - 1)
                            if t_first is not None and chunks > 1
                            else None),
                    chunks=chunks)
            except Exception as e:
                from ray_tpu.serve.proxy import ProxyActor

                # before the first message the caller still gets a real
                # status code; after it, this is a mid-stream abort —
                # same outcome split as the HTTP SSE path
                outcome = ("stream_aborted" if chunks
                           else ProxyActor._outcome_for(e))
                self._record(ctx, app_name, outcome, t0=t0, t1=t1,
                             t_first=t_first, model_id=model_id,
                             chunks=chunks)
                a = self._abort_for(app_name, e)
                context.abort(a.code, a.detail)
            finally:
                self._admission.release(app_name)


class _Abort(Exception):
    def __init__(self, code, detail):
        self.code = code
        self.detail = detail
