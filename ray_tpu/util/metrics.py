"""User-facing metrics API: Counter / Gauge / Histogram (ref analog:
python/ray/util/metrics.py:137,187,262).

Metrics register in a per-process registry; each record also publishes to
the GCS metrics channel (best-effort, dropped when no cluster is up) so
the state API / dashboard can aggregate cluster-wide.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, Optional, Sequence, Tuple

_registry: dict[str, "Metric"] = {}
_registry_lock = threading.Lock()

CH_METRICS = "metrics"


def _publish(name: str, kind: str, value: float, tags: dict):
    try:
        from ray_tpu.core.object_ref import get_core_worker

        cw = get_core_worker()
        if cw is None or cw.gcs is None:
            return
        cw.io.spawn(cw.gcs.publish(CH_METRICS, {
            "name": name, "kind": kind, "value": value, "tags": tags,
            "ts": time.time()}))
    except Exception:
        pass


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if not name:
            raise ValueError("metric name is required")
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry[name] = self

    @property
    def info(self) -> dict:
        return {"name": self._name, "description": self._description,
                "tag_keys": self._tag_keys}

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _merged_tags(self, tags: Optional[Dict[str, str]]) -> dict:
        out = dict(self._default_tags)
        if tags:
            out.update(tags)
        unknown = set(out) - set(self._tag_keys)
        if unknown:
            raise ValueError(f"unknown tag keys {unknown} for metric "
                             f"{self._name!r} (declared {self._tag_keys})")
        return out


class Counter(Metric):
    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        super().__init__(name, description, tag_keys)
        self._counts: Dict[Tuple, float] = {}

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None):
        if value <= 0:
            raise ValueError("Counter.inc() requires value > 0")
        merged = self._merged_tags(tags)
        key = tuple(sorted(merged.items()))
        with self._lock:
            self._counts[key] = self._counts.get(key, 0.0) + value
        _publish(self._name, "counter", value, merged)

    def get(self, tags: Optional[Dict[str, str]] = None) -> float:
        key = tuple(sorted(self._merged_tags(tags).items()))
        with self._lock:
            return self._counts.get(key, 0.0)


class Gauge(Metric):
    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        merged = self._merged_tags(tags)
        key = tuple(sorted(merged.items()))
        with self._lock:
            self._values[key] = float(value)
        _publish(self._name, "gauge", float(value), merged)

    def get(self, tags: Optional[Dict[str, str]] = None) -> float:
        key = tuple(sorted(self._merged_tags(tags).items()))
        with self._lock:
            return self._values.get(key, 0.0)


class Histogram(Metric):
    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Optional[Sequence[str]] = None):
        super().__init__(name, description, tag_keys)
        if not boundaries:
            raise ValueError("Histogram requires bucket boundaries")
        self._boundaries = sorted(float(b) for b in boundaries)
        self._buckets: Dict[Tuple, list] = {}

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        merged = self._merged_tags(tags)
        key = tuple(sorted(merged.items()))
        with self._lock:
            counts = self._buckets.setdefault(
                key, [0] * (len(self._boundaries) + 1))
            counts[bisect.bisect_left(self._boundaries, value)] += 1
        _publish(self._name, "histogram", float(value), merged)

    def buckets(self, tags: Optional[Dict[str, str]] = None) -> list:
        key = tuple(sorted(self._merged_tags(tags).items()))
        with self._lock:
            return list(self._buckets.get(
                key, [0] * (len(self._boundaries) + 1)))


def registered_metrics() -> dict[str, Metric]:
    with _registry_lock:
        return dict(_registry)
