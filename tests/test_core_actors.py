"""Actor semantics tests (model: reference python/ray/tests/test_actor*.py
— ordering, concurrency, restarts, named actors)."""

import time

import pytest

import ray_tpu as rt


@pytest.fixture(scope="module")
def cluster():
    ctx = rt.init(num_cpus=8, resources={"TPU": 8})
    yield ctx
    rt.shutdown()


@rt.remote
class Counter:
    def __init__(self, start=0):
        self.n = start

    def incr(self, by=1):
        self.n += by
        return self.n

    def value(self):
        return self.n


def test_actor_basic(cluster):
    c = Counter.remote()
    assert rt.get(c.incr.remote()) == 1
    assert rt.get(c.incr.remote(5)) == 6
    assert rt.get(c.value.remote()) == 6


def test_actor_ctor_args(cluster):
    c = Counter.remote(100)
    assert rt.get(c.value.remote()) == 100


def test_actor_call_ordering(cluster):
    c = Counter.remote()
    refs = [c.incr.remote() for _ in range(20)]
    # pipelined calls must execute in submission order
    assert rt.get(refs) == list(range(1, 21))


def test_actor_method_error(cluster):
    @rt.remote
    class Fragile:
        def ok(self):
            return "ok"

        def bad(self):
            raise RuntimeError("actor method error")

    a = Fragile.remote()
    with pytest.raises(rt.TaskError, match="actor method error"):
        rt.get(a.bad.remote())
    # actor survives a method error
    assert rt.get(a.ok.remote()) == "ok"


def test_actor_handle_passing(cluster):
    c = Counter.remote()

    @rt.remote
    def bump(counter):
        return rt.get(counter.incr.remote(10))

    assert rt.get(bump.remote(c)) == 10
    assert rt.get(c.value.remote()) == 10


def test_named_actor(cluster):
    Counter.options(name="shared_counter").remote(7)
    time.sleep(0.1)
    h = rt.get_actor("shared_counter")
    assert rt.get(h.value.remote()) == 7
    with pytest.raises(ValueError):
        rt.get_actor("no_such_actor")


def test_actor_death_raises(cluster):
    @rt.remote
    class Suicidal:
        def die(self):
            import os

            os._exit(1)

        def ping(self):
            return "pong"

    a = Suicidal.remote()
    assert rt.get(a.ping.remote()) == "pong"
    ref = a.die.remote()
    with pytest.raises((rt.ActorDiedError, rt.RayTpuError)):
        rt.get(ref, timeout=30)
    with pytest.raises((rt.ActorDiedError, rt.RayTpuError)):
        rt.get(a.ping.remote(), timeout=30)


def test_actor_restart(cluster):
    @rt.remote(max_restarts=1, max_task_retries=2)
    class Phoenix:
        def __init__(self):
            self.n = 0

        def crash_once(self):
            import os
            import tempfile

            path = f"{tempfile.gettempdir()}/rayt_phoenix"
            if not os.path.exists(path):
                open(path, "w").close()
                os._exit(1)
            os.unlink(path)
            return "reborn"

        def ping(self):
            return "pong"

    a = Phoenix.remote()
    assert rt.get(a.ping.remote()) == "pong"
    assert rt.get(a.crash_once.remote(), timeout=60) == "reborn"


def test_kill_actor(cluster):
    a = Counter.remote()
    assert rt.get(a.incr.remote()) == 1
    rt.kill(a)
    with pytest.raises((rt.ActorDiedError, rt.RayTpuError)):
        rt.get(a.incr.remote(), timeout=30)


def test_async_actor(cluster):
    @rt.remote
    class AsyncWorker:
        async def work(self, x):
            import asyncio

            await asyncio.sleep(0.05)
            return x * 2

    a = AsyncWorker.remote()
    refs = [a.work.remote(i) for i in range(8)]
    assert rt.get(refs) == [i * 2 for i in range(8)]


def test_max_concurrency_threaded(cluster):
    @rt.remote(max_concurrency=4)
    class Slow:
        def block(self, t):
            time.sleep(t)
            return "done"

    a = Slow.remote()
    rt.get(a.block.remote(0.0))  # warm up: exclude actor cold-start
    t0 = time.monotonic()
    refs = [a.block.remote(0.5) for _ in range(4)]
    rt.get(refs)
    # 4 concurrent 0.5s sleeps should take ~0.5s, far less than 2s serial
    assert time.monotonic() - t0 < 1.9


def test_actor_in_placement_group(cluster):
    pg = rt.placement_group([{"CPU": 1}], strategy="PACK")
    c = Counter.options(
        scheduling_strategy=pg.bundle_strategy(0)).remote()
    assert rt.get(c.incr.remote()) == 1
    rt.remove_placement_group(pg)
