"""GCS event manager — the cluster-wide EVENT LOG and scheduling-plane
DECISION-TRACE store (the scheduling sibling of gcs_task_manager.py /
gcs_object_manager.py / gcs_dag_manager.py; ref analogs: Ray's cluster
events / `ray status` node+demand rendering, and the raylet's
resource_demands feeding autoscaler state, arXiv:1712.05889).

Two stores, one module, because they answer the same question — *why is
work where it is* — from two directions:

* **Event log**: structured, timestamped, severity-tagged events from
  every plane (node register / heartbeat-lost / dead, worker start /
  crash / OOM-reap, actor create / restart / death with cause, job
  start/finish, GCS restart, lease spillback + infeasible verdicts,
  cluster- and serve-autoscaler decisions, DAG stall flag/clear, serve
  shed episodes), ingested from the ``cluster_events`` pubsub channel
  and from in-process GCS flows. Memory-bounded
  (``RAYT_CLUSTER_EVENTS_MAX``) with per-job oldest-first eviction +
  dropped accounting — the same contract as the task/object/DAG
  managers — and purged on job finish.

* **Scheduling decision traces**: every node manager coalesces its
  ``request_lease`` verdicts per DEMAND SHAPE (grant / spillback /
  queue / infeasible / cancelled, with reason, queue-wait time,
  spillback hop, and the candidate node views it considered) and ships
  the deltas on its heartbeat cadence together with its pending-lease
  queue depth and per-shape aggregate pending demand. This module
  merges them into cluster-wide per-shape records that feed
  ``rayt status``, ``rayt why-pending``, ``summarize_scheduling`` and
  the ``rayt_sched_*`` Prometheus family.
"""

from __future__ import annotations

import collections
import itertools
import time
from typing import Any, Optional

# pubsub channel events + sched reports ride (defined here, next to the
# consumer; gcs.py re-exports it beside its siblings)
CH_EVENTS = "cluster_events"

# severity taxonomy, rank-ordered: a severity FILTER is a minimum —
# querying WARNING returns WARNING and ERROR
SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR")
_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}

# recent per-shape decision samples kept cluster-side (queue-wait /
# spill-hop percentiles for the envelope bench + why-pending context)
_RECENT_DECISIONS = 256
# free-text payload bound: events are telemetry, not log shipping
_MSG_CAP = 500


def severity_rank(sev: str) -> int:
    return _SEV_RANK.get(sev, _SEV_RANK["INFO"])


def shape_key(demand: dict) -> str:
    """Canonical demand-shape key: sorted ``res:amount`` pairs, so
    ``{"CPU": 1.0}`` coalesces as ``CPU:1`` everywhere (node traces,
    GCS rollups, why-pending joins)."""
    if not demand:
        return "(none)"
    return ",".join(f"{k}:{demand[k]:g}" for k in sorted(demand))


def make_event(*, source: str, kind: str, message: str,
               severity: str = "INFO", job_id: str = "",
               node_id: str = "", ts: float | None = None,
               data: dict | None = None) -> dict:
    """The one wire schema for a cluster event — every emitter (GCS
    flows, node managers, autoscalers, serve, workers) builds events
    here so the log never sees divergent shapes."""
    return {
        "type": "event",
        "source": source,
        "kind": kind,
        "severity": severity if severity in _SEV_RANK else "INFO",
        "message": (message or "")[:_MSG_CAP],
        "job_id": job_id or "",
        "node_id": node_id or "",
        "ts": time.time() if ts is None else float(ts),
        "data": dict(data or {}),
    }


def emit_cluster_event(*, source: str, kind: str, message: str,
                       severity: str = "INFO", job_id: str = "",
                       node_id: str = "", **data) -> None:
    """Fire-and-forget event publish from any process with a live core
    worker (serve controller/proxies, drivers). Never raises — events
    are telemetry and must not break the emitting plane."""
    try:
        from ray_tpu._internal.config import get_config

        if not get_config().cluster_events_enabled:
            return
        from ray_tpu.core.object_ref import get_core_worker

        cw = get_core_worker()
        if cw is None or cw.gcs is None:
            return
        if not node_id:
            nid = getattr(cw, "node_id", None)
            node_id = nid.hex() if nid is not None else ""
        ev = make_event(source=source, kind=kind, message=message,
                        severity=severity, job_id=job_id,
                        node_id=node_id, data=data)
        cw._spawn_from_thread(cw.gcs.publish(CH_EVENTS, [ev]))
    except Exception:
        pass


def _new_shape_record(demand: dict) -> dict:
    return {
        "demand": dict(demand or {}),
        "granted": 0, "queued": 0, "spillback": 0,
        "infeasible": 0, "cancelled": 0,
        "queue_wait_s_total": 0.0, "queue_wait_max_s": 0.0,
        "max_spill_hops": 0,
        "last_reason": "",
        "last_candidates": None,
        "last_ts": 0.0,
        # recent decision samples: dicts with ts/node/verdict/hop/
        # queue_wait_s/reason (candidates ride only last_candidates)
        "recent": collections.deque(maxlen=_RECENT_DECISIONS),
    }


class GcsEventManager:
    def __init__(self, max_events: int = 10_000):
        self.max_events = max_events
        # event id -> record; insertion-ordered so per-job eviction
        # finds a job's oldest record cheaply via the index
        self._events: dict[int, dict] = {}
        self._seq = itertools.count(1)
        # job_hex -> insertion-ordered set of its event ids ("" bucket
        # holds cluster-scoped events with no job attribution)
        self._by_job: dict[str, dict[int, None]] = {}
        self._dropped_per_job: collections.Counter = collections.Counter()
        # ---- scheduling decision traces ----
        self._shapes: dict[str, dict] = {}
        # node hex -> {"pending": n, "pending_shapes": {...}, "ts": s}
        self._node_sched: dict[str, dict] = {}
        # node hex -> {job_hex: {res: amt}} — each node's ABSOLUTE leased
        # usage by job, shipped with its sched report; the cluster-wide
        # aggregate feeds the placement plane's quota accounting
        self._node_job_usage: dict[str, dict] = {}
        # cumulative per-job quota-throttle verdicts (deltas ingested
        # from sched reports, like the shape counters)
        self._quota_throttled: collections.Counter = collections.Counter()
        self._reports_ingested = 0
        # metric records derived from sched-report deltas, drained by
        # the GCS publish handler into the metrics store (this process
        # has no core worker — same raw-record pattern as the node
        # manager / dag manager)
        self._metric_records: list[dict] = []

    # ------------------------------------------------------------ ingest
    def ingest(self, msg: Any):
        """One pubsub payload: a single event, a batch of events, or a
        node manager's coalesced scheduling report."""
        if isinstance(msg, list):
            for m in msg:
                self.ingest(m)
            return
        if not isinstance(msg, dict):
            return
        t = msg.get("type")
        if t == "event":
            self._ingest_event(msg)
        elif t == "sched_report":
            self.ingest_sched_report(msg)

    def record(self, *, source: str, kind: str, message: str,
               severity: str = "INFO", job_id: str = "",
               node_id: str = "", data: dict | None = None):
        """In-process emission shortcut for flows the GCS itself drives
        (node/actor/job lifecycle, autoscaler) — no pubsub hop."""
        self._ingest_event(make_event(
            source=source, kind=kind, message=message, severity=severity,
            job_id=job_id, node_id=node_id, data=data))

    def _ingest_event(self, ev: dict):
        eid = next(self._seq)
        rec = {
            "id": eid,
            "ts": float(ev.get("ts", 0.0)) or time.time(),
            "severity": (ev.get("severity")
                         if ev.get("severity") in _SEV_RANK else "INFO"),
            "source": str(ev.get("source", ""))[:40],
            "kind": str(ev.get("kind", ""))[:60],
            "message": str(ev.get("message", ""))[:_MSG_CAP],
            "job_id": str(ev.get("job_id", "")),
            "node_id": str(ev.get("node_id", "")),
            "data": ev.get("data") if isinstance(ev.get("data"), dict)
            else {},
        }
        self._events[eid] = rec
        self._by_job.setdefault(rec["job_id"], {})[eid] = None
        self._maybe_evict()

    def _maybe_evict(self):
        """Per-job eviction under the global cap: the job holding the
        most events gives up its OLDEST one, with per-job dropped
        accounting (same fairness contract as GcsTaskManager — one
        event-flood job can't evict every other job's history)."""
        while len(self._events) > self.max_events:
            victim_job = max(self._by_job,
                             key=lambda j: len(self._by_job[j]))
            job_events = self._by_job[victim_job]
            eid = next(iter(job_events))
            del job_events[eid]
            if not job_events:
                del self._by_job[victim_job]
            self._events.pop(eid, None)
            self._dropped_per_job[victim_job] += 1

    def on_job_finished(self, job_hex: str):
        """The finished job's events are purged (regular freeing, not
        eviction — no dropped accounting), matching the task/object/DAG
        manager purge contract."""
        for eid in self._by_job.pop(job_hex, ()):
            self._events.pop(eid, None)
        self._quota_throttled.pop(job_hex, None)
        for usage in self._node_job_usage.values():
            usage.pop(job_hex, None)

    # ------------------------------------------------------------ queries
    def _iter_filtered(self, job_id=None, node_id=None, severity=None,
                       source=None, kind=None, start_s=None, end_s=None):
        min_rank = _SEV_RANK.get(severity) if severity else None
        if job_id is not None:
            ids: Any = self._by_job.get(job_id, ())
            rows = (self._events[e] for e in ids if e in self._events)
        else:
            rows = iter(self._events.values())
        for rec in rows:
            if node_id is not None and not rec["node_id"].startswith(
                    node_id):
                continue
            if min_rank is not None and \
                    _SEV_RANK[rec["severity"]] < min_rank:
                continue
            if source is not None and rec["source"] != source:
                continue
            if kind is not None and rec["kind"] != kind:
                continue
            if start_s is not None and rec["ts"] < start_s:
                continue
            if end_s is not None and rec["ts"] > end_s:
                continue
            yield rec

    def list(self, *, job_id: Optional[str] = None,
             node_id: Optional[str] = None,
             severity: Optional[str] = None,
             source: Optional[str] = None, kind: Optional[str] = None,
             start_s: Optional[float] = None,
             end_s: Optional[float] = None, limit: int = 100) -> dict:
        """Filtered events, newest-first, with truncation + per-job
        dropped accounting. ``severity`` is a MINIMUM (``WARNING``
        matches WARNING and ERROR); ``node_id`` matches by prefix."""
        matched = list(self._iter_filtered(job_id, node_id, severity,
                                           source, kind, start_s, end_s))
        matched.reverse()  # insertion order -> newest first
        limit = max(0, limit or 0)  # <= 0 means unlimited
        truncated = max(0, len(matched) - limit) if limit else 0
        return {
            "events": [dict(r, data=dict(r["data"]))
                       for r in (matched[:limit] if limit else matched)],
            "total": len(matched),
            "truncated": truncated,
            "dropped": self.dropped_counts(job_id),
        }

    def dropped_counts(self, job_id: Optional[str] = None) -> dict:
        if job_id is not None:
            return {job_id: self._dropped_per_job.get(job_id, 0)}
        return dict(self._dropped_per_job)

    def num_events(self) -> int:
        return len(self._events)

    # --------------------------------------------- scheduling decisions
    def ingest_sched_report(self, report: dict):
        """One node manager's heartbeat-cadence report: per-shape
        decision DELTAS since its last successful publish, plus its live
        pending-lease queue state. Derives the ``rayt_sched_*`` metric
        records as a side effect (drained by the GCS publish handler)."""
        node = str(report.get("node", ""))
        ts = float(report.get("ts", 0.0)) or time.time()
        self._reports_ingested += 1
        self._node_sched[node] = {
            "pending": int(report.get("pending", 0)),
            "pending_shapes": {
                k: {"count": int(v.get("count", 0)),
                    "demand": dict(v.get("demand", {}))}
                for k, v in (report.get("pending_shapes") or {}).items()},
            "ts": ts,
        }
        if report.get("job_usage") is not None:
            usage = {str(j): {r: float(a) for r, a in (u or {}).items()}
                     for j, u in report["job_usage"].items()}
            if usage:
                self._node_job_usage[node] = usage
            else:
                self._node_job_usage.pop(node, None)
        throttled = {str(j): max(0, int(n)) for j, n in
                     (report.get("quota_throttled") or {}).items()
                     if int(n) > 0}
        for j, n in throttled.items():
            self._quota_throttled[j] += n
        d_spill = d_infeas = 0
        d_qwait = 0.0
        for sk, d in (report.get("decisions") or {}).items():
            rec = self._shapes.get(sk)
            if rec is None:
                if len(self._shapes) >= 1024:  # shape-cardinality bound
                    continue
                rec = self._shapes[sk] = _new_shape_record(
                    d.get("demand") or {})
            for c in ("granted", "queued", "spillback", "infeasible",
                      "cancelled"):
                rec[c] += max(0, int(d.get(c, 0)))
            rec["queue_wait_s_total"] += max(
                0.0, float(d.get("queue_wait_s", 0.0)))
            rec["queue_wait_max_s"] = max(
                rec["queue_wait_max_s"],
                float(d.get("queue_wait_max_s", 0.0)))
            rec["max_spill_hops"] = max(
                rec["max_spill_hops"], int(d.get("max_spill_hops", 0)))
            if d.get("last_reason"):
                rec["last_reason"] = str(d["last_reason"])[:_MSG_CAP]
            if d.get("last_candidates") is not None:
                rec["last_candidates"] = d["last_candidates"]
            rec["last_ts"] = max(rec["last_ts"], ts)
            for sample in d.get("recent") or ():
                rec["recent"].append(sample)
            d_spill += max(0, int(d.get("spillback", 0)))
            d_infeas += max(0, int(d.get("infeasible", 0)))
            d_qwait += max(0.0, float(d.get("queue_wait_s", 0.0)))
        from ray_tpu.util.builtin_metrics import (quota_throttled_records,
                                                  sched_metric_records)

        self._metric_records.extend(sched_metric_records(
            node, spillbacks=d_spill, infeasible=d_infeas,
            queue_wait_s=d_qwait,
            pending=self._node_sched[node]["pending"], ts=ts))
        if throttled:
            self._metric_records.extend(
                quota_throttled_records(node, throttled, ts=ts))

    def drain_metric_records(self) -> list[dict]:
        out, self._metric_records = self._metric_records, []
        return out

    def node_sched(self, node_hex: str) -> dict:
        return self._node_sched.get(node_hex) or {
            "pending": 0, "pending_shapes": {}, "ts": 0.0}

    def drop_node(self, node_hex: str):
        """A dead node's pending-lease report will never be withdrawn
        by the node itself: purge it so `rayt status` / the autoscaler
        don't read phantom demand forever."""
        self._node_sched.pop(node_hex, None)
        self._node_job_usage.pop(node_hex, None)

    def job_usage(self) -> dict:
        """Cluster-wide leased usage by job: {job_hex: {res: amt}},
        summed over the nodes' absolute per-report ledgers. This is the
        quota plane's 'used' input (core/placement.py)."""
        out: dict[str, dict[str, float]] = {}
        for usage in self._node_job_usage.values():
            for j, res in usage.items():
                agg = out.setdefault(j, {})
                for r, amt in res.items():
                    agg[r] = agg.get(r, 0.0) + amt
        return out

    def quota_throttled_totals(self) -> dict:
        """Cumulative quota-throttle verdicts per job hex."""
        return dict(self._quota_throttled)

    def pending_demand(self) -> dict:
        """Cluster-wide aggregate pending lease demand by shape:
        shape_key -> {"count", "demand", "nodes": [hex, ...]}."""
        out: dict[str, dict] = {}
        for node, st in self._node_sched.items():
            for sk, entry in st.get("pending_shapes", {}).items():
                agg = out.setdefault(sk, {"count": 0,
                                          "demand": entry["demand"],
                                          "nodes": []})
                agg["count"] += entry["count"]
                agg["nodes"].append(node)
        return out

    def shape_stats(self, sk: str) -> Optional[dict]:
        rec = self._shapes.get(sk)
        if rec is None:
            return None
        return self._shape_view(rec)

    @staticmethod
    def _shape_view(rec: dict) -> dict:
        out = {k: v for k, v in rec.items() if k != "recent"}
        out["recent"] = [dict(s) if isinstance(s, dict) else s
                         for s in rec["recent"]]
        n_q = rec["queued"]
        out["queue_wait_mean_s"] = (
            rec["queue_wait_s_total"] / n_q if n_q else None)
        out["decisions"] = (rec["granted"] + rec["spillback"]
                            + rec["infeasible"] + rec["cancelled"])
        return out

    def summarize_scheduling(self) -> dict:
        """`rayt status` / state-API rollup: per-shape decision totals,
        per-node pending queue state, and cluster totals."""
        shapes = {sk: self._shape_view(r)
                  for sk, r in self._shapes.items()}
        totals = {"granted": 0, "queued": 0, "spillback": 0,
                  "infeasible": 0, "cancelled": 0,
                  "queue_wait_s_total": 0.0, "max_spill_hops": 0}
        for r in self._shapes.values():
            for c in ("granted", "queued", "spillback", "infeasible",
                      "cancelled"):
                totals[c] += r[c]
            totals["queue_wait_s_total"] += r["queue_wait_s_total"]
            totals["max_spill_hops"] = max(totals["max_spill_hops"],
                                           r["max_spill_hops"])
        totals["queue_wait_s_total"] = round(
            totals["queue_wait_s_total"], 4)
        return {
            "shapes": shapes,
            "nodes": {n: dict(st) for n, st in self._node_sched.items()},
            "pending_total": sum(st.get("pending", 0)
                                 for st in self._node_sched.values()),
            "totals": totals,
            "quota_throttled": dict(self._quota_throttled),
            "job_usage": self.job_usage(),
            "reports_ingested": self._reports_ingested,
        }
