"""Device-edge tests (ISSUE 12): same-client handoff, raw-shard-bytes
transport framing, per-edge device compile, donation helpers, in-mesh
collective lowering, and the RL payload pack coverage that backs the
zero-host-pickle acceptance.

Runs on the CPU backend (conftest pins jax to CPU with 8 virtual
devices): "device" memory is host RAM there, but the code paths — pack,
out-of-band raw shard bytes, device_put rebuild, donation vectors,
GSPMD reduce — are the ones a TPU run exercises.
"""

from __future__ import annotations

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.dag import InputNode, MultiOutputNode
from ray_tpu.dag.channel import ChannelClosed, ShmChannel
from ray_tpu.dag.channel_exec import ChannelCompiledDAG
from ray_tpu.dag.device_channel import (DeviceChannel, DeviceChannelSpec,
                                        DeviceTransportChannel,
                                        attach_device,
                                        donation_argnums_for, donating_jit,
                                        pack_device_tree)


def _jnp():
    import jax.numpy as jnp

    return jnp


# ------------------------------------------------- same-client channel

def test_device_channel_same_client_handoff_is_zero_copy():
    """A same-client device edge hands the jax.Array OBJECT over — the
    consumer reads the very same array, no serialize round trip."""
    jnp = _jnp()
    ch = DeviceChannel.create(n_slots=4)
    peer = attach_device(ch.spec)
    try:
        arr = jnp.arange(256, dtype=jnp.float32)
        ch.write(arr)
        out = peer.read(timeout=5)
        assert out is arr                      # identity, not a copy
        assert ch.device_arrays == 1
        assert ch.stats.writes == 1 and peer.stats.reads == 1
        assert ch.stats.bytes_written == arr.nbytes
    finally:
        peer.close()
        ch.close()


def test_device_channel_backpressure_and_close():
    jnp = _jnp()
    ch = DeviceChannel.create(n_slots=2)
    peer = attach_device(ch.spec)
    arr = jnp.zeros(8, jnp.float32)
    ch.write(arr)
    ch.write(arr)
    with pytest.raises(TimeoutError):
        ch.write(arr, timeout=0.2)             # handoff full: blocks
    assert ch.occupancy() == 2
    peer.read(timeout=5)
    ch.write(arr, timeout=5)                   # room again
    ch.close()
    ch.close()                                 # idempotent
    peer.read(timeout=5)                       # buffered items drain...
    peer.read(timeout=5)
    with pytest.raises(ChannelClosed):
        peer.read(timeout=5)                   # ...then close surfaces
    with pytest.raises(ChannelClosed):
        ch.write(arr, timeout=1)               # writes refuse after close
    # the registry entry is gone: a same-client-only spec can no longer
    # resolve in this process
    with pytest.raises(ChannelClosed):
        attach_device(DeviceChannelSpec(name=ch.spec.name, inner=None))


def test_device_channel_close_drains_buffered_items_first():
    jnp = _jnp()
    ch = DeviceChannel.create(n_slots=4)
    peer = attach_device(ch.spec)
    ch.write(jnp.ones(4))
    ch.close()
    # a buffered item written before close is still readable
    out = peer.read(timeout=5)
    assert float(np.asarray(out).sum()) == 4.0
    with pytest.raises(ChannelClosed):
        peer.read(timeout=5)
    peer.close()


# ---------------------------------------------------- payload framing

def test_pack_device_tree_covers_rl_shaped_payloads():
    """The zero-host-pickle assertion: packing a steady-state RL tick
    payload (batch dicts + weight pytrees, the shapes IMPALA's device
    edges carry) leaves NO jax.Array in the skeleton — pickle never
    sees a device buffer."""
    import jax

    jnp = _jnp()
    batch = {
        "obs": jnp.zeros((4, 8, 4), jnp.float32),
        "actions": jnp.zeros((4, 8), jnp.int32),
        "rewards": jnp.zeros((4, 8), jnp.float32),
        "episode_returns": [1.0, 2.0],           # host list rides as-is
    }
    weights = {"pi": {"w": jnp.zeros((4, 2)), "b": jnp.zeros((2,))},
               "v": [jnp.zeros((4,)), np.zeros((3,))]}
    payload = {"aux": {"loss": 0.5}, "updates": 1,
               "weights": weights, "batches": [[batch], []]}
    packed, n = pack_device_tree(payload)
    assert n == 6                                 # every jax leaf packed

    def assert_no_jax(tree):
        if isinstance(tree, dict):
            for v in tree.values():
                assert_no_jax(v)
        elif isinstance(tree, (list, tuple)):
            for v in tree:
                assert_no_jax(v)
        else:
            assert not isinstance(tree, jax.Array), tree

    assert_no_jax(packed)
    # numpy leaves stay numpy (they already ride out-of-band)
    assert isinstance(packed["weights"]["v"][1], np.ndarray)
    # no device leaves -> identity (no tree rebuild on host payloads)
    host_only = {"a": np.zeros(4), "b": [1, 2]}
    same, n0 = pack_device_tree(host_only)
    assert n0 == 0 and same is host_only


def test_pack_roundtrip_rebuilds_on_device():
    """serialize(pack(tree)) -> deserialize rebuilds jax.Arrays with
    equal contents; the pickle stream carries only metadata (the raw
    shard bytes ride out-of-band buffers)."""
    import jax

    from ray_tpu._internal.serialization import (chunks_to_bytes,
                                                 deserialize, serialize)

    jnp = _jnp()
    arr = jnp.arange(1024, dtype=jnp.float32).reshape(32, 32)
    packed, n = pack_device_tree({"x": arr, "tag": "t"})
    assert n == 1
    chunks = serialize(packed)
    # the pickle stream (chunk 1) must not contain the array payload —
    # the 4 KiB of float bytes ride as their own out-of-band chunk
    assert len(chunks[1]) < arr.nbytes
    out = deserialize(chunks_to_bytes(chunks))
    assert isinstance(out["x"], jax.Array)
    np.testing.assert_array_equal(np.asarray(out["x"]), np.asarray(arr))
    assert out["tag"] == "t"


def test_device_transport_over_shm_ring_releases_slots():
    """Cross-process framing over a shm ring: values rebuild as
    jax.Arrays on read, and slots release DETERMINISTICALLY (the read
    copies the payload out — a ring must never stall on jax's internal
    references to the rebuilt value's host source)."""
    import jax

    jnp = _jnp()
    inner = ShmChannel.create(slot_size=1 << 20, n_slots=2)
    peer = ShmChannel.attach(inner.spec)
    spec = DeviceChannelSpec(name=inner.spec.name, inner=inner.spec)
    prod = DeviceTransportChannel(inner, spec)
    cons = DeviceTransportChannel(peer, spec)
    try:
        held = []
        # 2-slot ring, 6 ticks while HOLDING every rebuilt value: only
        # possible when each read releases its slot immediately
        for i in range(6):
            prod.write({"x": jnp.full((128,), float(i))}, timeout=5)
            v = cons.read(timeout=5)
            assert isinstance(v["x"], jax.Array)
            assert float(np.asarray(v["x"])[0]) == float(i)
            held.append(v)
        assert peer.pinned_slots() == 0
        assert prod.device_arrays == 6
        snap = prod.snapshot()
        assert snap["device_arrays"] == 6
        assert snap["writes"] == 6 and snap["bytes_written"] > 0
    finally:
        cons.close()
        prod.close()


# -------------------------------------------------------- DAG compile

def test_dag_device_edge_end_to_end(local_cluster):
    """A .with_tensor_transport() edge compiles to kind=device (no
    Ineligible fallback), the consumer receives jax.Arrays, and
    teardown closes the device channels exactly once."""
    @rt.remote
    class Prod:
        def make(self, x):
            import jax.numpy as jnp

            return {"w": jnp.arange(16, dtype=jnp.float32) * x}

    @rt.remote
    class Cons:
        def consume(self, d):
            import jax

            assert isinstance(d["w"], jax.Array), type(d["w"])
            return float(d["w"].sum())

    p, c = Prod.remote(), Cons.remote()
    with InputNode() as inp:
        out = c.consume.bind(p.make.bind(inp).with_tensor_transport())
    dag = out.experimental_compile(channels=True)
    assert isinstance(dag, ChannelCompiledDAG)
    assert dag.channel_kinds["device"] == 1
    assert dag.channel_kinds["shm"] == 2          # input + output edges
    assert dag.execute(2).get(timeout=60) == 240.0
    assert dag.execute(3).get(timeout=60) == 360.0
    import collections

    calls = collections.Counter()
    for ch in dag._driver_channels:
        def _patched(_ch=ch, _orig=ch.close):
            # count EFFECTFUL closes only (close() is idempotent by
            # contract; teardown may invoke it from both the input
            # list and the driver-handle list)
            if not getattr(_ch, "_closed_locally", False):
                calls.update([id(_ch)])
            return _orig()

        ch.close = _patched
    dag.teardown()
    dag.teardown()                                 # idempotent
    assert len(calls) == len(dag._driver_channels)
    assert all(v == 1 for v in calls.values()), calls


def test_dag_device_input_edges_broadcast_weights(local_cluster):
    """device_input=True: the driver's input edges ship jax weight
    pytrees as raw shard bytes and each consumer rebuilds them on its
    devices (the RL weight-broadcast shape)."""
    import jax

    jnp = _jnp()

    @rt.remote
    class Runner:
        def tick(self, weights):
            import jax as j

            if weights is None:
                return -1.0
            assert isinstance(weights["w"], j.Array)
            return float(weights["w"].sum())

    r1, r2 = Runner.remote(), Runner.remote()
    with InputNode() as inp:
        dag = MultiOutputNode(
            [r1.tick.bind(inp), r2.tick.bind(inp)]).experimental_compile(
                channels=True, device_input=True)
    assert isinstance(dag, ChannelCompiledDAG)
    assert dag.channel_kinds["device"] == 2
    try:
        w = {"w": jnp.ones((8,), jnp.float32)}
        assert dag.execute(w).get(timeout=60) == [8.0, 8.0]
        # None ticks (no broadcast) flow through the same device edges
        assert dag.execute(None).get(timeout=60) == [-1.0, -1.0]
        # the driver-side producer wrappers counted the packed arrays
        assert sum(ch.device_arrays
                   for ch in dag._device_input_channels) == 2
    finally:
        dag.teardown()


# ------------------------------------------------------------ donation

def test_donation_vector_from_edge_arity():
    assert donation_argnums_for(3) == (0, 1, 2)
    assert donation_argnums_for(1, offset=2) == (2,)
    jnp = _jnp()

    def f(params, batch):
        return {k: v + batch for k, v in params.items()}

    jit_f = donating_jit(f, n_edge_args=1, offset=1)
    params = {"w": jnp.ones((4,))}
    batch = jnp.full((4,), 2.0)
    out = jit_f(params, batch)
    np.testing.assert_allclose(np.asarray(out["w"]), 3.0)


# ---------------------------------------------------- in-mesh lowering

def test_mesh_shared_detection():
    from ray_tpu.dag.collective import mesh_shared

    devs = ("TPU_0", "TPU_1")
    # two controllers of one 2-device mesh, one chip each: shared
    assert mesh_shared([(0, 2, devs, 1), (1, 2, devs, 1)])
    # a world of one shares its own mesh trivially — but ONLY when its
    # client is single-process: a lone controller of a 4-process mesh
    # must not dispatch a whole-mesh collective alone
    assert mesh_shared([(0, 1, ("CPU_0",), 1)])
    assert not mesh_shared([(0, 4, devs, 1)])
    # CPU actor fleet: each rank is its OWN single-process client whose
    # device view merely LOOKS identical — NOT a shared mesh
    assert not mesh_shared([(0, 1, devs, 1), (0, 1, devs, 1)])
    # different global device views: not one mesh
    assert not mesh_shared([(0, 2, ("TPU_0",), 1), (1, 2, devs, 1)])
    # >1 addressable chip per rank: contribution shape ambiguous
    assert not mesh_shared([(0, 2, devs, 2), (1, 2, devs, 2)])
    # duplicate process indices: not world-many distinct controllers
    assert not mesh_shared([(0, 2, devs, 1), (0, 2, devs, 1)])
    # a jax-less participant can never be in-mesh
    assert not mesh_shared([None, (1, 2, devs, 1)])


def test_in_mesh_allreduce_world_one_stays_on_device():
    import jax

    from ray_tpu.dag.collective import (in_mesh_allgather,
                                        in_mesh_allreduce)

    jnp = _jnp()
    x = jnp.arange(8, dtype=jnp.float32)
    out = in_mesh_allreduce(x, "sum")
    assert isinstance(out, jax.Array)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
    gathered = in_mesh_allgather(x)
    assert len(gathered) == 1
    np.testing.assert_array_equal(np.asarray(gathered[0]), np.asarray(x))
    with pytest.raises(ValueError):
        in_mesh_allreduce(x, "median")


def test_in_mesh_stack_reduce_gspmd_numerics(cpu_mesh_devices):
    """The multi-controller psum lowering, exercised on the 8-virtual-
    device CPU mesh: every device contributes one shard and the jitted
    GSPMD reduction replicates sum/max back — numerically identical to
    the out-of-band reduce of the same contributions."""
    import jax

    from ray_tpu.dag.collective import (_in_mesh_stack_gather,
                                        _in_mesh_stack_reduce)

    jnp = _jnp()
    world = len(jax.devices())
    x = jnp.arange(16, dtype=jnp.float32)
    red = _in_mesh_stack_reduce(x, "sum")
    # each of the `world` devices contributed x: psum == world * x
    np.testing.assert_allclose(np.asarray(red),
                               world * np.asarray(x))
    red_max = _in_mesh_stack_reduce(x, "max")
    np.testing.assert_allclose(np.asarray(red_max), np.asarray(x))
    gathered = _in_mesh_stack_gather(x)
    assert len(gathered) == world
    for g in gathered:
        np.testing.assert_allclose(np.asarray(g), np.asarray(x))


def test_in_mesh_equals_out_of_band_fallback(local_cluster):
    """Acceptance: in-mesh collective lowering verified
    equal-to-fallback numerically — the same single-participant
    allreduce through (a) the channel path, where the world-of-one
    group is mesh-shared and lowers in-mesh (the value is a jax.Array),
    and (b) the per-call fallback's out-of-band one-shot group."""
    from ray_tpu.dag import collective

    @rt.remote
    class W:
        def grad(self, x):
            import jax.numpy as jnp

            return jnp.full((4,), float(3 * x))

    a = W.remote()
    with InputNode() as inp:
        (r,) = collective.allreduce.bind([a.grad.bind(inp)], op="sum")
        dag = r.experimental_compile(channels=True)
    assert isinstance(dag, ChannelCompiledDAG)
    try:
        in_mesh_out = np.asarray(dag.execute(2).get(timeout=60))
    finally:
        dag.teardown()

    b = W.remote()
    with InputNode() as inp:
        (r2,) = collective.allreduce.bind([b.grad.bind(inp)], op="sum")
        fallback = r2.experimental_compile(channels=False)
    fallback_out = np.asarray(fallback.execute(2).get(timeout=60))
    np.testing.assert_allclose(in_mesh_out, fallback_out)
    np.testing.assert_allclose(in_mesh_out, np.full((4,), 6.0))


def test_allgather_binder_validation_and_channel_path(local_cluster):
    """allgather.bind: distinct-actors validation + in-loop gather over
    the channel fast path, parity with the per-call fallback."""
    from ray_tpu.dag import collective

    @rt.remote
    class W:
        def __init__(self, k):
            self.k = k

        def val(self, x):
            return np.full((2,), float(x * self.k))

    a, b = W.remote(1), W.remote(10)
    with pytest.raises(ValueError):
        collective.allgather.bind([])
    with InputNode() as inp:
        na = a.val.bind(inp)
        with pytest.raises(ValueError):
            # same actor twice: participants must be distinct
            collective.allgather.bind([na, a.val.bind(inp)])
        ga, gb = collective.allgather.bind([na, b.val.bind(inp)])
        dag = MultiOutputNode([ga, gb]).experimental_compile(
            channels=True)
    assert isinstance(dag, ChannelCompiledDAG)
    try:
        va, vb = dag.execute(3).get(timeout=60)
        assert len(va) == 2 and len(vb) == 2
        np.testing.assert_allclose(va[0], 3.0)
        np.testing.assert_allclose(va[1], 30.0)
        np.testing.assert_allclose(vb[0], 3.0)
        np.testing.assert_allclose(vb[1], 30.0)
    finally:
        dag.teardown()


# ------------------------------------------------- observability record

def test_device_edge_record_and_cli_rendering(capsys):
    """A kind=device edge lands in the GCS dag record with transport +
    device_arrays, and `rayt dag` renders it (not a blank row)."""
    from ray_tpu.core.gcs_dag_manager import GcsDagManager
    from ray_tpu.scripts.cli import _print_dag

    mgr = GcsDagManager()
    mgr.ingest({
        "kind": "register", "dag_id": "d" * 16, "job_id": "j" * 8,
        "driver": "w" * 8, "ts": 100.0,
        "channel_kinds": {"shm": 1, "dcn": 0, "device": 1},
        "edges": [
            {"edge": "e0", "channel": "c0", "kind": "device",
             "transport": "shm", "n_slots": 8, "slot_size": 1 << 20,
             "role": "edge",
             "producer": {"actor": "a" * 8, "label": "Agg:aaaa"},
             "consumer": {"actor": "b" * 8, "label": "Learner:bbbb"}},
            {"edge": "e1", "channel": "c1", "kind": "shm",
             "n_slots": 8, "slot_size": 1 << 20, "role": "output",
             "producer": {"actor": "b" * 8, "label": "Learner:bbbb"},
             "consumer": {"actor": "", "label": "driver"}},
        ]})
    mgr.ingest({
        "kind": "report", "dag_id": "d" * 16, "ts": 101.0,
        "channels": {"c0": {"role": "producer", "writes": 7,
                            "bytes_written": 7 << 20,
                            "device_arrays": 21, "write_block_s": 0.0,
                            "write_blocked_s_now": 0.0}}})
    rec = mgr.list(dag_id="d" * 16)["dags"][0]
    e0 = next(e for e in rec["edges"] if e["edge"] == "e0")
    assert e0["kind"] == "device" and e0["transport"] == "shm"
    assert e0["ticks"] == 7 and e0["device_arrays"] == 21
    assert e0["bytes"] == 7 << 20            # shard-bytes throughput
    _print_dag(rec)
    out = capsys.readouterr().out
    assert "device/shm" in out
    assert "21" in out                       # device_arrays column
    assert "device=1" in out                 # channel_kinds header


def test_device_objects_single_shard_skips_full_gather():
    """Satellite: serialize_array ships ONE addressable shard's bytes
    when it covers the array (single-shard / fully replicated) — the
    full-gather path must not run, and bytes-on-the-wire equals exactly
    one shard."""
    from ray_tpu.core.device_objects import (deserialize_array,
                                             serialize_array)

    jnp = _jnp()
    arr = jnp.arange(64, dtype=jnp.float32)
    raw, dtype, shape = serialize_array(arr)
    assert len(raw) == arr.nbytes            # one shard == whole array
    rebuilt = deserialize_array((raw, dtype, shape))
    np.testing.assert_array_equal(np.asarray(rebuilt), np.asarray(arr))

    class _Shard:
        def __init__(self, data):
            self.data = data

    class _FakeReplicated:
        """Duck-typed stand-in for a replicated sharded array: any full
        gather (np.asarray on the array itself) is an error."""
        shape = (8,)
        is_fully_replicated = True

        def __init__(self):
            one = np.arange(8, dtype=np.float32)
            self.addressable_shards = [_Shard(one), _Shard(one.copy())]

        def __array__(self, *a, **k):
            raise AssertionError(
                "full host gather ran for a replicated array")

    raw, dtype, shape = serialize_array(_FakeReplicated())
    assert len(raw) == 8 * 4                 # ONE shard's bytes shipped
    assert dtype == "float32" and tuple(shape) == (8,)
