"""Unit tests for the GCS metrics time-series store: bin retention,
downsampling, counter→rate conversion, and cross-node histogram
percentile merge (ref analog: metrics_agent aggregation semantics)."""

from __future__ import annotations

import pytest

from ray_tpu.core.metrics_store import (MetricsStore, _bucket_percentile)

T0 = 1_700_000_000.0  # fixed epoch so tests are deterministic


def rec(name, kind, value=None, ts=T0, tags=None, **extra):
    out = {"name": name, "kind": kind, "tags": tags or {}, "ts": ts}
    if value is not None:
        out["value"] = value
    out.update(extra)
    return out


def series_points(out, idx=0):
    return out["series"][idx]["points"]


def nonnull(points):
    return [(t, v) for t, v in points if v is not None]


class TestCounter:
    def test_rate_conversion(self):
        s = MetricsStore(retention_s=120, resolution_s=1.0)
        # 10 increments of 2.0 spread over 10 seconds
        for i in range(10):
            s.ingest(rec("c", "counter", 2.0, ts=T0 + i))
        out = s.query("c", window_s=20, step_s=10, now=T0 + 10)
        assert out["kind"] == "counter" and out["agg"] == "rate"
        # rate * step recovers the total increase
        total = sum(v * out["step_s"] for _, v in nonnull(
            series_points(out)))
        assert total == pytest.approx(20.0)

    def test_increase_agg_and_downsample(self):
        s = MetricsStore(retention_s=120, resolution_s=1.0)
        for i in range(10):
            s.ingest(rec("c", "counter", 1.0, ts=T0 + i))
        out = s.query("c", window_s=10, step_s=5, agg="increase",
                      now=T0 + 9.5)
        vals = [v for _, v in nonnull(series_points(out))]
        assert sum(vals) == pytest.approx(10.0)
        assert len(vals) == 2  # two 5s steps, 5 increments each
        assert vals == [pytest.approx(5.0), pytest.approx(5.0)]

    def test_tag_sets_are_separate_series(self):
        s = MetricsStore(retention_s=60, resolution_s=1.0)
        s.ingest(rec("c", "counter", 1.0, tags={"route": "a"}))
        s.ingest(rec("c", "counter", 3.0, tags={"route": "b"}))
        out = s.query("c", window_s=10, step_s=10, now=T0 + 1)
        assert len(out["series"]) == 2
        flt = s.query("c", window_s=10, step_s=10, now=T0 + 1,
                      tags={"route": "b"})
        assert len(flt["series"]) == 1
        total = sum(v * flt["step_s"] for _, v in nonnull(
            series_points(flt)))
        assert total == pytest.approx(3.0)


class TestGauge:
    def test_last_write_wins_within_step(self):
        s = MetricsStore(retention_s=60, resolution_s=1.0)
        # one set per second; a 5s step must report the LAST value,
        # never the sum of the five sets
        for i in range(5):
            s.ingest(rec("g", "gauge", float(i + 1), ts=T0 + i))
        out = s.query("g", window_s=5, step_s=5, now=T0 + 4.5)
        vals = [v for _, v in nonnull(series_points(out))]
        assert vals == [pytest.approx(5.0)]

    def test_retention_drops_old_bins(self):
        s = MetricsStore(retention_s=10, resolution_s=1.0)
        s.ingest(rec("g", "gauge", 111.0, ts=T0))
        for i in range(20):  # push the ring past retention
            s.ingest(rec("g", "gauge", float(i), ts=T0 + 5 + i))
        out = s.query("g", window_s=10, step_s=1, now=T0 + 25)
        vals = [v for _, v in nonnull(series_points(out))]
        assert 111.0 not in vals
        assert vals[-1] == pytest.approx(19.0)

    def test_merge_sums_across_nodes(self):
        s = MetricsStore(retention_s=60, resolution_s=1.0)
        s.ingest(rec("g", "gauge", 2.0, tags={"node": "a"}))
        s.ingest(rec("g", "gauge", 3.0, tags={"node": "b"}))
        out = s.query("g", window_s=10, step_s=10, merge=True,
                      now=T0 + 1)
        assert len(out["series"]) == 1
        vals = [v for _, v in nonnull(series_points(out))]
        assert vals == [pytest.approx(5.0)]


class TestHistogram:
    BOUNDS = [0.1, 1.0, 10.0]

    def test_raw_observations_bucket(self):
        s = MetricsStore(retention_s=60, resolution_s=1.0)
        for v in (0.05, 0.5, 5.0, 50.0):
            s.ingest(rec("h", "histogram", v, bounds=self.BOUNDS))
        snap = {m["name"]: m for m in s.snapshot()}["h"]
        assert snap["count"] == 4
        assert snap["sum"] == pytest.approx(55.55)
        # cumulative buckets: [0.1]=1, [1.0]=2, [10.0]=3, +Inf=4
        assert [c for _, c in snap["buckets"]] == [1, 2, 3, 4]

    def test_batched_bucket_delta_record(self):
        s = MetricsStore(retention_s=60, resolution_s=1.0)
        s.ingest(rec("h", "histogram", bounds=self.BOUNDS,
                     counts=[3, 2, 1, 0], sum=4.0, count=6))
        snap = {m["name"]: m for m in s.snapshot()}["h"]
        assert snap["count"] == 6 and snap["sum"] == pytest.approx(4.0)
        out = s.query("h", window_s=10, step_s=10, agg="count",
                      now=T0 + 1)
        vals = [v for _, v in nonnull(series_points(out))]
        assert vals == [pytest.approx(0.6)]  # 6 obs / 10s step

    def test_cross_node_percentile_merge(self):
        """Two nodes publish the same histogram with different node
        tags; merge=True combines their buckets for cluster
        percentiles."""
        s = MetricsStore(retention_s=60, resolution_s=1.0)
        # node a: 100 observations all <= 0.1
        s.ingest(rec("h", "histogram", tags={"node": "a"},
                     bounds=self.BOUNDS, counts=[100, 0, 0, 0],
                     sum=5.0, count=100))
        # node b: 100 observations in (1.0, 10.0]
        s.ingest(rec("h", "histogram", tags={"node": "b"},
                     bounds=self.BOUNDS, counts=[0, 0, 100, 0],
                     sum=500.0, count=100))
        p50 = s.query("h", window_s=10, step_s=10, agg="p50",
                      merge=True, now=T0 + 1)
        assert len(p50["series"]) == 1
        v50 = nonnull(series_points(p50))[0][1]
        assert v50 <= 0.1 + 1e-9  # median sits at the end of bucket 0
        p99 = s.query("h", window_s=10, step_s=10, agg="p99",
                      merge=True, now=T0 + 1)
        v99 = nonnull(series_points(p99))[0][1]
        assert 1.0 < v99 <= 10.0  # deep inside node b's bucket
        mean = s.query("h", window_s=10, step_s=10, agg="mean",
                       merge=True, now=T0 + 1)
        vm = nonnull(series_points(mean))[0][1]
        assert vm == pytest.approx(505.0 / 200)

    def test_same_tags_merge_at_ingest(self):
        """Identical (name, tags) from different processes land in ONE
        series — cross-node merge needs no query-side work."""
        s = MetricsStore(retention_s=60, resolution_s=1.0)
        for _ in range(2):  # two 'processes'
            s.ingest(rec("h", "histogram", tags={}, bounds=self.BOUNDS,
                         counts=[1, 1, 0, 0], sum=0.6, count=2))
        out = s.query("h", window_s=10, step_s=10, agg="count",
                      now=T0 + 1)
        assert len(out["series"]) == 1
        vals = [v for _, v in nonnull(series_points(out))]
        assert vals == [pytest.approx(0.4)]  # 4 obs / 10s


class TestStoreHygiene:
    def test_names_directory(self):
        s = MetricsStore(retention_s=60, resolution_s=1.0)
        s.ingest(rec("a", "counter", 1.0, tags={"x": "1"}))
        s.ingest(rec("a", "counter", 1.0, tags={"x": "2", "y": "z"}))
        s.ingest(rec("b", "gauge", 1.0))
        names = {n["name"]: n for n in s.names()}
        assert names["a"]["kind"] == "counter"
        assert names["a"]["num_series"] == 2
        assert names["a"]["tag_keys"] == ["x", "y"]
        assert names["b"]["kind"] == "gauge"

    def test_malformed_records_dropped_not_raised(self):
        s = MetricsStore(retention_s=60, resolution_s=1.0)
        s.ingest({"name": "x"})  # no kind
        s.ingest(rec("x", "mystery", 1.0))
        s.ingest(rec("x", "counter", "not-a-number"))
        assert s.dropped_records == 3
        assert s.names() == []  # no phantom series from bad records

    def test_series_cap_evicts_lru(self):
        s = MetricsStore(retention_s=60, resolution_s=1.0, max_series=4)
        for i in range(8):
            s.ingest(rec("m", "counter", 1.0, ts=T0 + i,
                         tags={"i": str(i)}))
        assert sum(n["num_series"] for n in s.names()) == 4

    def test_prune_idle_series(self):
        s = MetricsStore(retention_s=10, resolution_s=1.0)
        s.ingest(rec("old", "gauge", 1.0, ts=T0))
        s.ingest(rec("new", "gauge", 1.0, ts=T0 + 100))
        assert s.prune(now=T0 + 100) == 1
        assert [n["name"] for n in s.names()] == ["new"]

    def test_query_unknown_metric_is_empty(self):
        s = MetricsStore(retention_s=60, resolution_s=1.0)
        out = s.query("nope", window_s=10, now=T0)
        assert out["series"] == [] and out["kind"] is None

    def test_bad_agg_raises(self):
        s = MetricsStore(retention_s=60, resolution_s=1.0)
        s.ingest(rec("c", "counter", 1.0))
        with pytest.raises(ValueError):
            s.query("c", agg="p99", now=T0 + 1)


def test_bucket_percentile_interpolation():
    bounds = [1.0, 2.0]
    # 10 obs uniformly in (1, 2]: p50 interpolates to ~1.5
    assert _bucket_percentile(bounds, [0, 10, 0], 10, 0.5) == \
        pytest.approx(1.5)
    # overflow bucket clamps to the last bound
    assert _bucket_percentile(bounds, [0, 0, 10], 10, 0.9) == \
        pytest.approx(2.0)
