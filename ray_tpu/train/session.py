"""Per-worker training session (ref analog: train/_internal/session.py —
`ray.train.report`, `get_checkpoint`, `get_context`).

Runs inside each TrainWorker actor. `report()` persists the worker's
checkpoint shard into run storage and queues the metrics row; the
controller drains rows via a concurrent actor method (threaded actor).
"""

from __future__ import annotations

import collections
import os
import shutil
import threading
import time
from typing import Any, Optional

from ray_tpu.train.checkpoint import Checkpoint

_context: "TrainContext | None" = None
_context_lock = threading.Lock()


class TrainContext:
    def __init__(self, rank: int, world_size: int, experiment_path: str,
                 experiment_name: str, latest_checkpoint: Optional[str],
                 mesh_axes: Optional[dict] = None,
                 ingest_spec=None, run_id: Optional[str] = None,
                 node_id: str = ""):
        self.rank = rank
        self.world_size = world_size
        self.experiment_path = experiment_path
        self.experiment_name = experiment_name
        self.mesh_axes = mesh_axes
        self.ingest_spec = ingest_spec
        self.run_id = run_id
        # per-step waterfall recorder (train/telemetry.py), live when
        # the controller minted a run id and capture is enabled
        self.recorder = None
        if run_id:
            try:
                from ray_tpu.train.telemetry import (StepRecorder,
                                                     recording_enabled)

                if recording_enabled():
                    self.recorder = StepRecorder(
                        run_id, experiment_name, rank=rank,
                        node_id=node_id)
            except Exception:
                self.recorder = None
        self._latest_checkpoint_dir = latest_checkpoint
        self._results: collections.deque = collections.deque()
        self._results_cond = threading.Condition()
        # resume past existing step dirs so a restarted worker group never
        # reuses checkpoint_* names the controller has already seen
        self._report_index = self._next_free_index(experiment_path)
        self._last_report_t: float | None = None

    @staticmethod
    def _next_free_index(experiment_path: str) -> int:
        import glob

        top = 0
        for d in glob.glob(os.path.join(experiment_path, "checkpoint_*")):
            tail = os.path.basename(d).rsplit("_", 1)[-1]
            if tail.isdigit():
                top = max(top, int(tail) + 1)
        return top

    # -------------------------------------------------------------- API
    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.rank

    def get_local_rank(self) -> int:
        return self.rank  # single-host-per-worker model

    def get_experiment_name(self) -> str:
        return self.experiment_name

    def get_checkpoint(self) -> Optional[Checkpoint]:
        if self._latest_checkpoint_dir is None:
            return None
        return Checkpoint(self._latest_checkpoint_dir)

    def get_mesh(self, devices=None):
        """Build the mesh described by ScalingConfig.mesh over the local
        (per-host) device set; pure-DP mesh when no axes were given."""
        from ray_tpu.parallel.mesh import build_mesh

        axes = self.mesh_axes or {"data": -1}
        return build_mesh(dict(axes), devices)

    def get_ingest(self, *, mesh=None, state: Optional[dict] = None):
        """This worker's corpus-ingest iterator (train/ingest.py), built
        from ScalingConfig.ingest with the shard slice derived from
        (rank, world_size). `state` restores a cursor saved in a
        checkpoint so the resumed token stream is bit-identical."""
        from ray_tpu.train.ingest import CorpusIngestIterator

        if self.ingest_spec is None:
            raise RuntimeError(
                "no ingest configured: pass ScalingConfig(ingest="
                "IngestSpec(...)) to the trainer")
        return CorpusIngestIterator(
            self.ingest_spec, dp_rank=self.rank,
            world_size=self.world_size, mesh=mesh, state=state,
            experiment=self.experiment_name, recorder=self.recorder)

    def _emit_metrics(self, metrics: dict):
        """Per-report training telemetry onto the cluster metrics
        pipeline (TorchTitan-style per-step throughput — PAPERS.md):
        tokens/sec (passthrough or tokens/dt), MFU, and a generic gauge
        per scalar key so any reported metric charts on the dashboard."""
        from ray_tpu.util import builtin_metrics as bm

        t = time.monotonic()
        dt = (t - self._last_report_t
              if self._last_report_t is not None else None)
        self._last_report_t = t
        tags = {"experiment": self.experiment_name, "rank": str(self.rank)}

        def scalar(key):
            v = metrics.get(key)
            return float(v) if isinstance(v, (int, float)) \
                and not isinstance(v, bool) else None

        tps = scalar("tokens_per_s")
        if tps is None and dt and dt > 0 and scalar("tokens") is not None:
            tps = scalar("tokens") / dt
        if tps is not None:
            bm.train_tokens_per_s.set(tps, tags=tags)
        mfu = scalar("mfu")
        if mfu is not None:
            bm.train_mfu.set(mfu, tags=tags)
        for k, v in metrics.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                bm.train_metric.set(float(v), tags={**tags, "key": str(k)})

    def report(self, metrics: dict, checkpoint: Optional[Checkpoint] = None):
        try:
            self._emit_metrics(metrics)
        except Exception:
            pass  # telemetry must never fail a train step
        entry = {"metrics": dict(metrics), "rank": self.rank,
                 "index": self._report_index, "checkpoint_dir": None}
        if checkpoint is not None:
            # the synchronous slice of the save (staging the shard into
            # run storage) is the step's ckpt_block_s waterfall stage;
            # an async-committed checkpoint's background portion is NOT
            # in here (see checkpoint.save_pytree_async)
            if self.recorder is not None:
                self.recorder.begin_phase("ckpt_block")
            try:
                step_dir = os.path.join(
                    self.experiment_path,
                    f"checkpoint_{self._report_index:06d}")
                rank_dir = os.path.join(step_dir, f"rank_{self.rank}")
                if os.path.abspath(checkpoint.path) != \
                        os.path.abspath(rank_dir):
                    os.makedirs(step_dir, exist_ok=True)
                    shutil.copytree(checkpoint.path, rank_dir,
                                    dirs_exist_ok=True)
                # durable completion marker: lets the controller recover
                # this checkpoint even if the worker dies before results
                # are drained
                with open(os.path.join(
                        step_dir, f".complete-rank_{self.rank}"), "w"):
                    pass
                entry["checkpoint_dir"] = step_dir
                self._latest_checkpoint_dir = step_dir
            finally:
                if self.recorder is not None:
                    self.recorder.end_phase()
        self._report_index += 1
        with self._results_cond:
            self._results.append(entry)
            self._results_cond.notify_all()

    def close_telemetry(self):
        """Worker teardown: drain the recorder's buffered step records
        synchronously so the run's tail survives the actor exit."""
        if self.recorder is not None:
            try:
                self.recorder.close()
            except Exception:
                pass

    # ------------------------------------------------------ controller side
    def drain_results(self) -> list[dict]:
        with self._results_cond:
            out = list(self._results)
            self._results.clear()
        return out


def get_context() -> TrainContext:
    if _context is None:
        raise RuntimeError("ray_tpu.train.get_context() called outside a "
                           "training worker")
    return _context


def set_context(ctx: Optional[TrainContext]):
    global _context
    with _context_lock:
        _context = ctx


def report(metrics: dict, checkpoint: Optional[Checkpoint] = None):
    get_context().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_context().get_checkpoint()


def get_ingest(*, mesh=None, state: Optional[dict] = None):
    return get_context().get_ingest(mesh=mesh, state=state)
