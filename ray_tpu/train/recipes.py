"""Reusable train-loop recipes for JaxTrainer.

The reference ships fine-tuning as free-standing torch/DeepSpeed example
scripts (ref: doc/source/train/examples/deepspeed/,
release/air_examples/dolly_v2_lightning_fsdp_finetuning/); here the
canonical loops are library code so tests, benches, and users share one
implementation.
"""

from __future__ import annotations

from typing import Any, Callable


def corpus_pretrain_loop(config: dict):
    """Pre-train from a sharded tokenized corpus via session ingest
    (train/ingest.py). The model is a deliberately tiny embedding net —
    this recipe is the canonical wiring of the INGEST contract: the
    corpus cursor is saved inside every checkpoint and restored on
    (re)start, so a run killed mid-epoch resumes consuming exactly the
    tokens an uninterrupted run would have.

    config keys:
      vocab_size, dim      — toy model size (default 128 / 8)
      lr, steps            — SGD rate / max train steps (corpus may end
                             earlier; the loop stops at either)
      checkpoint_every     — steps between checkpointed reports (def. 5)
      use_mesh             — shard batches onto the ScalingConfig mesh
      trace_dir            — debug/test hook: persist the consumed token
                             ids per step (trace_dir/rank{r}/step_*.npy);
                             re-executed steps overwrite, so the dir
                             always holds the EFFECTIVE consumed stream
      crash_at_step        — fault-injection hook: hard-exit the worker
                             before that step, once per marker file
    """
    import os
    import shutil

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu import train
    from ray_tpu.train.checkpoint import Checkpoint

    ctx = train.get_context()
    rank = ctx.get_world_rank()
    mesh = ctx.get_mesh() if config.get("use_mesh") else None

    vocab = config.get("vocab_size", 128)
    dim = config.get("dim", 8)
    lr = config.get("lr", 1e-2)
    steps = config.get("steps", 20)
    ckpt_every = config.get("checkpoint_every", 5)

    start_step = 0
    ingest_state = None
    w = jax.random.normal(jax.random.PRNGKey(0), (vocab, dim)) * 0.02
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        restored = Checkpoint(
            os.path.join(ckpt.path, f"rank_{rank}")).to_dict()
        w = jnp.asarray(restored["w"])
        start_step = int(restored["step"])
        ingest_state = restored["ingest"]

    it = ctx.get_ingest(mesh=mesh, state=ingest_state)

    @jax.jit
    def sgd_step(w, tokens):
        def loss_fn(w):
            emb = w[tokens]  # (B, T, dim) gather
            return jnp.mean(jnp.square(emb - jnp.mean(emb)))

        loss, grad = jax.value_and_grad(loss_fn)(w)
        return w - lr * grad, loss

    # per-step waterfall (train/telemetry): ingest stamps data_wait/h2d,
    # the compute phase block-until-readies so step_s is honest, report
    # stamps ckpt_block — the four stages tile step wall by construction.
    # wrap_jit adds compile/retrace accounting on the train step.
    rec = ctx.recorder
    if rec is not None:
        sgd_step = rec.wrap_jit(sgd_step, "sgd_step")

    trace_dir = config.get("trace_dir")
    if trace_dir:
        os.makedirs(os.path.join(trace_dir, f"rank{rank}"), exist_ok=True)
    crash_at = config.get("crash_at_step")

    loss = None
    try:
        for step in range(start_step, steps):
            if crash_at is not None and step == crash_at:
                marker = os.path.join(ctx.experiment_path,
                                      f".crashed-rank_{rank}")
                if not os.path.exists(marker):
                    open(marker, "w").close()
                    os._exit(1)  # simulate a hard worker kill mid-epoch
            try:
                batch = next(it)  # ingest stamps data_wait (+h2d if mesh)
            except StopIteration:
                break  # corpus exhausted before `steps`
            if rec is not None:
                with rec.phase("h2d"):
                    tokens = jnp.asarray(batch["tokens"])
            else:
                tokens = jnp.asarray(batch["tokens"])
            if trace_dir:
                np.save(os.path.join(trace_dir, f"rank{rank}",
                                     f"step_{step:05d}.npy"),
                        np.asarray(batch["tokens"]))
            if rec is not None:
                with rec.phase("step"):
                    w, loss = sgd_step(w, tokens)
                    jax.block_until_ready(loss)
            else:
                w, loss = sgd_step(w, tokens)
            if (step + 1) % ckpt_every == 0 or step == steps - 1:
                c = Checkpoint.from_dict({
                    "w": np.asarray(w), "step": step + 1,
                    "ingest": it.state_dict()})
                train.report(
                    {"loss": float(loss), "step": step + 1,
                     "tokens": int(batch["tokens"].size),
                     "ingest_stall_s": it.stats.stall_s,
                     "ingest_load_s": it.stats.load_s},
                    checkpoint=c)
                shutil.rmtree(c.path, ignore_errors=True)  # report copied
            if rec is not None:
                rec.end_step(step + 1, tokens=int(batch["tokens"].size),
                             loss=float(loss))
    finally:
        it.close()  # a failed step must not leak the prefetch thread
    return float(loss) if loss is not None else None


def lora_finetune_loop(config: dict):
    """LoRA fine-tune a Llama-family model (BASELINE.json config #3).

    Runs inside each TrainWorker: builds the mesh from ScalingConfig,
    initializes (or loads) frozen base params + LoRA adapters, and trains
    ONLY the adapters (build_train_step(trainable_keys=("lora",)) — the
    backward computes no base-weight gradients and the optimizer holds
    moments only for A/B).

    config keys:
      preset        — llama preset name (default "debug")
      model_overrides — dict merged into the preset config
      lora_rank / lora_alpha / lora_targets
      lr, steps, batch_size, seq_len, grad_accum
      report_every  — steps between train.report calls (default 10)
      batch_fn      — optional callable (step, rank) -> {"tokens","targets"}
                      (defaults to synthetic LM data)
      init_params_fn — optional callable (cfg) -> base params (defaults to
                      random init; real runs pass a checkpoint loader)
    """
    import os
    import pickle
    import tempfile

    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu import train
    from ray_tpu.models import llama, lora
    from ray_tpu.parallel.spmd import build_train_step, shard_batch
    from ray_tpu.train.checkpoint import Checkpoint, save_pytree

    ctx = train.get_context()
    mesh = ctx.get_mesh()

    overrides = dict(config.get("model_overrides") or {})
    overrides.setdefault("lora_alpha", config.get("lora_alpha", 16.0))
    cfg = llama.config_for(config.get("preset", "debug"), **overrides)
    lcfg = lora.LoraConfig(
        # cfg.lora_alpha is the single source of truth for the scale (the
        # forward and merge_lora both read it); mirror it here for repr
        rank=config.get("lora_rank", 8),
        alpha=cfg.lora_alpha,
        targets=tuple(config.get("lora_targets", lora.DEFAULT_TARGETS)))

    key = jax.random.PRNGKey(config.get("seed", 0))
    init_fn: Callable[[Any], Any] = config.get("init_params_fn") \
        or (lambda c: llama.init_params(c, key))
    base = init_fn(cfg)
    adapters = lora.init_lora_params(cfg, lcfg, jax.random.fold_in(key, 1))
    params = {**base, "lora": adapters}
    axes = {**llama.param_logical_axes(cfg),
            "lora": lora.lora_logical_axes(cfg, lcfg)}

    loss = lambda p, b: llama.loss_fn(p, b, cfg)
    step, state = build_train_step(
        loss, optax.adamw(config.get("lr", 1e-3)), params, axes, mesh,
        grad_accum=config.get("grad_accum", 1),
        trainable_keys=("lora",))

    rank = ctx.get_world_rank()
    start_step = 0
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        # failure-policy restart: reload adapters + optimizer moments +
        # step so a resumed run continues EXACTLY where it stopped —
        # resetting adamw moments would silently change training
        # dynamics after every restart. Moments cover only the adapters,
        # so the artifact stays small and serving-loadable.
        from ray_tpu.train.checkpoint import load_pytree

        ckpt_dir = ckpt.subdir(f"rank_{rank}").path
        restored = load_pytree(ckpt_dir)
        loaded = jax.tree.map(jnp.asarray, restored["lora"])
        state["params"]["lora"] = jax.tree.map(
            lambda x, cur: jax.device_put(x.astype(cur.dtype), cur.sharding),
            loaded, state["params"]["lora"])
        opt_path = os.path.join(ckpt_dir, "opt_state.pkl")
        if os.path.exists(opt_path):
            # pickled host copy (not save_pytree): pickle preserves the
            # optax NamedTuple structure exactly, so tree.map against the
            # live opt_state restores sharded without re-registration
            with open(opt_path, "rb") as f:
                opt_host = pickle.load(f)
            state["opt_state"] = jax.tree.map(
                lambda h, cur: jax.device_put(
                    jnp.asarray(h, cur.dtype), cur.sharding),
                opt_host, state["opt_state"])
        start_step = int(restored["step"])

    bsz = config.get("batch_size", 8)
    seq = config.get("seq_len", min(128, cfg.max_seq_len))
    batch_fn = config.get("batch_fn")

    def synthetic(i, rank):
        k = jax.random.PRNGKey(1000 * rank + i)
        toks = jax.random.randint(k, (bsz, seq), 0, cfg.vocab_size)
        return {"tokens": toks,
                "targets": jnp.roll(toks, -1, axis=1)}

    make_batch = batch_fn or synthetic
    report_every = config.get("report_every", 10)
    steps = config.get("steps", 50)

    # same waterfall as corpus_pretrain_loop (h2d = shard_batch, step =
    # block-until-ready update, ckpt_block stamped inside report)
    rec = ctx.recorder
    if rec is not None:
        step = rec.wrap_jit(step, "lora_step")

    last_loss = first_loss = None
    for i in range(start_step, steps):
        if rec is not None:
            with rec.phase("h2d"):
                batch = shard_batch(make_batch(i, rank), mesh)
            with rec.phase("step"):
                state, aux = step(state, batch)
                jax.block_until_ready(aux["loss"])
        else:
            batch = shard_batch(make_batch(i, rank), mesh)
            state, aux = step(state, batch)
        if (i + 1) % report_every == 0 or i == steps - 1:
            last_loss = float(aux["loss"])
            if first_loss is None:
                first_loss = last_loss
            with tempfile.TemporaryDirectory() as d:
                # adapters-only checkpoint: the LoRA artifact is the
                # deliverable (base stays wherever it was loaded from);
                # optimizer moments ride along so restarts resume the
                # exact trajectory
                save_pytree({"lora": state["params"]["lora"],
                             "step": i + 1}, d)
                with open(os.path.join(d, "opt_state.pkl"), "wb") as f:
                    pickle.dump(jax.device_get(state["opt_state"]), f)
                train.report({"loss": last_loss, "first_loss": first_loss,
                              "step": i + 1},
                             checkpoint=Checkpoint(d))
        if rec is not None:
            rec.end_step(i + 1, loss=float(aux["loss"]))
    return last_loss
