"""Sustained-load serve data-plane floor gate (slow-marked so tier-1
stays fast; ISSUE 10 acceptance leg).

Runs the serve_bench ``sustained`` leg — open-loop arrival through the
HTTP ingress with a >=30s steady state and a burst at ~2x min-replica
capacity — and floors:

* max-QPS: admitted throughput at steady state and under the burst,
* admitted-request p99 latency in both phases,
* shed behavior: the burst MUST shed (503 + Retry-After), MUST NOT
  time out an admitted request, and MUST NOT 500,
* the closed loop E2E: the autoscaler scales replicas up under the
  burst and back to min after the drain,
* Prometheus counters: rayt_serve_{shed,admitted}_total and the
  autoscale decision gauge are emitting cluster-wide.

ISSUE 19 adds the ``multi_proxy`` floor gates: sharded-ingress fan-out
with a mid-burst proxy kill (admitted QPS floor, zero admitted
failures, per-proxy window shares summing to the cluster window within
5%, redistribution within one liveness TTL), prefix KV-reuse (hit-rate
and hit-TTFT-vs-cold floors), and disaggregated prefill/decode (decode
occupancy must not dip vs fused; KV handoff rides the shm/device edge
with zero pickle fallbacks).

CLI twins refreshing SERVE_BENCH.json:
``python tools/serve_bench.py --leg sustained`` /
``--leg multi_proxy``.
"""

from __future__ import annotations

import os
import signal
import sys

import pytest

pytestmark = pytest.mark.slow

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

# committed SERVE_BENCH.json sustained_load leg on this class of box:
# steady 13.4 qps / p99 ~160ms, burst 44 qps admitted / p99 ~1.4s with
# shed_rate ~0.17 and peak_replicas 3. Floors sit 2-4x below committed,
# clearing loaded-suite noise while still failing a reintroduced
# unbounded-queueing or broken-autoscaler regression by an order of
# magnitude.
STEADY_QPS_FLOOR = 8.0
STEADY_P99_MS_CEIL = 1500.0
BURST_QPS_FLOOR = 20.0
BURST_P99_MS_CEIL = 4000.0
BURST_SHED_RATE_CEIL = 0.9

# latency leg (ISSUE 16): the paced app yields its first chunk
# immediately, so client TTFT is pure serve-path overhead (proxy
# admission + routing + dispatch + replica queue + first yield).
# Committed SERVE_BENCH.json measures p99 ~= tens of ms on this class
# of box; the ceiling sits an order of magnitude above to clear
# loaded-suite noise while still failing a reintroduced
# poll-loop/blocking-dispatch regression (which lands at seconds).
LATENCY_TTFT_P99_MS_CEIL = 1000.0
# server-side proxy waterfall stages must tile the proxied e2e: the
# stage means (admission+router+dispatch+stream) must sum to within
# 10% of the mean recorded e2e, or a stage is unaccounted for.
WATERFALL_TILE_TOL = 0.10


def test_sustained_load_floors_and_closed_loop():
    signal.alarm(600)  # tier-1 SIGALRM budget is sized for fast tests
    from serve_bench import run_sustained

    import ray_tpu as rt
    from ray_tpu import serve

    rt.init(num_cpus=4)
    try:
        res = run_sustained(steady_s=30.0, burst_s=10.0)
    finally:
        serve.shutdown()
        rt.shutdown()

    steady, burst, drain = res["steady"], res["burst"], res["drain"]
    # steady state: everything admitted, latency flat
    assert steady["achieved_qps"] >= STEADY_QPS_FLOOR, steady
    assert steady["timeouts"] == 0 and steady["errors"] == 0, steady
    assert steady["latency_p99_ms"] <= STEADY_P99_MS_CEIL, steady

    # burst at 2x min-capacity: excess SHEDS, admitted requests never
    # time out, nothing turns into a 500/transport error
    assert burst["shed"] > 0, burst
    assert burst["shed_rate"] <= BURST_SHED_RATE_CEIL, burst
    assert burst["timeouts"] == 0, burst
    assert burst["errors"] == 0, burst
    assert burst["achieved_qps"] >= BURST_QPS_FLOOR, burst
    assert burst["latency_p99_ms"] <= BURST_P99_MS_CEIL, burst

    # the closed loop E2E: scale-up under the burst, back to min after
    assert burst["peak_replicas"] >= 2, burst
    assert drain["final_replicas"] == 1, drain

    # Prometheus family emitted cluster-wide (GCS metrics store)
    metrics = res["metrics"]
    assert metrics.get("rayt_serve_shed_total", 0) > 0, metrics
    assert metrics.get("rayt_serve_admitted_total", 0) > 0, metrics
    assert "rayt_serve_autoscale_decision" in metrics, metrics


def test_request_latency_floors_and_waterfall_tiling():
    """ISSUE 16 floor gate: streaming TTFT p99 through the full proxy
    path stays bounded, and the server-side waterfall stages account
    for the request — stage means sum to within 10% of the recorded
    e2e mean (nothing slips between the instrumentation points)."""
    signal.alarm(600)
    from serve_bench import run_latency

    import ray_tpu as rt
    from ray_tpu import serve

    rt.init(num_cpus=4)
    try:
        res = run_latency(rate_qps=8.0, duration_s=10.0)
    finally:
        serve.shutdown()
        rt.shutdown()

    assert res["outcomes"].get("ok", 0) >= 40, res["outcomes"]
    assert res["ttft_p99_ms"] is not None
    assert res["ttft_p99_ms"] <= LATENCY_TTFT_P99_MS_CEIL, res
    assert res["tpot_p50_ms"] is not None, res

    wf = res["waterfall"]
    assert wf.get("count", 0) >= 40, wf  # records landed in the GCS
    stage_sum = sum(wf.get(k, 0.0) for k in (
        "admission_mean_ms", "router_mean_ms", "dispatch_mean_ms",
        "stream_mean_ms"))
    e2e = wf.get("e2e_mean_ms")
    assert e2e and stage_sum > 0, wf
    assert abs(stage_sum - e2e) <= WATERFALL_TILE_TOL * e2e + 0.5, (
        stage_sum, e2e, wf)
    # the replica-side nest and the client/server TTFT clocks agree to
    # within the same order of magnitude
    assert wf.get("replica_service_mean_ms") is not None, wf
    assert wf.get("ttft_mean_ms") is not None, wf


# multi_proxy leg (ISSUE 19) floors. Committed SERVE_BENCH.json on this
# class of box: fanout 236 admitted qps across 3 proxies with 0
# timeouts/500s, share error 3.1% before / 0% after the kill,
# redistribution 3.6s; prefix hit_rate 0.6, warm TTFT 0.32x cold;
# disagg occupancy 1.0 vs fused 0.989.
FANOUT_QPS_FLOOR = 150.0          # ISSUE 19 acceptance floor
WINDOW_SHARE_TOL = 0.05           # per-proxy windows vs cluster window
REDISTRIBUTE_S_CEIL = 10.0        # liveness TTL 3s + refresh + slack
PREFIX_HIT_RATE_FLOOR = 0.5
PREFIX_WARM_OVER_COLD_CEIL = 0.5  # hit TTFT p50 <= 0.5x cold
DISAGG_OCCUPANCY_SLACK = 0.02     # "not dipping" tolerance vs fused


def test_multi_proxy_fanout_floors_and_chaos():
    """Sharded ingress: N proxies split one admission window, sustain
    the QPS floor with zero admitted failures, and survive a mid-burst
    proxy kill — the dead member's share redistributes to the
    survivors within one liveness TTL."""
    signal.alarm(600)
    from serve_bench import run_multi_proxy_fanout

    import ray_tpu as rt
    from ray_tpu import serve

    rt.init(num_cpus=4)
    try:
        res = run_multi_proxy_fanout()
    finally:
        serve.shutdown()
        rt.shutdown()

    # throughput + zero admitted failures (shed 503s are backpressure,
    # not failures; conn errors are failover against the killed member)
    assert res["admitted_qps"] >= FANOUT_QPS_FLOOR, res
    assert res["admitted_timeouts"] == 0, res
    assert res["errors_5xx"] == 0, res

    # per-proxy windows shard the one cluster window
    before = res["window_shares_before"]
    assert before["live_proxies"] == 3, before
    assert len(before["windows"]) == 3, before
    assert before["share_error"] is not None
    assert before["share_error"] <= WINDOW_SHARE_TOL, before

    # chaos: survivors pick up the dead member's share
    after = res["window_shares_after_chaos"]
    assert after["live_proxies"] == 2, after
    assert after["share_error"] <= WINDOW_SHARE_TOL, after
    assert res["chaos_redistributed_s"] is not None, res
    assert res["chaos_redistributed_s"] <= REDISTRIBUTE_S_CEIL, res


def test_prefix_reuse_floors():
    """Prefix KV-reuse: repeated-prefix prompts must actually hit the
    engine's prefix store and a hit must prefill only the tail — TTFT
    at or under half of a cold prefill."""
    signal.alarm(600)
    from serve_bench import run_prefix_reuse

    res = run_prefix_reuse()
    assert res["hit_rate"] >= PREFIX_HIT_RATE_FLOOR, res
    assert res["prefix_hit_tokens"] > 0, res
    assert res["warm_over_cold_ttft"] <= PREFIX_WARM_OVER_COLD_CEIL, res


def test_disagg_occupancy_and_edge_floors():
    """Disaggregated prefill/decode: with prefill in a separate pool
    and KV handed over the shm device edge as one packed tick, the
    decode pool's occupancy must not dip vs the fused baseline, the
    handoff must not touch the DCN edge, and every tick must frame its
    k/v leaves as raw shard bytes (zero pickle fallbacks)."""
    signal.alarm(600)
    from serve_bench import run_disagg

    res = run_disagg()
    assert res["fused_occupancy_mean"] is not None, res
    assert res["disagg_occupancy_mean"] is not None, res
    assert res["disagg_occupancy_mean"] >= (
        res["fused_occupancy_mean"] - DISAGG_OCCUPANCY_SLACK), res
    assert res["kv_handoffs"] > 0, res
    assert res["kv_handoff_bytes_total"] > 0, res
    assert "dcn" not in res["edge_kinds"], res
    assert res["pickle_fallbacks"] == 0, res
