"""Replay buffer actor for off-policy RL (ref analogs:
rllib/utils/replay_buffers/replay_buffer.py — uniform ring buffer —
and multi_agent_replay_buffer usage in rllib/algorithms/dqn/).

A plain remote actor: rollout actors `add` transition batches, the
learner `sample`s uniform minibatches. Storage is preallocated numpy
rings (stable memory, O(1) add), created lazily from the first batch's
shapes so the buffer is agnostic to observation spaces.
"""

from __future__ import annotations

import numpy as np


class ReplayBuffer:
    """Uniform-sampling ring buffer over transition dicts."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = int(capacity)
        self._store: dict[str, np.ndarray] | None = None
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)
        self._added = 0

    def _init_store(self, batch: dict):
        self._store = {}
        for k, v in batch.items():
            v = np.asarray(v)
            self._store[k] = np.zeros((self.capacity,) + v.shape[1:],
                                      v.dtype)

    def add(self, batch: dict) -> int:
        """batch: dict of [N, ...] arrays (same N). Returns total added."""
        arrays = {k: np.asarray(v) for k, v in batch.items()}
        if self._store is None:
            self._init_store(arrays)
        n = len(next(iter(arrays.values())))
        i = self._idx
        for k, v in arrays.items():
            end = min(i + n, self.capacity)
            first = end - i
            self._store[k][i:end] = v[:first]
            if first < n:  # wrap
                self._store[k][:n - first] = v[first:]
        self._idx = (i + n) % self.capacity
        self._size = min(self._size + n, self.capacity)
        self._added += n
        return self._added

    def sample(self, batch_size: int) -> dict | None:
        if self._size < batch_size:
            return None
        idxs = self._rng.integers(0, self._size, batch_size)
        return {k: v[idxs] for k, v in self._store.items()}

    def size(self) -> int:
        return self._size

    def stats(self) -> dict:
        return {"size": self._size, "added": self._added,
                "capacity": self.capacity}
