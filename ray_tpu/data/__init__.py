"""ray_tpu.data — streaming datasets over tasks/actors (ref analog:
python/ray/data; SURVEY.md §2.3 Data)."""

from __future__ import annotations

from typing import Optional

from ray_tpu.data.aggregate import (AggregateFn, Count, Max,  # noqa: F401
                                    Mean, Min, Std, Sum)
from ray_tpu.data.dataset import (DataIterator, Dataset,  # noqa: F401
                                  from_items_rows)
from ray_tpu.data.datasink import (Datasink, FileDatasink,  # noqa: F401
                                   JSONLDatasink, NpzDatasink,
                                   ParquetDatasink, WriteResult)
from ray_tpu.data.datasource import (read_csv, read_json,  # noqa: F401
                                     read_npz, read_parquet, read_text,
                                     write_parquet)
from ray_tpu.data.exchange import (ExchangeController,  # noqa: F401
                                   ExchangeSpec)
from ray_tpu.data.executor import ActorPoolStrategy  # noqa: F401
from ray_tpu.data.llm_corpus import (CorpusCursor,  # noqa: F401
                                     TokenCorpus, build_corpus,
                                     read_token_corpus)
from ray_tpu.data.partitioning import Partitioning  # noqa: F401


def from_items(items: list, num_blocks: int = 8) -> Dataset:
    rows = [it if isinstance(it, dict) else {"item": it} for it in items]
    return from_items_rows(rows, num_blocks)


def range(n: int, num_blocks: int = 8) -> Dataset:  # noqa: A001
    import builtins

    return from_items_rows([{"id": i} for i in builtins.range(n)], num_blocks)


def from_numpy(array, num_blocks: int = 8) -> Dataset:
    return from_items_rows([{"data": row} for row in array], num_blocks)


def from_pandas(df, num_blocks: int = 8) -> Dataset:
    return from_items_rows(df.to_dict("records"), num_blocks)
