"""Autoscaler: slice-granular scale-up/down driven by GCS demand (ref:
python/ray/autoscaler/ — v2 reconciler architecture, fake multi-node
provider for tests)."""

from ray_tpu.autoscaler.autoscaler import Autoscaler  # noqa: F401
from ray_tpu.autoscaler.node_provider import (  # noqa: F401
    FakeTpuSliceProvider, NodeProvider, NodeTypeConfig)
