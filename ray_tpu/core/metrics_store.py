"""GCS-side metrics time-series store (ref analogs:
_private/metrics_agent.py:483 cluster aggregation for Prometheus, and
the reference dashboard's metrics head backed by Prometheus queries —
here a self-contained in-memory TSDB so the dashboard needs no external
Prometheus).

Every record published on the ``metrics`` pubsub channel lands here (see
``GcsServer.publish``). Records are aggregated into per-series ring
buffers of fixed-``resolution_s`` time bins bounded by ``retention_s``:

* **counter** records carry increment deltas; a bin holds the sum of
  deltas that landed in it, so query-time rate conversion is just
  ``sum(deltas in step) / step``.
* **gauge** records last-write-win within a bin.
* **histogram** records carry either a single raw observation (legacy
  single-record publish) or a batched bucket-delta
  (``counts``/``sum``/``count`` + ``bounds``, the batcher in
  util/metrics.py); bins hold bucket-count deltas so percentiles are
  computed by bucket interpolation at query time — and because series
  are keyed by (name, kind, tags), records for the same series from
  DIFFERENT nodes merge at ingest, giving cross-node percentiles for
  free. Series that differ only by a node-ish tag merge at query time
  with ``merge=True``.

Single-threaded by design: ingest and query both run on the GCS event
loop (the dashboard head is colocated), so no locking is needed.
"""

from __future__ import annotations

import bisect
import collections
import math
import time
from typing import Any, Optional, Sequence

# fallback bucket layout for raw histogram observations whose metric
# never declared boundaries (latencies in seconds fit this comfortably)
DEFAULT_BOUNDS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                  0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

_HIST_AGGS = ("p50", "p90", "p95", "p99", "mean", "count", "sum")


class _Series:
    __slots__ = ("name", "kind", "tags", "bounds", "bins", "total",
                 "last", "cum_counts", "cum_sum", "cum_count", "updated")

    def __init__(self, name: str, kind: str, tags: tuple, maxbins: int):
        self.name = name
        self.kind = kind
        self.tags = tags
        self.bounds: tuple | None = None
        # ring of [bin_start_ts, payload]; maxlen implements retention
        self.bins: collections.deque = collections.deque(maxlen=maxbins)
        self.total = 0.0          # counter: cumulative sum of deltas
        self.last = 0.0           # gauge: last value seen
        self.cum_counts: list[int] | None = None  # histogram cumulative
        self.cum_sum = 0.0
        self.cum_count = 0
        self.updated = 0.0


class MetricsStore:
    def __init__(self, retention_s: float = 900.0,
                 resolution_s: float = 5.0, max_series: int = 4096):
        if resolution_s <= 0 or retention_s < resolution_s:
            raise ValueError("need resolution_s > 0 and "
                             "retention_s >= resolution_s")
        self.retention_s = float(retention_s)
        self.resolution_s = float(resolution_s)
        self.max_series = int(max_series)
        self._maxbins = int(math.ceil(retention_s / resolution_s)) + 1
        # LRU by last update so a tag-cardinality explosion evicts the
        # stalest series instead of growing without bound
        self._series: collections.OrderedDict[tuple, _Series] = \
            collections.OrderedDict()
        self.dropped_records = 0

    # -------------------------------------------------------------- ingest
    def ingest_many(self, records: Sequence[dict], now: float | None = None):
        for rec in records:
            self.ingest(rec, now=now)

    def ingest(self, rec: dict, now: float | None = None):
        """Accept one published metric record; malformed records are
        counted and dropped (observability must never take down the GCS
        event loop)."""
        try:
            self._ingest(rec, now)
        except Exception:
            self.dropped_records += 1

    def _ingest(self, rec: dict, now: float | None):
        name, kind = rec["name"], rec["kind"]
        ts = float(rec.get("ts") or now or time.time())
        tags = tuple(sorted((rec.get("tags") or {}).items()))
        # validate BEFORE creating the series so a malformed record
        # can't leave a phantom entry in the name directory
        if kind in ("counter", "gauge"):
            value = float(rec["value"])
        elif kind != "histogram":
            raise ValueError(f"unknown metric kind {kind!r}")
        key = (name, kind, tags)
        s = self._series.get(key)
        if s is None:
            s = self._series[key] = _Series(name, kind, tags, self._maxbins)
            while len(self._series) > self.max_series:
                self._series.popitem(last=False)
        else:
            self._series.move_to_end(key)
        s.updated = ts
        if kind == "counter":
            s.total += value
            self._bin_add(s, ts, value)
        elif kind == "gauge":
            s.last = value
            self._bin_set(s, ts, value)
        else:
            self._ingest_histogram(s, rec, ts)

    def _ingest_histogram(self, s: _Series, rec: dict, ts: float):
        bounds = tuple(float(b) for b in (rec.get("bounds")
                                          or s.bounds or DEFAULT_BOUNDS))
        if s.bounds is None or (s.bounds != bounds and s.cum_count == 0):
            # first record (or a redefinition before any data) fixes the
            # bucket layout for the series' lifetime
            s.bounds = bounds
            s.cum_counts = [0] * (len(bounds) + 1)
        elif s.bounds != bounds:
            raise ValueError("histogram bounds changed mid-series")
        if "counts" in rec:  # batched bucket-delta record
            counts = list(rec["counts"])
            if len(counts) != len(s.bounds) + 1:
                raise ValueError("bucket count length mismatch")
            dsum = float(rec.get("sum", 0.0))
            dcount = int(rec.get("count", sum(counts)))
        else:  # legacy raw observation
            counts = [0] * (len(s.bounds) + 1)
            v = float(rec["value"])
            counts[bisect.bisect_left(s.bounds, v)] = 1
            dsum, dcount = v, 1
        for i, c in enumerate(counts):
            s.cum_counts[i] += c
        s.cum_sum += dsum
        s.cum_count += dcount
        payload = self._bin_payload(s, ts)
        for i, c in enumerate(counts):
            payload["counts"][i] += c
        payload["sum"] += dsum
        payload["count"] += dcount

    # bins -----------------------------------------------------------------
    def _bin_start(self, ts: float) -> float:
        return math.floor(ts / self.resolution_s) * self.resolution_s

    def _locate_bin(self, s: _Series, ts: float):
        """Find-or-create the bin for ts. Bins append in time order; a
        slightly-late record (cross-node clock skew) merges into a recent
        bin by a short right-to-left scan, and anything older than the
        ring folds into the oldest bin rather than corrupting order."""
        b = self._bin_start(ts)
        if not s.bins or b > s.bins[-1][0]:
            s.bins.append([b, self._zero_payload(s)])
            return s.bins[-1]
        for i in range(len(s.bins) - 1, max(-1, len(s.bins) - 9), -1):
            if s.bins[i][0] == b:
                return s.bins[i]
            if s.bins[i][0] < b:
                return s.bins[i + 1] if i + 1 < len(s.bins) else s.bins[-1]
        return s.bins[0]

    def _zero_payload(self, s: _Series):
        if s.kind == "counter":
            return [0.0]
        if s.kind == "gauge":
            return [0.0, False]  # value, seen
        return {"counts": [0] * (len(s.bounds or DEFAULT_BOUNDS) + 1),
                "sum": 0.0, "count": 0}

    def _bin_add(self, s: _Series, ts: float, v: float):
        self._locate_bin(s, ts)[1][0] += v

    def _bin_set(self, s: _Series, ts: float, v: float):
        payload = self._locate_bin(s, ts)[1]
        payload[0] = v
        payload[1] = True

    def _bin_payload(self, s: _Series, ts: float) -> dict:
        return self._locate_bin(s, ts)[1]

    # -------------------------------------------------------------- queries
    def names(self) -> list[dict]:
        """Metric name directory: kind, tag-key union, series count."""
        by_name: dict[tuple, dict] = {}
        for (name, kind, tags), s in self._series.items():
            entry = by_name.setdefault((name, kind), {
                "name": name, "kind": kind, "tag_keys": set(),
                "num_series": 0})
            entry["num_series"] += 1
            entry["tag_keys"].update(k for k, _ in tags)
        out = [{**e, "tag_keys": sorted(e["tag_keys"])}
               for e in by_name.values()]
        out.sort(key=lambda e: e["name"])
        return out

    def query(self, name: str, window_s: float = 300.0,
              step_s: float | None = None, agg: str | None = None,
              tags: Optional[dict] = None, merge: bool = False,
              now: float | None = None) -> dict:
        """Aligned time series for one metric name.

        Returns ``{"name", "kind", "agg", "step_s", "start", "end",
        "series": [{"tags": {...}, "points": [[t, v|None], ...]}]}``
        with one point per ``step_s`` covering ``window_s`` back from
        ``now``. Steps snap to multiples of the store resolution.

        * counters: ``agg`` "rate" (default, per-second) or "increase"
        * gauges: last value in the step (None where no data)
        * histograms: ``agg`` p50/p90/p95/p99 (bucket-interpolated),
          "mean", "count" (observations/s), or "sum"
        * ``tags``: subset filter ({"k": "v"} keeps matching series)
        * ``merge``: collapse all matching series into one (counters sum
          rates, gauges sum values, histogram buckets merge — the
          cross-node percentile path)
        """
        now = float(now if now is not None else time.time())
        window_s = max(float(window_s), self.resolution_s)
        window_s = min(window_s, self.retention_s)
        if step_s is None:
            step_s = max(self.resolution_s, window_s / 60.0)
        step_s = max(self.resolution_s,
                     math.ceil(float(step_s) / self.resolution_s)
                     * self.resolution_s)
        end = math.floor(now / step_s) * step_s + step_s
        nsteps = max(1, int(math.ceil(window_s / step_s)))
        start = end - nsteps * step_s

        matched = [s for (n, _k, _t), s in self._series.items()
                   if n == name and self._tags_match(s, tags)]
        kind = matched[0].kind if matched else None
        agg = self._check_agg(kind, agg)
        if merge and len(matched) > 1:
            groups = [matched]
        else:
            groups = [[s] for s in matched]
        series_out = []
        for group in groups:
            series_out.append({
                "tags": self._common_tags(group),
                "points": self._render_points(group, start, step_s,
                                              nsteps, agg),
            })
        return {"name": name, "kind": kind, "agg": agg,
                "step_s": step_s, "start": start, "end": end,
                "series": series_out}

    @staticmethod
    def _tags_match(s: _Series, flt: Optional[dict]) -> bool:
        if not flt:
            return True
        have = dict(s.tags)
        return all(have.get(k) == v for k, v in flt.items())

    @staticmethod
    def _common_tags(group: list[_Series]) -> dict:
        common = set(group[0].tags)
        for s in group[1:]:
            common &= set(s.tags)
        return dict(sorted(common))

    @staticmethod
    def _check_agg(kind: str | None, agg: str | None) -> str | None:
        if kind == "counter":
            agg = agg or "rate"
            if agg not in ("rate", "increase"):
                raise ValueError(f"bad counter agg {agg!r}")
        elif kind == "gauge":
            agg = agg or "last"
            if agg != "last":
                raise ValueError(f"bad gauge agg {agg!r}")
        elif kind == "histogram":
            agg = agg or "p50"
            if agg not in _HIST_AGGS:
                raise ValueError(f"bad histogram agg {agg!r}")
        return agg

    def _render_points(self, group: list[_Series], start: float,
                       step_s: float, nsteps: int, agg: str | None):
        kind = group[0].kind
        if kind == "histogram":
            return self._render_histogram(group, start, step_s, nsteps,
                                          agg)
        # two-level accumulation: within one series a step holds the
        # delta-sum (counter) or the LAST bin's value (gauge —
        # downsampling must not sum repeated sets); across merged series
        # steps sum (cluster totals across nodes)
        acc: list[float | None] = [None] * nsteps
        for s in group:
            per: list[float | None] = [None] * nsteps
            for b, payload in s.bins:  # bins are in time order
                idx = int((b - start) // step_s)
                if idx < 0 or idx >= nsteps:
                    continue
                if kind == "counter":
                    per[idx] = (per[idx] or 0.0) + payload[0]
                elif payload[1]:
                    per[idx] = payload[0]
            for i, v in enumerate(per):
                if v is not None:
                    acc[i] = (acc[i] or 0.0) + v
        points = []
        for i in range(nsteps):
            t = start + i * step_s
            v = acc[i]
            if v is not None and kind == "counter" and agg == "rate":
                v = v / step_s
            points.append([t, v])
        return points

    def _render_histogram(self, group: list[_Series], start: float,
                          step_s: float, nsteps: int, agg: str):
        bounds = group[0].bounds or DEFAULT_BOUNDS
        nb = len(bounds) + 1
        counts = [[0] * nb for _ in range(nsteps)]
        sums = [0.0] * nsteps
        totals = [0] * nsteps
        seen = [False] * nsteps
        for s in group:
            if (s.bounds or DEFAULT_BOUNDS) != bounds:
                continue  # merge needs one bucket layout; skip strangers
            for b, payload in s.bins:
                idx = int((b - start) // step_s)
                if idx < 0 or idx >= nsteps:
                    continue
                seen[idx] = True
                for i, c in enumerate(payload["counts"]):
                    counts[idx][i] += c
                sums[idx] += payload["sum"]
                totals[idx] += payload["count"]
        points = []
        for i in range(nsteps):
            t = start + i * step_s
            if not seen[i] or totals[i] == 0:
                points.append([t, None])
                continue
            if agg == "count":
                v: float = totals[i] / step_s
            elif agg == "sum":
                v = sums[i]
            elif agg == "mean":
                v = sums[i] / totals[i]
            else:
                q = {"p50": 0.5, "p90": 0.9, "p95": 0.95,
                     "p99": 0.99}[agg]
                v = _bucket_percentile(bounds, counts[i], totals[i], q)
            points.append([t, v])
        return points

    # ------------------------------------------------------------ snapshot
    def snapshot(self) -> list[dict]:
        """Cumulative view for the Prometheus scrape: counters/gauges as
        ``value``; histograms as count/sum plus cumulative ``buckets``
        ([upper_bound, cumulative_count], +Inf last) ready for
        ``_bucket`` rendering."""
        out = []
        for (name, kind, tags), s in self._series.items():
            entry: dict[str, Any] = {"name": name, "kind": kind,
                                     "tags": dict(tags)}
            if kind == "counter":
                entry["value"] = s.total
            elif kind == "gauge":
                entry["value"] = s.last
            else:
                entry["count"] = s.cum_count
                entry["sum"] = s.cum_sum
                cum = 0
                buckets = []
                for bound, c in zip(s.bounds or (), s.cum_counts or ()):
                    cum += c
                    buckets.append([bound, cum])
                buckets.append(["+Inf", s.cum_count])
                entry["buckets"] = buckets
            out.append(entry)
        return out

    def prune(self, now: float | None = None) -> int:
        """Drop series idle past twice the retention window (keeps the
        name directory honest for long-lived clusters)."""
        now = float(now if now is not None else time.time())
        horizon = now - 2.0 * self.retention_s
        stale = [k for k, s in self._series.items() if s.updated < horizon]
        for k in stale:
            del self._series[k]
        return len(stale)


def _bucket_percentile(bounds: Sequence[float], counts: Sequence[int],
                       total: int, q: float) -> float:
    """Percentile estimate by linear interpolation inside the target
    bucket (Prometheus histogram_quantile semantics). The overflow
    bucket clamps to its lower bound — an honest floor, since the true
    upper edge is unknown."""
    target = q * total
    cum = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if cum + c >= target:
            lo = bounds[i - 1] if i > 0 else 0.0
            if i >= len(bounds):  # overflow bucket
                return float(bounds[-1])
            hi = bounds[i]
            frac = (target - cum) / c
            return float(lo + (hi - lo) * frac)
        cum += c
    return float(bounds[-1])
