"""Replay buffer actor for off-policy RL (ref analogs:
rllib/utils/replay_buffers/replay_buffer.py — uniform ring buffer —
and multi_agent_replay_buffer usage in rllib/algorithms/dqn/).

A plain remote actor: rollout actors `add` transition batches, the
learner `sample`s uniform minibatches. Storage is preallocated numpy
rings (stable memory, O(1) add), created lazily from the first batch's
shapes so the buffer is agnostic to observation spaces.
"""

from __future__ import annotations

import numpy as np


class ReplayRolloutMixin:
    """Shared rollout loop for off-policy runner actors (DQN/SAC). The
    host class provides `self.env`, `self._obs`, `self._ep_return`,
    `self._completed`; action selection is the only per-algorithm part.

    Truncation semantics (rllib's): truncation is NOT a terminal for
    bootstrapping — `dones` records true terminations only, and the
    stored next_obs of a truncated env is its pre-reset final_obs so the
    critic can bootstrap from the real final state."""

    def _rollout(self, num_steps: int, select_action) -> dict:
        env = self.env
        obs_l, act_l, rew_l, nxt_l, done_l = [], [], [], [], []
        for _ in range(num_steps):
            action = select_action(self._obs)
            obs_l.append(self._obs.copy())
            (next_obs, reward, terminated, truncated,
             final_obs) = env.step(action)
            truncated = truncated & ~terminated
            stored_next = next_obs.copy()
            if truncated.any():
                idxs = np.nonzero(truncated)[0]
                stored_next[idxs] = final_obs[idxs]
            act_l.append(action)
            rew_l.append(reward.astype(np.float32))
            nxt_l.append(stored_next)
            done_l.append(terminated.copy())
            self._ep_return += reward
            for i in np.nonzero(terminated | truncated)[0]:
                self._completed.append(float(self._ep_return[i]))
                self._ep_return[i] = 0.0
            self._obs = next_obs
        completed, self._completed = self._completed, []
        return {
            "transitions": {
                "obs": np.concatenate(obs_l),
                "actions": np.concatenate(act_l),
                "rewards": np.concatenate(rew_l),
                "next_obs": np.concatenate(nxt_l),
                "dones": np.concatenate(done_l),
            },
            "episode_returns": completed,
            "steps": num_steps * env.num_envs,
        }


class ReplayBuffer:
    """Uniform-sampling ring buffer over transition dicts."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = int(capacity)
        self._store: dict[str, np.ndarray] | None = None
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)
        self._added = 0

    def _init_store(self, batch: dict):
        self._store = {}
        for k, v in batch.items():
            v = np.asarray(v)
            self._store[k] = np.zeros((self.capacity,) + v.shape[1:],
                                      v.dtype)

    def add(self, batch: dict) -> int:
        """batch: dict of [N, ...] arrays (same N). Returns total added."""
        arrays = {k: np.asarray(v) for k, v in batch.items()}
        if self._store is None:
            self._init_store(arrays)
        n = len(next(iter(arrays.values())))
        i = self._idx
        for k, v in arrays.items():
            end = min(i + n, self.capacity)
            first = end - i
            self._store[k][i:end] = v[:first]
            if first < n:  # wrap
                self._store[k][:n - first] = v[first:]
        self._idx = (i + n) % self.capacity
        self._size = min(self._size + n, self.capacity)
        self._added += n
        return self._added

    def sample(self, batch_size: int) -> dict | None:
        if self._size < batch_size:
            return None
        idxs = self._rng.integers(0, self._size, batch_size)
        return {k: v[idxs] for k, v in self._store.items()}

    def size(self) -> int:
        return self._size

    def stats(self) -> dict:
        return {"size": self._size, "added": self._added,
                "capacity": self.capacity}
