"""On-chip MFU sweep: try bench configs in ONE process, print a table.

Usage: python tools/mfu_sweep.py  (expects a live TPU backend)
"""
from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import optax

from ray_tpu.models import llama
from ray_tpu.parallel.mesh import build_mesh
from ray_tpu.parallel.spmd import build_train_step, shard_batch

PEAK = 197e12  # v5e bf16


def measure(preset: str, batch: int, seq: int, remat: bool,
            mu_dtype=None, steps: int = 15, attn="flash") -> dict:
    cfg = llama.config_for(preset, max_seq_len=seq, remat=remat,
                           attn_impl=attn)
    mesh = build_mesh({"data": 1}, jax.devices()[:1])
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    opt = optax.adamw(3e-4, mu_dtype=mu_dtype)
    step, state = build_train_step(
        lambda p, b: llama.loss_fn(p, b, cfg), opt, params,
        llama.param_logical_axes(cfg), mesh)
    del params
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                                cfg.vocab_size)
    data = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
    data = shard_batch(data, mesh)
    state, aux = step(state, data)
    float(aux["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, aux = step(state, data)
    float(aux["loss"])
    dt = time.perf_counter() - t0
    tok_s = batch * seq * steps / dt
    mfu = tok_s * cfg.flops_per_token() / PEAK
    del state
    return {"tok_s": round(tok_s, 1), "mfu": round(mfu, 4)}


def main():
    configs = [
        dict(preset="410m", batch=8, seq=2048, remat=True),
        dict(preset="410m", batch=8, seq=2048, remat=False),
        dict(preset="410m", batch=16, seq=2048, remat=True),
        dict(preset="410m", batch=16, seq=2048, remat=False),
        dict(preset="410m", batch=32, seq=2048, remat=True),
        dict(preset="1b", batch=8, seq=2048, remat=True,
             mu_dtype=jnp.bfloat16),
        dict(preset="1b", batch=16, seq=2048, remat=True,
             mu_dtype=jnp.bfloat16),
    ]
    for c in configs:
        label = {k: (str(v) if k == "mu_dtype" else v)
                 for k, v in c.items()}
        try:
            r = measure(**c)
        except Exception as e:
            print(json.dumps({"cfg": label,
                              "error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)
            continue
        print(json.dumps({"cfg": label, **r}), flush=True)


if __name__ == "__main__":
    main()
