"""TPU autodetect + slice resource modeling (ref analog:
python/ray/_private/accelerators/tpu.py:70,197 and its test suite)."""

import ray_tpu as rt
from ray_tpu._internal.accelerators import (TpuSliceInfo, detect_tpu_slice,
                                            tpu_slice_bundles)


def test_detect_from_gke_env():
    env = {"TPU_ACCELERATOR_TYPE": "v4-16", "TPU_WORKER_ID": "1",
           "TPU_WORKER_HOSTNAMES": "h0,h1,h2,h3",
           "TPU_VISIBLE_CHIPS": "0,1,2,3", "TPU_NAME": "my-slice"}
    info = detect_tpu_slice(env, use_metadata=False)
    # "v4-16" counts TensorCores (2/chip): 8 chips — but the advertised
    # type string stays what the platform exports (users target it)
    assert info.accel_type == "v4-16"
    assert info.gen == "v4"
    assert info.total_chips == 8
    assert info.chips_on_host == 4
    assert info.worker_id == 1
    assert info.num_workers == 4  # TPU_WORKER_HOSTNAMES wins over chips/host
    res = info.resources()
    assert res == {"TPU": 4.0, "TPU-v4-16": 4.0}  # not worker 0: no head
    assert info.labels()["tpu-slice"] == "my-slice"


def test_detect_v5litepod_head_resource():
    env = {"TPU_ACCELERATOR_TYPE": "v5litepod-8", "TPU_WORKER_ID": "0",
           "TPU_VISIBLE_CHIPS": "0,1,2,3,4,5,6,7"}
    info = detect_tpu_slice(env, use_metadata=False)
    # raw platform type string preserved; gen normalized for labels
    assert info.accel_type == "v5litepod-8"
    assert info.gen == "v5e"
    assert info.num_workers == 1
    res = info.resources()
    assert res["TPU-v5litepod-8-head"] == 1.0
    assert res["TPU"] == 8.0


def test_detect_none_without_tpu():
    assert detect_tpu_slice({}, use_metadata=False) is None


def test_slice_bundles_shape():
    info = TpuSliceInfo(accel_type="v5p-16", gen="v5p", total_chips=16,
                        chips_on_host=4, num_workers=4)
    assert tpu_slice_bundles(info) == [{"TPU": 4.0}] * 4


def test_slice_gang_placement_group():
    """STRICT_SPREAD slice PG over per-host TPU bundles + a coordinator
    pinned to the slice-head resource (the TPU-<type>-head trick)."""
    from ray_tpu.cluster_utils import Cluster

    info = TpuSliceInfo(accel_type="v5e-16", gen="v5e", total_chips=16,
                        chips_on_host=8, worker_id=0, num_workers=2)
    # model a 2-host slice: two in-process nodes advertise the slice
    # resources exactly as detect_tpu_slice would on each host
    cluster = Cluster(head_resources={"CPU": 2.0})
    cluster.add_node(num_cpus=2, resources={"TPU": 8.0, "TPU-v5e-16": 8.0,
                                            "TPU-v5e-16-head": 1.0})
    cluster.add_node(num_cpus=2, resources={"TPU": 8.0, "TPU-v5e-16": 8.0})
    cluster.connect()
    try:
        _slice_pg_body(info)
    finally:
        cluster.shutdown()


def _slice_pg_body(info):
    pg = rt.placement_group(tpu_slice_bundles(info),
                            strategy="STRICT_SPREAD")

    @rt.remote(num_cpus=0, resources={"TPU": 1})
    def on_slice_host():
        import os
        return os.getpid()

    pids = rt.get([
        on_slice_host.options(
            scheduling_strategy=pg.bundle_strategy(i)).remote()
        for i in range(2)], timeout=60)
    assert len(set(pids)) == 2  # one per host

    @rt.remote(num_cpus=0, resources={"TPU-v5e-16-head": 1})
    def coordinator():
        return "coord"

    assert rt.get(coordinator.remote(), timeout=60) == "coord"
    rt.remove_placement_group(pg)
