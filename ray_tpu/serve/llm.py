"""TP-sharded LLM serving: batched prefill/decode engine + Serve app.

BASELINE config #5 (Llama TP Serve replicas): a replica pins a
pjit-sharded Llama across the host's local mesh (tensor axis over chips,
ICI collectives inserted by GSPMD), batches concurrent requests into one
left-padded decode batch, and streams tokens through the existing
streaming-return path (SSE at the proxy).

Ref analogs: python/ray/serve/_private/replica.py:750 (user-callable
host), router.py:321 (request path); the engine itself has no reference
equivalent (Ray serves LLMs via vLLM) — this is the TPU-native design:
static shapes (prompt-length buckets x fixed batch slots), jitted
prefill/decode with donated KV cache, greedy/temperature sampling in-jit.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import llama
from ray_tpu.parallel.mesh import build_mesh, shard_params, spec_for


def _bucket(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


@dataclass
class _Request:
    tokens: list[int]
    max_new_tokens: int
    temperature: float
    out: asyncio.Queue = field(default_factory=asyncio.Queue)
    loop: Optional[asyncio.AbstractEventLoop] = None


class LLMEngine:
    """Batched TP generation engine over the local device mesh.

    One engine per replica process. Requests queue; a background loop
    groups up to `max_batch` of them (within `batch_window_s`), left-pads
    prompts to a length bucket, prefills the batch in one jit call, then
    decodes step-by-step, streaming each request's tokens as they land.
    """

    def __init__(self, preset: str = "debug", *, tp: int | None = None,
                 max_batch: int = 4, max_seq_len: int | None = None,
                 batch_window_s: float = 0.02,
                 prompt_buckets: tuple[int, ...] = (32, 128, 512, 1024),
                 eos_token_id: int | None = None,
                 params: Any = None, seed: int = 0):
        devices = jax.devices()
        tp = tp or len(devices)
        self.mesh = build_mesh({"data": 1, "tensor": tp}, devices[:tp])
        cfg = llama.config_for(preset)
        if max_seq_len is not None:
            cfg = llama.config_for(preset, max_seq_len=max_seq_len)
        self.cfg = cfg
        self.max_batch = max_batch
        self.batch_window_s = batch_window_s
        self.prompt_buckets = tuple(
            b for b in prompt_buckets if b < cfg.max_seq_len) or (
                cfg.max_seq_len // 2,)
        self.eos_token_id = eos_token_id
        logical = llama.param_logical_axes(cfg)
        if params is None:
            params = llama.init_params(cfg, jax.random.PRNGKey(seed))
        shardings = shard_params(params, logical, self.mesh)
        self.params = jax.device_put(params, shardings)
        self._cache_sharding = jax.tree.map(
            lambda ax: jax.sharding.NamedSharding(
                self.mesh, spec_for(ax, mesh=self.mesh)),
            llama.kv_cache_logical_axes(),
            is_leaf=lambda x: isinstance(x, tuple))

        def step(params, cache, tokens, key, temperature):
            logits, cache = llama.decode_step(params, cache, tokens, cfg)
            key, sub = jax.random.split(key)
            greedy = jnp.argmax(logits, axis=-1)
            sampled = jax.random.categorical(
                sub, logits / jnp.maximum(temperature, 1e-4))
            nxt = jnp.where(temperature[:, 0] > 0, sampled, greedy)
            return nxt.astype(jnp.int32), cache, key

        # one jit; prefill (s=bucket) and decode (s=1) are separate traces
        # of the same function, cached per shape
        self._step = jax.jit(step, donate_argnums=(1,))
        self._queue: asyncio.Queue[_Request] = None  # type: ignore
        self._task = None
        self._loop = None
        # perf counters (for the serve bench)
        self.generated_tokens = 0
        self.batches = 0

    # ------------------------------------------------------------ serving
    async def ensure_started(self):
        loop = asyncio.get_running_loop()
        if self._loop is not loop or self._task is None or self._task.done():
            # (re)bind to the current event loop — a queue/task from a
            # previous loop (replica restart, repeated asyncio.run) is dead
            self._queue = asyncio.Queue()
            self._task = asyncio.ensure_future(self._batch_loop())
            self._loop = loop

    async def generate(self, tokens: list[int], *,
                       max_new_tokens: int = 32,
                       temperature: float = 0.0):
        """Async generator of generated token ids. Raises ValueError for
        prompts longer than the largest prefill bucket — silent front-
        truncation would return plausible-but-wrong output."""
        limit = max(self.prompt_buckets)
        if len(tokens) > limit:
            raise ValueError(
                f"prompt is {len(tokens)} tokens; this engine's largest "
                f"prefill bucket is {limit} (raise prompt_buckets / "
                f"max_seq_len)")
        await self.ensure_started()
        req = _Request(list(tokens), int(max_new_tokens), float(temperature),
                       loop=asyncio.get_running_loop())
        await self._queue.put(req)
        while True:
            item = await req.out.get()
            if item is None:
                return
            if isinstance(item, Exception):
                raise item
            yield item

    async def _batch_loop(self):
        while True:
            first = await self._queue.get()
            batch = [first]
            deadline = time.monotonic() + self.batch_window_s
            while len(batch) < self.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(await asyncio.wait_for(
                        self._queue.get(), remaining))
                except asyncio.TimeoutError:
                    break
            loop = asyncio.get_running_loop()
            try:
                await loop.run_in_executor(None, self._run_batch, batch)
            except Exception as e:  # engine-level failure: fail the batch
                for r in batch:
                    r.loop.call_soon_threadsafe(r.out.put_nowait, e)

    # ------------------------------------------------------- the hot loop
    def _run_batch(self, batch: list[_Request]):
        cfg = self.cfg
        bsz = self.max_batch  # fixed slots: one decode-jit trace ever
        longest = max(len(r.tokens) for r in batch)
        bucket = _bucket(longest, self.prompt_buckets)
        prompts = np.zeros((bsz, bucket), np.int32)
        start = np.full((bsz,), bucket, np.int32)  # empty slots: all-pad
        temps = np.zeros((bsz, 1), np.float32)
        for i, r in enumerate(batch):
            toks = r.tokens[-bucket:]
            prompts[i, bucket - len(toks):] = toks
            start[i] = bucket - len(toks)
            temps[i, 0] = r.temperature
        max_new = max(r.max_new_tokens for r in batch)
        budget = min(max_new, cfg.max_seq_len - bucket)

        cache = llama.init_kv_cache(cfg, bsz, max_len=cfg.max_seq_len)
        cache["start"] = jnp.asarray(start)
        cache = jax.device_put(cache, self._cache_sharding)
        key = jax.random.PRNGKey(int(time.time_ns()) % (1 << 31))
        temps_j = jnp.asarray(temps)

        nxt, cache, key = self._step(
            self.params, cache, jnp.asarray(prompts), key, temps_j)
        done = [False] * bsz
        emitted = [0] * bsz
        for i in range(len(batch), bsz):
            done[i] = True
        for step_i in range(budget):
            toks = np.asarray(nxt)  # host sync: the step's sampled tokens
            for i, r in enumerate(batch):
                if done[i]:
                    continue
                t = int(toks[i])
                if self.eos_token_id is not None and t == self.eos_token_id:
                    done[i] = True
                    r.loop.call_soon_threadsafe(r.out.put_nowait, None)
                    continue
                emitted[i] += 1
                self.generated_tokens += 1
                r.loop.call_soon_threadsafe(r.out.put_nowait, t)
                if emitted[i] >= r.max_new_tokens:
                    done[i] = True
                    r.loop.call_soon_threadsafe(r.out.put_nowait, None)
            if all(done):
                break
            nxt, cache, key = self._step(
                self.params, cache, nxt[:, None], key, temps_j)
        for i, r in enumerate(batch):
            if not done[i]:
                r.loop.call_soon_threadsafe(r.out.put_nowait, None)
        self.batches += 1

    def stats(self) -> dict:
        return {"generated_tokens": self.generated_tokens,
                "batches": self.batches,
                "tp": self.mesh.shape.get("tensor", 1)}


class LlamaService:
    """Serve callable hosting one LLMEngine (deploy via serve.deployment).

    Request payload: {"tokens": [...], "max_new_tokens": int,
    "temperature": float} -> streams {"token": id} dicts.
    """

    def __init__(self, preset: str = "debug", **engine_kw):
        self.engine = LLMEngine(preset, **engine_kw)

    async def __call__(self, payload: dict):
        tokens = payload["tokens"]
        if isinstance(tokens, str):  # raw byte-level "tokenizer"
            tokens = [b % self.engine.cfg.vocab_size
                      for b in tokens.encode()]
        async for tok in self.engine.generate(
                tokens,
                max_new_tokens=int(payload.get("max_new_tokens", 32)),
                temperature=float(payload.get("temperature", 0.0))):
            yield {"token": int(tok)}

    def stats(self) -> dict:
        return self.engine.stats()


def llm_app(preset: str = "debug", *, num_replicas: int = 1,
            max_ongoing_requests: int = 64, **engine_kw):
    """Build a Serve application for a TP-sharded Llama."""
    from ray_tpu.serve.deployment import deployment

    dep = deployment(
        LlamaService,
        num_replicas=num_replicas,
        max_ongoing_requests=max_ongoing_requests,
    )
    return dep.bind(preset, **engine_kw)
