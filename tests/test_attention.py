"""Numeric parity of the Pallas flash attention kernels (fwd + bwd)
against the XLA reference path. Off-TPU these run the kernels in pallas
interpret mode, so CI covers the exact kernel code (small shapes — the
interpreter is slow)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import xla_attention
from ray_tpu.ops.pallas.flash_attention import flash_attention


def _make_qkv(b, s, h, hk, d, seed=0, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(k1, (b, s, h, d), dtype)
    k = jax.random.normal(k2, (b, s, hk, d), dtype)
    v = jax.random.normal(k3, (b, s, hk, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_flash_fwd_parity(causal):
    q, k, v = _make_qkv(1, 256, 2, 2, 64)
    out_flash = flash_attention(q, k, v, causal, None, 128, 128)
    out_ref = xla_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out_flash, out_ref, atol=2e-5, rtol=2e-5)


def test_flash_fwd_parity_gqa():
    q, k, v = _make_qkv(1, 256, 4, 2, 64, seed=1)
    out_flash = flash_attention(q, k, v, True, None, 128, 128)
    out_ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out_flash, out_ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_bwd_parity(causal):
    q, k, v = _make_qkv(1, 256, 2, 2, 64, seed=2)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, causal, None, 128, 128)
        return (out * jnp.cos(out)).sum()

    def loss_ref(q, k, v):
        out = xla_attention(q, k, v, causal=causal)
        return (out * jnp.cos(out)).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")


def test_flash_bwd_parity_gqa():
    q, k, v = _make_qkv(1, 256, 4, 2, 64, seed=3)

    def loss(attn):
        def f(q, k, v):
            out = attn(q, k, v)
            return (out ** 2).sum()
        return f

    flash = loss(lambda q, k, v: flash_attention(q, k, v, True, None,
                                                 128, 128))
    ref = loss(lambda q, k, v: xla_attention(q, k, v, causal=True))
    gf = jax.grad(flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.parametrize("bq,bk", [(64, 128), (128, 64), (32, 256)])
def test_flash_parity_rectangular_blocks(bq, bk):
    """Non-square tiles (the mfu_sweep retune axis: wider K blocks feed
    the MXU a longer contraction per softmax rescale) must stay exact in
    fwd and bwd."""
    q, k, v = _make_qkv(1, 256, 2, 2, 64, seed=5)

    out = flash_attention(q, k, v, True, None, bq, bk)
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def loss(fn):
        def f(q, k, v):
            return (fn(q, k, v) * jnp.arange(
                q.shape[1], dtype=q.dtype)[None, :, None, None]).sum()
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    gf = loss(lambda q, k, v: flash_attention(q, k, v, True, None, bq, bk))
    gr = loss(lambda q, k, v: xla_attention(q, k, v, causal=True))
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)
